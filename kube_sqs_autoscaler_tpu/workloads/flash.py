"""Pallas flash attention: the workload's hot op as a TPU kernel.

Causal multi-head attention with the flash-attention schedule — online
softmax over key/value blocks, never materializing the ``[S, S]`` score
matrix — written in Pallas for TPU (no reference counterpart: the reference
contains no numerical code at all, SURVEY.md §2).

Why a kernel when XLA already fuses well: for ``S`` up to a few thousand the
dense path (``model._dense_attention``) is fine, but its ``[B, H, S, S]``
fp32 score tensor is HBM-resident; at ``S = 8k`` with 8 heads that is 2 GiB
per example. The flash schedule keeps only per-block tiles on chip, turning
attention from HBM-bandwidth-bound to MXU-bound.

TPU mapping:

- grid ``(batch, heads, S/block_q, S/block_k)``; TPU grid iteration is
  sequential with the last axis innermost, so the fp32 running
  max / sum / output accumulators live in VMEM *scratch* that persists
  across the ``k`` axis (initialized at ``k==0``, written out at the last
  ``k`` block) — VMEM residency is O(block), independent of ``S``;
- q/k/v arrive as ``[block, head_dim]`` VMEM tiles via BlockSpec index
  maps; score tiles hit the MXU via
  ``jnp.dot(..., preferred_element_type=f32)``;
- causality makes blocks strictly above the diagonal no-ops (``pl.when``
  skips their compute entirely — about half the FLOPs of full attention)
  and masks the partial diagonal blocks with ``-inf``;
- block sizes auto-select the largest power-of-two tile up to 512 dividing
  ``S`` (128 = lane-width minimum): measured on TPU v5e at ``S = 4k``,
  512-wide tiles run ~2x faster than 128-wide and ~3x faster than the
  dense XLA path, while bf16-into-the-MXU (fp32 accumulate only) is what
  keeps the score matmul on the fast path.

Plugs into the model through the ``attention_fn`` seam
(``model.forward(..., attention_fn=flash_attention)``); composes with ring
attention by serving as the per-shard local kernel.

Off TPU the kernel runs in Pallas interpret mode (exact same code path), so
the CPU test suite validates the real kernel — but interpret mode is
Python-speed, which is why :func:`attention_fn_for` only dispatches to the
kernel when actually running on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 128  # minimum tile: the MXU/VPU lane width
PREFERRED_BLOCK = 512  # best-measured tile on TPU v5e (see module docstring)


def _pick_block(seq_len: int, requested: int | None) -> int:
    """Auto block size: the largest power-of-two <= PREFERRED_BLOCK that
    divides ``seq_len``, floored at DEFAULT_BLOCK (an explicit ``requested``
    wins, clamped to ``seq_len``; ``seq_len`` itself for short sequences).

    Non-dividing sequence lengths fall through to DEFAULT_BLOCK so the
    caller's divisibility check raises its clear ValueError instead of a
    mis-tiled kernel failing deep in Mosaic lowering.
    """
    if requested is not None:
        return min(requested, seq_len)
    if seq_len <= DEFAULT_BLOCK:
        return seq_len
    block = 1 << (min(PREFERRED_BLOCK, seq_len).bit_length() - 1)
    while block > DEFAULT_BLOCK and seq_len % block:
        block //= 2
    return block


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, max_ref, sum_ref, acc_ref,
    *, block_q: int, block_k: int, scale: float, causal: bool,
):
    # q_ref/o_ref: [1, 1, block_q, D] tiles; k_ref/v_ref: [1, 1, block_k, D]
    q_block_idx = pl.program_id(2)
    k_block_idx = pl.program_id(3)
    num_k_blocks = pl.num_programs(3)
    q_offset = q_block_idx * block_q
    k_offset = k_block_idx * block_k

    @pl.when(k_block_idx == 0)
    def _init():
        max_ref[:] = jnp.full_like(max_ref, -jnp.inf)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # blocks strictly above the diagonal contribute nothing under causality
    diagonal_or_below = k_offset <= q_offset + block_q - 1

    @pl.when(jnp.logical_or(not causal, diagonal_or_below))
    def _compute():
        # keep q/k in their storage dtype (bf16) into the dot so the MXU
        # runs bf16 inputs with fp32 accumulate — casting to f32 first would
        # force a (much slower) f32 matmul; fold the 1/sqrt(D) scale in after
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        scores = (
            jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        )  # [bq, bk] fp32
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(rows >= cols, scores, -jnp.inf)
        run_max = max_ref[:]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        new_max = jnp.maximum(run_max, block_max)
        # rows fully masked in THIS block get exp(-inf - finite) = 0; rows
        # with no finite max yet cannot occur under causal iteration order
        # (k block 0 is unmasked for every q row)
        probs = jnp.exp(scores - new_max)
        correction = jnp.exp(run_max - new_max)
        max_ref[:] = new_max
        sum_ref[:] = sum_ref[:] * correction + jnp.sum(
            probs, axis=-1, keepdims=True
        )
        acc_ref[:] = acc_ref[:] * correction + jnp.dot(
            probs.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    @pl.when(k_block_idx == num_k_blocks - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / sum_ref[:]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "causal", "interpret")
)
def _flash_call(
    q, k, v, *, block_q: int, block_k: int, causal: bool, interpret: bool
):
    batch, heads, seq_len, head_dim = q.shape
    grid = (batch, heads, seq_len // block_q, seq_len // block_k)
    q_spec = pl.BlockSpec(
        (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, j, 0)
    )
    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=1.0 / head_dim**0.5,
        causal=causal,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running sum
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_q: int | None = None,
    block_k: int | None = None,
    causal: bool = True,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal flash attention on ``[B, H, S, D]`` (drop-in for
    ``model._dense_attention``).

    ``block_q``/``block_k`` default to the largest power-of-two tile up to
    512 that divides ``S`` — measured on TPU v5e, 512-wide tiles run ~2x
    faster than 128 at long S (fewer grid steps, better MXU utilization).
    ``interpret=None`` auto-selects: compiled kernel on TPU, Pallas
    interpreter elsewhere (same code path, for tests/CPU dev — slow).
    Requires ``S`` divisible by the block sizes; callers with small/odd
    shapes should use the dense path (see :func:`attention_fn_for`).
    """
    seq_len = q.shape[2]
    block_q = _pick_block(seq_len, block_q)
    block_k = _pick_block(seq_len, block_k)
    if seq_len % block_q or seq_len % block_k:
        raise ValueError(
            f"seq_len={seq_len} not divisible by block sizes "
            f"({block_q}, {block_k})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_call(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        interpret=interpret,
    )


def attention_fn_for(
    seq_len: int, *, block: int = DEFAULT_BLOCK, backend: str | None = None
):
    """Pick the attention implementation for a static sequence length.

    The flash kernel is chosen only when (a) the shape tiles cleanly onto
    the MXU blocks AND (b) the backend is actually TPU — everywhere else
    the dense XLA path wins (off TPU the kernel would run in the
    Python-speed Pallas interpreter, which must never end up on a serving
    hot path). ``backend=None`` reads ``jax.default_backend()``.

    Use as ``forward(..., attention_fn=attention_fn_for(seq))``.
    """
    from .model import _dense_attention

    if backend is None:
        backend = jax.default_backend()
    if backend == "tpu" and seq_len >= block and seq_len % block == 0:
        return flash_attention
    return _dense_attention
