"""Pallas flash attention: the workload's hot op as a TPU kernel.

Causal multi-head attention with the flash-attention schedule — online
softmax over key/value blocks, never materializing the ``[S, S]`` score
matrix — written in Pallas for TPU (no reference counterpart: the reference
contains no numerical code at all, SURVEY.md §2).

Why a kernel when XLA already fuses well: for ``S`` up to a few thousand the
dense path (``model._dense_attention``) is fine, but its ``[B, H, S, S]``
fp32 score tensor is HBM-resident; at ``S = 8k`` with 8 heads that is 2 GiB
per example. The flash schedule keeps only per-block tiles on chip, turning
attention from HBM-bandwidth-bound to MXU-bound.

TPU mapping:

- grid ``(batch, heads, S/block_q, S/block_k)``; TPU grid iteration is
  sequential with the last axis innermost, so the fp32 running
  max / sum / output accumulators live in VMEM *scratch* that persists
  across the ``k`` axis (initialized at ``k==0``, written out at the last
  ``k`` block) — VMEM residency is O(block), independent of ``S``;
- q/k/v arrive as ``[block, head_dim]`` VMEM tiles via BlockSpec index
  maps; score tiles hit the MXU via
  ``jnp.dot(..., preferred_element_type=f32)``;
- causality makes blocks strictly above the diagonal no-ops (``pl.when``
  skips their compute entirely — about half the FLOPs of full attention)
  and masks the partial diagonal blocks with ``-inf``;
- block sizes auto-select the largest power-of-two tile up to 512 dividing
  ``S`` (128 = lane-width minimum); bf16-into-the-MXU (fp32 accumulate
  only) keeps the score matmul on the fast path.

**GQA-native**: ``k``/``v`` may carry fewer heads than ``q``
(``[B, H_kv, S, D]`` with ``H % H_kv == 0``).  The query-head → kv-head
mapping happens in the BlockSpec *index maps* (``h // groups``), so the
kernel streams the compact ``H_kv``-head K/V straight from HBM — the
bandwidth GQA exists to save is actually saved, with no
``repeat_kv`` materialization before the kernel (the dense XLA path needs
the broadcast; see ``llama._gqa_wrap``).

**Differentiable**: the backward pass is two more Pallas kernels under
``jax.custom_vjp`` (the flash-attention backward recurrence):

- the forward additionally emits the per-row logsumexp ``L = m + log(l)``;
- ``dq`` kernel: grid ``(B, H, S/bq, S/bk)``, recomputes the probability
  tile ``p = exp(q kᵀ·s − L)`` and accumulates ``dq += (p∘(dp − Δ))·s @ k``
  in VMEM scratch across the k axis;
- ``dk``/``dv`` kernel: grid ``(B, H_kv, S/bk, groups·S/bq)`` — the
  query-head group is *folded into the innermost grid axis*, so the
  per-kv-head accumulators sum over all query heads of the group in VMEM
  and each compact dk/dv block is written exactly once (this is where
  GQA's backward would otherwise materialize full-head gradients);
- ``Δ = rowsum(dO ∘ O)`` is precomputed outside the kernels (one fused
  elementwise reduction, XLA's bread and butter).

Plugs into the model through the ``attention_fn`` seam
(``model.forward(..., attention_fn=flash_attention)``); composes with
ring/zig-zag attention as the per-hop local kernel via
:func:`flash_attention_lse` — rectangular blocks with a ``q_shift``
causal offset return normalized ``(out, lse)`` partials that
:func:`merge_attention_partials` folds across hops (see
``ring._ring_attention_kernel_local`` / ``zigzag``'s counterpart) — and
with a sharded mesh via :func:`make_sharded_attention` (a ``shard_map``
wrapper, so the ``pallas_call`` partitions over data/model axes instead
of forcing XLA to gather around an opaque custom call).

Off TPU the kernels run in Pallas interpret mode (exact same code path), so
the CPU test suite validates the real kernels — but interpret mode is
Python-speed, which is why :func:`attention_fn_for` only dispatches to the
kernel when actually running on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

DEFAULT_BLOCK = 128  # minimum tile: the MXU/VPU lane width
# Best-measured tile on TPU v5e: 1024 beats 512 at every measured shape
# (fwd+bwd, S ∈ {2k, 4k, 8k}, D ∈ {64, 128} — e.g. S=2048/D=128
# 4.40 -> 2.68 ms); 2048-wide tiles exceed VMEM and fail to compile.
PREFERRED_BLOCK = 1024
# Row statistics (logsumexp, Δ) are stored lane-replicated as
# [B, H, S, 128]: Mosaic requires the last two block dims to be
# (8, 128)-tiled, so a [bq]-shaped row vector is not a legal output tile —
# broadcasting each per-row scalar across one lane width is the canonical
# TPU layout for them (the upstream TPU flash kernel does the same).
_LANES = 128

# All three kernels iterate (batch, head, outer block, inner block) with the
# VMEM accumulators carried across the innermost axis only: batch/head/outer
# are embarrassingly parallel, the inner axis is a sequential reduction.
# Telling Mosaic so (instead of the all-"arbitrary" default) lets it
# reorder/pipeline the parallel dims — measured ~10% off fwd+bwd at the
# flagship train shape (B=8, H=16, S=2048, D=64, TPU v5e).
# jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5; accept
# whichever this jaxlib ships so the kernels import on both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)
_GRID_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
)


def tiles_cleanly(seq_len: int) -> bool:
    """Whether the auto-picked block divides ``seq_len`` — the shape gate
    callers use before choosing a kernel path (e.g. ring/zig-zag fall
    back to their einsum body for local lengths like 192 that no
    power-of-two block >= 128 divides)."""
    return seq_len > 0 and seq_len % _pick_block(seq_len, None) == 0


def _pick_block(seq_len: int, requested: int | None) -> int:
    """Auto block size: the largest power-of-two <= PREFERRED_BLOCK that
    divides ``seq_len``, floored at DEFAULT_BLOCK (an explicit ``requested``
    wins, clamped to ``seq_len``; ``seq_len`` itself for short sequences).

    Non-dividing sequence lengths fall through to DEFAULT_BLOCK so the
    caller's divisibility check raises its clear ValueError instead of a
    mis-tiled kernel failing deep in Mosaic lowering.
    """
    if requested is not None:
        return min(requested, seq_len)
    if seq_len <= DEFAULT_BLOCK:
        return seq_len
    block = 1 << (min(PREFERRED_BLOCK, seq_len).bit_length() - 1)
    while block > DEFAULT_BLOCK and seq_len % block:
        block //= 2
    return block


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _window_live(q_offset, k_offset, block_k: int, window: int | None):
    """Whether any of this block's keys can fall inside some row's
    sliding window (False = the whole block is older than the oldest
    row's window start and is skipped like an above-diagonal block).
    Offsets are traced grid values; ``window`` is static."""
    if window is None:
        return True
    return k_offset + block_k - 1 >= q_offset - window + 1


def _window_mask(scores, rows, cols, window: int | None):
    """Mask keys older than each row's ``window``-position lookback
    (row ``r`` attends ``r - window + 1 .. r`` under causality)."""
    if window is None:
        return scores
    return jnp.where(cols > rows - window, scores, -jnp.inf)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *rest,
    block_q: int, block_k: int, scale: float, causal: bool, q_shift: int,
    window: int | None,
):
    # rest = (lse_ref,) + scratch when the caller needs the backward's
    # logsumexp residual, else just the scratch refs
    if len(rest) == 4:
        lse_ref, max_ref, sum_ref, acc_ref = rest
    else:
        lse_ref = None
        max_ref, sum_ref, acc_ref = rest
    # q_ref/o_ref: [1, 1, block_q, D] tiles; k_ref/v_ref: [1, 1, block_k, D]
    # (already the kv head for this query head, via the BlockSpec index map)
    q_block_idx = pl.program_id(2)
    k_block_idx = pl.program_id(3)
    num_k_blocks = pl.num_programs(3)
    # q_shift: static offset of q row 0's causal position relative to k
    # column 0 — rectangular blocks of a larger attention problem (ring /
    # zig-zag hops) express their piece of the global causal mask with it
    # (row i attends cols <= i + q_shift); 0 = plain causal
    q_offset = q_block_idx * block_q
    k_offset = k_block_idx * block_k

    @pl.when(k_block_idx == 0)
    def _init():
        max_ref[:] = jnp.full_like(max_ref, -jnp.inf)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # blocks strictly above the diagonal contribute nothing under
    # causality; blocks entirely below the sliding window likewise
    diagonal_or_below = k_offset <= q_offset + q_shift + block_q - 1
    live = jnp.logical_and(
        jnp.logical_or(not causal, diagonal_or_below),
        _window_live(q_offset + q_shift, k_offset, block_k, window),
    )

    @pl.when(live)
    def _compute():
        # keep q/k in their storage dtype (bf16) into the dot so the MXU
        # runs bf16 inputs with fp32 accumulate — casting to f32 first would
        # force a (much slower) f32 matmul; fold the 1/sqrt(D) scale in after
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        scores = (
            jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        )  # [bq, bk] fp32
        if causal:
            rows = q_offset + q_shift + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            # a sliding window uses a large FINITE mask: a row whose whole
            # block is below its window would make block_max = -inf and
            # exp(-inf - -inf) = NaN; with -1e30 the dead row's new_max
            # stays -1e30 and the explicit live-row guard below zeroes its
            # probs.  The windowless path keeps the exact -inf masking
            # (every row's k block 0 is live under plain causality).
            mask_value = -jnp.inf if window is None else jnp.float32(-1e30)
            scores = jnp.where(rows >= cols, scores, mask_value)
            if window is not None:
                scores = jnp.where(cols > rows - window, scores, mask_value)
        run_max = max_ref[:]
        block_max = jnp.max(scores, axis=-1, keepdims=True)
        new_max = jnp.maximum(run_max, block_max)
        # rows fully masked in THIS block get exp(-inf - finite) = 0; rows
        # with no finite max yet cannot occur under causal iteration order
        # (k block 0 is unmasked for every q row) — except under a sliding
        # window, where the live-row guard handles them
        probs = jnp.exp(scores - new_max)
        if window is not None:
            probs = probs * (new_max > -1e29)
        correction = jnp.exp(run_max - new_max)
        max_ref[:] = new_max
        sum_ref[:] = sum_ref[:] * correction + jnp.sum(
            probs, axis=-1, keepdims=True
        )
        acc_ref[:] = acc_ref[:] * correction + jnp.dot(
            probs.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    @pl.when(k_block_idx == num_k_blocks - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / sum_ref[:]).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp, the backward pass's softmax residual
            lse_ref[0, 0] = jnp.broadcast_to(
                max_ref[:] + jnp.log(sum_ref[:]), (o_ref.shape[2], _LANES)
            )


@functools.partial(
    jax.jit,
    static_argnames=(
        "block_q", "block_k", "causal", "interpret", "need_lse", "q_shift",
        "window",
    ),
)
def _fwd_call(
    q, k, v, *, block_q: int, block_k: int, causal: bool, interpret: bool,
    need_lse: bool, q_shift: int = 0, window: int | None = None,
):
    # need_lse=False (forward-only / serving): the logsumexp output is not
    # declared at all, so the kernel writes no [B, H, S, _LANES] residual
    # to HBM; the differentiated path requests it for the backward.
    # q and k/v may carry different sequence lengths (rectangular blocks
    # of a larger problem — the ring/zig-zag hops).
    batch, heads, q_len, head_dim = q.shape
    kv_heads, k_len = k.shape[1], k.shape[2]
    groups = heads // kv_heads
    grid = (batch, heads, q_len // block_q, k_len // block_k)
    q_spec = pl.BlockSpec(
        (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, head_dim),
        lambda b, h, i, j: (b, h // groups, j, 0),
    )
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)
    )
    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=1.0 / head_dim**0.5,
        causal=causal,
        q_shift=q_shift,
        window=window,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        compiler_params=_GRID_SEMANTICS,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=(q_spec, lse_spec) if need_lse else (q_spec,),
        out_shape=(
            (
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct(
                    (batch, heads, q_len, _LANES), jnp.float32
                ),
            )
            if need_lse
            else (jax.ShapeDtypeStruct(q.shape, q.dtype),)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running sum
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out if need_lse else (out[0], None)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, block_q: int, block_k: int, scale: float, causal: bool, q_shift: int,
    window: int | None,
):
    q_block_idx = pl.program_id(2)
    k_block_idx = pl.program_id(3)
    num_k_blocks = pl.num_programs(3)
    q_offset = q_block_idx * block_q
    k_offset = k_block_idx * block_k

    @pl.when(k_block_idx == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    diagonal_or_below = k_offset <= q_offset + q_shift + block_q - 1
    live = jnp.logical_and(
        jnp.logical_or(not causal, diagonal_or_below),
        _window_live(q_offset + q_shift, k_offset, block_k, window),
    )

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        scores = (
            jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        )  # [bq, bk]
        if causal:
            rows = q_offset + q_shift + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            # -inf is NaN-safe here: p = exp(-inf - lse) = 0 because every
            # row's lse is finite (the diagonal key is always in-window)
            scores = jnp.where(rows >= cols, scores, -jnp.inf)
            scores = _window_mask(scores, rows, cols, window)
        # exact softmax probabilities via the saved logsumexp: masked
        # entries are exp(-inf - finite) = 0 (row stats are
        # lane-replicated [bq, _LANES] tiles; column 0 is the value)
        p = jnp.exp(scores - lse_ref[0, 0][:, :1])
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale  # [bq, bk] fp32
        dq_acc[:] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    @pl.when(k_block_idx == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, block_q: int, block_k: int, num_q_blocks: int, scale: float,
    causal: bool, q_shift: int, window: int | None,
):
    # grid (B, H_kv, S/bk, groups * S/bq): the innermost axis enumerates
    # (query head of the group, q block) pairs, so the VMEM accumulators
    # sum the whole group's contribution and each compact [bk, D] dk/dv
    # block is written exactly once
    k_block_idx = pl.program_id(2)
    t = pl.program_id(3)
    num_t = pl.num_programs(3)
    q_block_idx = t % num_q_blocks
    q_offset = q_block_idx * block_q
    k_offset = k_block_idx * block_k

    @pl.when(t == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    diagonal_or_below = k_offset <= q_offset + q_shift + block_q - 1
    live = jnp.logical_and(
        jnp.logical_or(not causal, diagonal_or_below),
        _window_live(q_offset + q_shift, k_offset, block_k, window),
    )

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        scores = (
            jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        )  # [bq, bk]
        if causal:
            rows = q_offset + q_shift + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(rows >= cols, scores, -jnp.inf)
            scores = _window_mask(scores, rows, cols, window)
        p = jnp.exp(scores - lse_ref[0, 0][:, :1])  # [bq, bk]
        dv_acc[:] += jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dk_acc[:] += jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )

    @pl.when(t == num_t - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "block_q", "block_k", "causal", "interpret", "q_shift", "window",
    ),
)
def _bwd_call(
    q, k, v, out, lse, do, dlse=None,
    *, block_q: int, block_k: int, causal: bool, interpret: bool,
    q_shift: int = 0, window: int | None = None,
):
    batch, heads, q_len, head_dim = q.shape
    kv_heads, k_len = k.shape[1], k.shape[2]
    groups = heads // kv_heads
    num_q_blocks = q_len // block_q
    num_k_blocks = k_len // block_k
    scale = 1.0 / head_dim**0.5

    # Δ = rowsum(dO ∘ O): one fused elementwise reduction, no kernel
    # needed; lane-replicated to the [B, H, S, _LANES] row-stat layout.
    # An lse cotangent folds in as Δ' = Δ − dlse: the total score
    # cotangent is ds = p∘(dp − Δ + dlse) (d lse/d s = p), so shifting Δ
    # routes it through the existing kernels unchanged.
    delta_rows = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    if dlse is not None:
        delta_rows = delta_rows - dlse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta_rows, (batch, heads, q_len, _LANES))

    # dq: same grid shape as the forward
    q_spec = pl.BlockSpec(
        (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, head_dim),
        lambda b, h, i, j: (b, h // groups, j, 0),
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)
    )
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
            q_shift=q_shift, window=window,
        ),
        grid=(batch, heads, num_q_blocks, num_k_blocks),
        compiler_params=_GRID_SEMANTICS,
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: kv-head-major grid; query-head group folded into the inner axis
    def q_idx(b, g, j, t):
        return (b, g * groups + t // num_q_blocks, t % num_q_blocks, 0)

    q_spec2 = pl.BlockSpec((1, 1, block_q, head_dim), q_idx)
    kv_spec2 = pl.BlockSpec(
        (1, 1, block_k, head_dim), lambda b, g, j, t: (b, g, j, 0)
    )
    row_spec2 = pl.BlockSpec((1, 1, block_q, _LANES), q_idx)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            block_q=block_q, block_k=block_k, num_q_blocks=num_q_blocks,
            scale=scale, causal=causal, q_shift=q_shift, window=window,
        ),
        grid=(batch, kv_heads, num_k_blocks, groups * num_q_blocks),
        compiler_params=_GRID_SEMANTICS,
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, block_q, block_k, causal, interpret, window):
    out, _ = _fwd_call(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        interpret=interpret, need_lse=False, window=window,
    )
    return out


def _flash_fwd(q, k, v, block_q, block_k, causal, interpret, window):
    out, lse = _fwd_call(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        interpret=interpret, need_lse=True, window=window,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(block_q, block_k, causal, interpret, window, residuals, do):
    q, k, v, out, lse = residuals
    dq, dk, dv = _bwd_call(
        q, k, v, out, lse, do,
        block_q=block_q, block_k=block_k, causal=causal, interpret=interpret,
        window=window,
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, block_q, block_k, causal, q_shift, interpret):
    out, lse = _fwd_call(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        interpret=interpret, need_lse=True, q_shift=q_shift,
    )
    return out, lse[..., 0]


def _flash_lse_fwd(q, k, v, block_q, block_k, causal, q_shift, interpret):
    out, lse = _fwd_call(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        interpret=interpret, need_lse=True, q_shift=q_shift,
    )
    return (out, lse[..., 0]), (q, k, v, out, lse)


def _flash_lse_bwd(block_q, block_k, causal, q_shift, interpret, residuals,
                   cotangents):
    q, k, v, out, lse = residuals
    do, dlse = cotangents
    dq, dk, dv = _bwd_call(
        q, k, v, out, lse, do, dlse,
        block_q=block_q, block_k=block_k, causal=causal, interpret=interpret,
        q_shift=q_shift,
    )
    return dq, dk, dv


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_shift: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """:func:`flash_attention` that also returns the per-row logsumexp.

    The composable form: ``(out, lse)`` partials from rectangular blocks
    of a larger attention problem merge exactly via
    :func:`merge_attention_partials` — this is what makes the kernel the
    per-shard local op of ring/zig-zag attention (each hop is one kernel
    call; the online-softmax merge happens across hops).  Differentiable
    in both outputs: an ``lse`` cotangent folds into the backward kernels
    as a Δ shift (see ``_bwd_call``).

    ``q`` may be shorter than ``k``/``v`` (rectangular); ``q_shift``
    places q row 0 at that causal position relative to k column 0 (row
    ``i`` attends cols ``<= i + q_shift``; must be >= 0 so every row has
    at least one visible key).  ``lse`` is fp32 ``[B, H, S_q]``.
    """
    q_len, k_len = q.shape[2], k.shape[2]
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"query heads {q.shape[1]} not divisible by kv heads {k.shape[1]}"
        )
    if causal and q_shift < 0:
        raise ValueError(f"q_shift={q_shift} must be >= 0 under causal")
    block_q = _pick_block(q_len, block_q)
    block_k = _pick_block(k_len, block_k)
    if q_len % block_q or k_len % block_k:
        raise ValueError(
            f"shapes ({q_len}, {k_len}) not divisible by block sizes "
            f"({block_q}, {block_k})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_lse(q, k, v, block_q, block_k, causal, q_shift, interpret)


MERGE_NEG_INF = -1e9
"""Initial / not-covered lse value for :func:`merge_attention_partials`:
large-negative *finite* so ``-inf - -inf`` NaNs can never arise in the
merge or its gradient (``exp(-1e9 - x)`` underflows to exactly 0).  A
plain Python float on purpose: a module-level ``jnp`` constant would be
traced into the first ``shard_map``'s mesh context and then poison every
later trace on a different mesh."""


def merge_attention_partials(acc_out, acc_lse, out, lse):
    """Fold one ``(out, lse)`` attention partial into fp32 accumulators.

    Standard normalized-partial merge: with ``L = logaddexp(acc_lse,
    lse)``, the merged output is ``acc_out·e^{acc_lse−L} + out·e^{lse−L}``
    — associative, so hops can arrive in any order.  Start from
    ``acc_out = 0``, ``acc_lse = MERGE_NEG_INF``; rows a partial does not
    cover contribute ``lse = MERGE_NEG_INF`` (weight exactly 0).
    """
    new_lse = jnp.logaddexp(acc_lse, lse)
    w_acc = jnp.exp(acc_lse - new_lse)[..., None]
    w_new = jnp.exp(lse - new_lse)[..., None]
    return acc_out * w_acc + out.astype(jnp.float32) * w_new, new_lse


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_q: int | None = None,
    block_k: int | None = None,
    causal: bool = True,
    interpret: bool | None = None,
    window: int | None = None,
) -> jax.Array:
    """Causal flash attention on ``[B, H, S, D]`` (drop-in for
    ``model._dense_attention``), differentiable (Pallas backward kernels)
    and GQA-native: ``k``/``v`` may be ``[B, H_kv, S, D]`` with
    ``H % H_kv == 0`` — the compact heads are streamed directly, no
    ``repeat_kv`` materialization.

    ``window`` enables Mistral-style sliding-window attention: row ``r``
    attends keys ``r - window + 1 .. r`` (requires ``causal``).  Blocks
    entirely below the window are skipped like above-diagonal blocks, so
    long-sequence cost is ``O(S·window)``, not ``O(S²)``.

    ``block_q``/``block_k`` default to the largest power-of-two tile up to
    1024 that divides ``S``. ``interpret=None`` auto-selects: compiled
    kernel on TPU, Pallas interpreter elsewhere (same code path, for
    tests/CPU dev — slow). Requires ``S`` divisible by the block sizes;
    callers with small/odd shapes should use the dense path (see
    :func:`attention_fn_for`).
    """
    seq_len = q.shape[2]
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"query heads {q.shape[1]} not divisible by kv heads {k.shape[1]}"
        )
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
    block_q = _pick_block(seq_len, block_q)
    block_k = _pick_block(seq_len, block_k)
    if seq_len % block_q or seq_len % block_k:
        raise ValueError(
            f"seq_len={seq_len} not divisible by block sizes "
            f"({block_q}, {block_k})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, block_q, block_k, causal, interpret, window)


# GQA marker the attention_fn dispatchers check: this kernel accepts
# [B, H_kv, S, D] k/v directly (the dense path needs repeat_kv first)
flash_attention.gqa_native = True


FLASH_MIN_SEQ = 2048
"""Measured flash/dense crossover (TPU v5e, fwd+bwd, interleaved medians
of 30-iteration timings): S=512 0.96x, S=1024 0.95-1.00x across sessions,
S=2048 1.43-1.61x, S=4096 2.8-3.2x, S=8192 27.9x — at or below 1k both
paths are dispatch-bound and dense's single fused XLA computation ties or
edges out the kernel, so the dispatcher only picks the kernel from the
first shape where it measurably wins."""


def attention_fn_for(
    seq_len: int, *, block: int = DEFAULT_BLOCK, backend: str | None = None
):
    """Pick the attention implementation for a static sequence length.

    The flash kernel is chosen only when (a) the shape tiles cleanly onto
    the MXU blocks, (b) the backend is actually TPU — everywhere else
    the dense XLA path wins (off TPU the kernel would run in the
    Python-speed Pallas interpreter, which must never end up on a serving
    hot path) — and (c) ``seq_len`` is at or past the measured crossover
    (:data:`FLASH_MIN_SEQ`), so the hot path is never slower than dense
    at any shape. ``backend=None`` reads ``jax.default_backend()``.

    Use as ``forward(..., attention_fn=attention_fn_for(seq))``.
    """
    from .model import _dense_attention

    if backend is None:
        backend = jax.default_backend()
    if (
        backend == "tpu"
        and seq_len >= max(block, FLASH_MIN_SEQ)
        and seq_len % block == 0
    ):
        return flash_attention
    return _dense_attention


def windowed(fn, window: int | None):
    """Bind a sliding window into an attention fn (``flash_attention`` or
    ``model._dense_attention`` — both take ``window=``), preserving the
    ``gqa_native`` marker.  ``None`` returns ``fn`` untouched."""
    if window is None:
        return fn

    def attend(q, k, v):
        return fn(q, k, v, window=window)

    attend.gqa_native = getattr(fn, "gqa_native", False)
    return attend


def gqa_adapt(fn):
    """The one place the GQA broadcast policy lives: adapt ``fn`` so it
    accepts compact ``[B, H_kv, S, D]`` k/v.  GQA-native kernels (marked
    ``gqa_native`` — the flash kernel, the sharded dispatcher) pass
    through untouched; MHA-shaped ones (dense XLA) get ``repeat_kv``
    applied just before the call (XLA fuses the broadcast into the
    matmul).  MHA inputs (``H == H_kv``) are unaffected either way.
    """
    if getattr(fn, "gqa_native", False):
        return fn

    def attend(q, k, v):
        if q.shape[1] != k.shape[1]:
            from .llama import repeat_kv

            groups = q.shape[1] // k.shape[1]
            k = repeat_kv(k, groups)
            v = repeat_kv(v, groups)
        return fn(q, k, v)

    return attend


def make_sharded_attention(
    mesh: Mesh,
    *,
    data_axis: str = "data",
    model_axis: str = "model",
    backend: str | None = None,
    window: int | None = None,
):
    """Attention fn for a ``(data, model)``-sharded mesh: per-shard
    flash-or-dense, wrapped in ``shard_map``.

    A ``pallas_call`` is an opaque custom call to the SPMD partitioner —
    left inside a plain ``jit``, sharded operands would be gathered to run
    it replicated. ``shard_map`` pins the shard-local view instead: batch
    shards over ``data_axis``, heads over ``model_axis`` (q's full heads
    and the compact GQA kv heads shard the same way, so the per-shard
    group structure is preserved), and the kernel choice is made at trace
    time from the *local* static shape (flash on TPU when it tiles, dense
    XLA elsewhere — same policy as :func:`attention_fn_for`).

    Meshes with a nontrivial ``seq`` axis use :mod:`.ring` instead (see
    ``train.mesh_attention_fn``).
    """
    spec = P(data_axis, model_axis, None, None)
    data_n = mesh.shape.get(data_axis, 1)
    model_n = mesh.shape.get(model_axis, 1)

    def local(q, k, v):
        return gqa_adapt(
            windowed(attention_fn_for(q.shape[2], backend=backend), window)
        )(q, k, v)

    def attend(q, k, v):
        # shard_map needs exact divisibility (unlike NamedSharding, which
        # pads); shapes that don't tile onto the mesh keep the plain XLA
        # dense path, where the partitioner handles any layout (never the
        # kernel: an unpartitioned pallas_call would force a gather)
        if (
            q.shape[0] % data_n
            or q.shape[1] % model_n
            or k.shape[1] % model_n
        ):
            from .model import _dense_attention

            return gqa_adapt(windowed(_dense_attention, window))(q, k, v)
        # check_vma=False: pallas_call out_shapes carry no varying-mesh-axes
        # info, so the vma checker cannot type the kernel's outputs
        return jax.shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    attend.gqa_native = True
    return attend
