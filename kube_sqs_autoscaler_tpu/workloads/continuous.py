"""Continuous batching: rolling decode slots that refill independently.

The batch-generate worker (:mod:`.service` in generate mode) decodes a
whole batch to completion before touching the queue again — one long
prompt or one unlucky batch blocks every other message (head-of-line
blocking).  Real LM serving keeps a *rolling* batch instead: every row of
the KV cache is an independent slot; each engine step advances all active
slots by one token, finished slots emit their continuation immediately,
and new requests are prefilled **into** a free slot while the others keep
decoding.  The per-row cache machinery from :mod:`.decode` (per-row
``length``, per-row write positions, per-row masks) is exactly what makes
this work — and the llama family's compact GQA cache
(:func:`.llama.init_llama_cache`) has the same per-row shape, so both
families serve through one slot machine.

TPU shape discipline: there are only two compiled programs —

- ``decode_step`` (the existing one): advances all ``batch`` slots one
  position, active or not (inactive rows compute garbage that is never
  read — lockstep static shapes beat dynamic batch reshapes);
- ``insert`` : prefill one prompt (padded to a fixed bucket) as a
  ``[1, P]`` batch and ``dynamic_update_slice`` its layer caches into the
  slot's row, set the row's length, and return the first sampled token.

Sampling is :func:`.decode._pick` — the one policy every decode path
shares (greedy at temperature 0, else temperature/top-k/top-p), keyed
per engine step from :func:`.service.sampling_keys`.  ``eos_id`` frees a
slot the moment it fires (the continuous-batching win: the row's cache
becomes a fresh slot while its batchmates keep decoding); outputs are
padded with ``eos_id`` to the token budget, exactly like
:func:`.decode.generate`'s post-eos padding, so the greedy
outputs-equal-per-request invariant holds verbatim.

The reference has no serving at all (SURVEY.md §2); this is the TPU-shop
shape of the queue-consumer its README deploys.
"""

from __future__ import annotations

import itertools
import logging
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .decode import _pick, init_cache, prefill

log = logging.getLogger(__name__)


def _insert_row_impl(
    params: dict,
    cache: dict,
    row: jax.Array,
    prompt: jax.Array,
    length: jax.Array,
    key: jax.Array | None,
    config: Any,
    prompt_len: int,
    family: str = "gpt",
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized_kv: bool = False,
    prefix_len: int = 0,
    prefix_cache: dict | None = None,
) -> tuple[dict, jax.Array]:
    """Prefill ``prompt`` (int32 ``[prompt_len]``, right-padded to the
    static bucket) and splice it into slot ``row`` of ``cache``.

    Returns ``(cache, first_token)`` — the slot's length is the prompt's
    real length and its first continuation token (greedy or sampled by
    the shared ``_pick`` policy with ``key``) is ready to feed the next
    ``decode_step``.  ``family`` picks the prefill: the gpt path or the
    llama GQA path — the splice is layout-agnostic (every cache entry
    puts the batch row on axis 0 and the POSITION on axis 2: ``[B, H,
    S, D]`` codes/values and ``[B, H, S]`` scales alike, so one
    axis-2 slice serves both the bf16 and the int8 layouts).

    ``prefix_len > 0`` (with ``prefix_cache``): the prompt is a SUFFIX
    continuing from a shared prefix — the prefill runs through
    ``prefill_with_prefix``, only the suffix region ``[prefix_len,
    prefix_len + prompt_len)`` is spliced (the batch cache's rows
    already hold the broadcast prefix, which slot reuse never
    overwrites — decode writes at ``length >= prefix_len``), and the
    slot's length starts past the prefix.
    """
    logits, row_cache = _row_prefill(
        params, prompt, length, config, family, quantized_kv, prefix_len,
        prefix_cache,
    )
    new_layers = _splice_row_layers(cache, row_cache, row, prefix_len,
                                    prompt_len)
    lengths = jax.lax.dynamic_update_index_in_dim(
        cache["length"], prefix_len + length, row, 0
    )
    first = _pick(logits, key, temperature, top_k, top_p)[0]
    return {"layers": new_layers, "length": lengths}, first


def _row_prefill(params, prompt, length, config, family, quantized_kv,
                 prefix_len, prefix_cache):
    """One prompt's prefill as a ``[1, P]`` batch through the family's
    layout variant; returns ``(logits [1, V], row_cache)``."""
    if prefix_len:
        if quantized_kv:
            if family == "llama":
                from .llama import (
                    llama_quantized_prefill_with_prefix as pf,
                )
            else:
                from .decode import quantized_prefill_with_prefix as pf
        elif family == "llama":
            from .llama import llama_prefill_with_prefix as pf
        else:
            from .decode import prefill_with_prefix as pf
        return pf(
            params, prefix_cache, prompt[None], config, lengths=length[None]
        )
    if quantized_kv:
        if family == "llama":
            from .llama import llama_quantized_prefill as prefill_fn
        else:
            from .decode import quantized_prefill as prefill_fn
    elif family == "llama":
        from .llama import llama_prefill as prefill_fn
    else:
        prefill_fn = prefill
    return prefill_fn(params, prompt[None], config, lengths=length[None])


def _splice_row_layers(cache, row_cache, row, prefix_len, prompt_len,
                       beams: int = 1):
    """Splice a ``[1, ...]`` row cache's prompt positions into slot
    ``row`` of the batch cache; returns the new layers list.

    ``beams > 1``: the one prefilled row is repeated ``beams`` times and
    spliced into the slot's contiguous row block
    ``[row*beams, (row+1)*beams)`` — every beam of a fresh beam slot
    starts from the same prompt cache (``beams=1`` degenerates to the
    plain single-row splice)."""
    new_layers = []
    for layer_cache, row_layer in zip(cache["layers"], row_cache["layers"]):
        entry = {}
        for name, buf in layer_cache.items():
            piece = row_layer[name]
            # keep only the prompt positions: axis 2 for [1, H, S, D]
            # codes/values, axis 2 for [1, H, S] scales too (under a
            # prefix, the suffix positions only)
            piece = jax.lax.slice_in_dim(
                piece, prefix_len, prefix_len + prompt_len, axis=2
            )
            if beams > 1:
                piece = jnp.repeat(piece, beams, axis=0)
            start = (row * beams, 0, prefix_len) + (0,) * (buf.ndim - 3)
            entry[name] = jax.lax.dynamic_update_slice(buf, piece, start)
        new_layers.append(entry)
    return new_layers


def _spec_insert_row_impl(
    params: dict,
    cache: dict,
    draft_cache: dict,
    row: jax.Array,
    prompt: jax.Array,
    length: jax.Array,
    key: jax.Array | None,
    config: Any,
    prompt_len: int,
    draft_layers: int,
    family: str = "gpt",
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized_kv: bool = False,
    prefix_len: int = 0,
    prefix_cache: dict | None = None,
) -> tuple[dict, dict, jax.Array]:
    """:func:`_insert_row_impl` for speculative slots: ONE target prefill
    populates both caches — the early-exit self-draft is the target's
    first ``draft_layers`` layers, and layer ``i``'s k/v depend only on
    layers ``< i``, so the draft's row cache is literally the layer-wise
    prefix of the target's (same trick as
    :func:`.speculative.draft_prefix_from_target`)."""
    logits, row_cache = _row_prefill(
        params, prompt, length, config, family, quantized_kv, prefix_len,
        prefix_cache,
    )
    new_layers = _splice_row_layers(cache, row_cache, row, prefix_len,
                                    prompt_len)
    draft_row = {"layers": row_cache["layers"][:draft_layers],
                 "length": row_cache["length"]}
    new_draft_layers = _splice_row_layers(draft_cache, draft_row, row,
                                          prefix_len, prompt_len)
    lengths = jax.lax.dynamic_update_index_in_dim(
        cache["length"], prefix_len + length, row, 0
    )
    draft_lengths = jax.lax.dynamic_update_index_in_dim(
        draft_cache["length"], prefix_len + length, row, 0
    )
    first = _pick(logits, key, temperature, top_k, top_p)[0]
    return (
        {"layers": new_layers, "length": lengths},
        {"layers": new_draft_layers, "length": draft_lengths},
        first,
    )


_insert_row = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "family", "temperature",
                     "top_k", "top_p", "quantized_kv", "prefix_len"),
    donate_argnums=(1,),
)(_insert_row_impl)


_spec_insert_row = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "draft_layers", "family",
                     "temperature", "top_k", "top_p", "quantized_kv",
                     "prefix_len"),
    donate_argnums=(1, 2),
)(_spec_insert_row_impl)


def _beam_insert_row_impl(
    params: dict,
    cache: dict,
    scores: jax.Array,
    out: jax.Array,
    alive: jax.Array,
    emitted: jax.Array,
    current: jax.Array,
    row: jax.Array,
    prompt: jax.Array,
    length: jax.Array,
    config: Any,
    prompt_len: int,
    beams: int,
    family: str = "gpt",
    quantized_kv: bool = False,
    prefix_len: int = 0,
    eos_id: int | None = None,
    prefix_cache: dict | None = None,
) -> tuple[dict, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """:func:`_insert_row_impl` for beam slots: one prefill seeds the
    slot's ``beams`` cache rows and its device-side search state — the
    first expansion's top-``beams`` tokens become the beams' seeds
    (scores, first output column, alive mask), exactly the standalone
    :func:`.beam.beam_search` seeding re-hosted per slot."""
    logits, row_cache = _row_prefill(
        params, prompt, length, config, family, quantized_kv, prefix_len,
        prefix_cache,
    )
    new_layers = _splice_row_layers(cache, row_cache, row, prefix_len,
                                    prompt_len, beams=beams)
    lengths = jax.lax.dynamic_update_slice(
        cache["length"],
        jnp.full((beams,), prefix_len + length, jnp.int32),
        (row * beams,),
    )
    logp = jax.nn.log_softmax(logits[0].astype(jnp.float32))
    first_scores, first_tokens = jax.lax.top_k(logp, beams)
    first_tokens = first_tokens.astype(jnp.int32)
    out_row = jnp.full((beams, out.shape[-1]),
                       eos_id if eos_id is not None else 0, jnp.int32)
    out_row = out_row.at[:, 0].set(first_tokens)
    alive_row = (
        first_tokens != eos_id if eos_id is not None
        else jnp.ones((beams,), bool)
    )
    scores = jax.lax.dynamic_update_index_in_dim(scores, first_scores,
                                                 row, 0)
    out = jax.lax.dynamic_update_index_in_dim(out, out_row, row, 0)
    alive = jax.lax.dynamic_update_index_in_dim(alive, alive_row, row, 0)
    emitted = jax.lax.dynamic_update_index_in_dim(
        emitted, jnp.ones((beams,), jnp.int32), row, 0
    )
    current = jax.lax.dynamic_update_slice(current, first_tokens,
                                           (row * beams,))
    return ({"layers": new_layers, "length": lengths}, scores, out,
            alive, emitted, current)


# Donate the KV cache AND the five beam-state operands (scores, out,
# alive, emitted, current): all six are returned updated and immediately
# rebound by the caller, so XLA reuses their buffers in place instead of
# copying the whole search state per insert.
_beam_insert_row = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "beams", "family",
                     "quantized_kv", "prefix_len", "eos_id"),
    donate_argnums=(1, 2, 3, 4, 5, 6),
)(_beam_insert_row_impl)


@dataclass
class _Slot:
    busy: bool = False
    produced: list = field(default_factory=list)
    budget: int = 0
    done: bool = False  # hit eos before the budget (frees this step)
    payload: Any = None  # caller's per-request context (receipt handle...)
    # speculative slots: per-request verify rounds and accepted drafts
    # (the serving-side signal for tuning draft_tokens / draft_layers)
    rounds: int = 0
    accepted: int = 0


class ContinuousBatcher:
    """The slot machine: submit prompts, step the batch, collect results.

    Queue-agnostic and synchronous — drive it from anything that produces
    ``(token_ids, payload)`` requests.  Both model families (``family`` —
    the llama GQA cache is per-row just like the gpt one), greedy or
    sampled decoding (``temperature``/``top_k``/``top_p`` through the
    shared ``_pick`` policy, keyed per engine step), ``eos_id``
    termination per slot.  Greedy outputs are exactly what
    :func:`.decode.generate` / :func:`.llama.llama_generate` produce for
    each prompt alone, eos padding included (pinned by test): continuous
    batching changes *scheduling*, never results.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        batch_size: int,
        prompt_len: int,
        generate_tokens: int,
        *,
        family: str = "gpt",
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int | None = None,
        sample_seed: int = 0,
        mesh=None,
        quantized_kv: bool = False,
        prefix_cache: dict | None = None,
        draft_layers: int = 0,
        draft_tokens: int = 4,
        beams: int = 1,
        length_penalty: float = 0.0,
    ) -> None:
        if beams < 1:
            raise ValueError(f"beams={beams} must be >= 1")
        if beams > 1:
            # beam slots: each slot owns `beams` contiguous cache rows
            # and a device-side search state; deterministic by
            # construction, so the sampling/speculative knobs are out
            if draft_layers:
                raise ValueError(
                    "beams do not combine with draft_layers (beam "
                    "search is deterministic; speculative rounds are "
                    "per-row)"
                )
            if temperature > 0.0:
                raise ValueError(
                    "beams are deterministic; temperature must be 0"
                )
        self.prefix_len = 0
        self._prefix_cache = prefix_cache
        if prefix_cache is not None:
            # slots start past a shared, once-prefilled prefix (see
            # decode.prefill_prefix) in the decode path's cache layout —
            # bf16 or int8 (quantized_kv takes a quantized_prefill_prefix
            # cache), single-chip or head-sharded over a (data, model)
            # mesh (the broadcast rows land under cache_shardings in the
            # mesh block below)
            from .decode import _check_prefix_layout

            _check_prefix_layout(prefix_cache, quantized_kv)
            self.prefix_len = int(prefix_cache["length"][0])
        if draft_layers:
            # speculative slots: early-exit self-draft inside the slot
            # machine — each engine step is one draft-and-verify round
            if not 0 < draft_layers < config.n_layers:
                raise ValueError(
                    f"draft_layers={draft_layers} must be in "
                    f"[1, n_layers-1] (model has n_layers="
                    f"{config.n_layers})"
                )
            if draft_tokens < 1:
                raise ValueError(
                    f"draft_tokens={draft_tokens} must be >= 1"
                )
        # speculative rounds can overshoot a slot's budget by up to k and
        # still write k+1 masked positions past the frozen length — the
        # same 2k slack speculative_generate reserves
        spec_slack = 2 * draft_tokens if draft_layers else 0
        budget = self.prefix_len + prompt_len + generate_tokens + spec_slack
        if budget > config.max_seq_len:
            slack = f" + 2*draft_tokens ({spec_slack})" if spec_slack else ""
            raise ValueError(
                f"prefix + prompt_len + generate_tokens{slack} = "
                f"{budget} exceeds max_seq_len={config.max_seq_len}"
            )
        if family not in ("gpt", "llama"):
            raise ValueError(f"unknown family {family!r}")
        # unconditional (decode._pick re-checks at trace time, but that
        # would fire inside a worker's never-dies retry loop; greedy mode
        # must reject bad knobs at construction too)
        if top_k < 0:
            raise ValueError(f"top_k={top_k} must be >= 0")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p={top_p} must be in (0, 1]")
        self.params = params
        self.config = config
        self.family = family
        self.prompt_len = prompt_len
        self.generate_tokens = generate_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.mesh = mesh
        self.quantized_kv = quantized_kv
        self.draft_layers = draft_layers
        self.draft_tokens = draft_tokens
        self.beams = beams
        self.length_penalty = length_penalty
        # aggregate speculative stats (per-request stats ride the slots)
        self.spec_rounds = 0
        self.spec_accepted = 0
        # beam slots own `beams` contiguous cache rows each
        cache_rows = batch_size * beams
        if prefix_cache is not None:
            # every slot row starts as a copy of the shared prefix (the
            # broadcast is layout-agnostic: gpt and llama caches both
            # put rows on axis 0)
            from .decode import broadcast_prefix

            self.cache = broadcast_prefix(prefix_cache, cache_rows)
        elif quantized_kv:
            # slots store int8 codes + per-position scales: half the
            # bytes every engine step streams (see decode's int8 cache),
            # allocated directly — no transient bf16 buffers at startup
            from .decode import init_quantized_cache

            self.cache = init_quantized_cache(
                config, cache_rows,
                kv_heads=(config.n_kv_heads if family == "llama"
                          else None),
            )
        elif family == "llama":
            from .llama import init_llama_cache

            self.cache = init_llama_cache(config, cache_rows)
        else:
            self.cache = init_cache(config, cache_rows)
        if draft_layers:
            # the draft is the target's first layers: its params are a
            # layer slice, its cache the same layout with fewer layers
            import dataclasses

            self.draft_config = dataclasses.replace(
                config, n_layers=draft_layers
            )
            self.draft_params = dict(
                params, layers=params["layers"][:draft_layers]
            )
            if prefix_cache is not None:
                from .decode import broadcast_prefix
                from .speculative import draft_prefix_from_target

                self.draft_cache = broadcast_prefix(
                    draft_prefix_from_target(prefix_cache, draft_layers),
                    batch_size,
                )
            elif quantized_kv:
                from .decode import init_quantized_cache

                self.draft_cache = init_quantized_cache(
                    self.draft_config, batch_size,
                    kv_heads=(config.n_kv_heads if family == "llama"
                              else None),
                )
            elif family == "llama":
                from .llama import init_llama_cache

                self.draft_cache = init_llama_cache(
                    self.draft_config, batch_size
                )
            else:
                self.draft_cache = init_cache(self.draft_config,
                                              batch_size)
        self.slots = [_Slot() for _ in range(batch_size)]
        # each slot's pending input token(s) for the next decode step
        self._current = jnp.zeros((cache_rows,), jnp.int32)
        if beams > 1:
            # device-side per-slot search state (the standalone
            # beam_search's scan carry, re-hosted as rolling state)
            self._beam_scores = jnp.zeros((batch_size, beams), jnp.float32)
            self._beam_out = jnp.full(
                (batch_size, beams, generate_tokens),
                eos_id if eos_id is not None else 0, jnp.int32,
            )
            self._beam_alive = jnp.zeros((batch_size, beams), bool)
            self._beam_emitted = jnp.zeros((batch_size, beams), jnp.int32)
        if mesh is not None:
            # mesh-sharded slots: batch rows over "data", heads over
            # "model" (the serving layout of decode.cache_shardings);
            # the one-prompt insert prefill replicates over data — tp is
            # the axis that matters for a model too big for one chip
            from .decode import require_serving_mesh

            require_serving_mesh(mesh)
            if batch_size % mesh.shape["data"]:
                raise ValueError(
                    f"batch_size {batch_size} not divisible by the "
                    f"mesh's data axis ({mesh.shape['data']})"
                )
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .decode import cache_shardings

            self._cache_shard = cache_shardings(mesh, self.cache)
            self._rows_shard = NamedSharding(mesh, P("data"))
            self.cache = jax.device_put(self.cache, self._cache_shard)
            self._current = jax.device_put(self._current, self._rows_shard)
            if beams > 1:
                # slot-major state: slots over "data" (each slot's beam
                # rows stay contiguous within one shard because
                # batch_size % data == 0)
                self._slot_shard = NamedSharding(mesh, P("data", None))
                self._beam_scores = jax.device_put(self._beam_scores,
                                                   self._slot_shard)
                self._beam_out = jax.device_put(
                    self._beam_out, NamedSharding(mesh, P("data", None,
                                                          None)))
                self._beam_alive = jax.device_put(self._beam_alive,
                                                  self._slot_shard)
                self._beam_emitted = jax.device_put(self._beam_emitted,
                                                    self._slot_shard)
            if draft_layers:
                self._draft_cache_shard = cache_shardings(
                    mesh, self.draft_cache
                )
                self.draft_cache = jax.device_put(
                    self.draft_cache, self._draft_cache_shard
                )
        # one PRNG key per engine step / insert.  Greedy single-chip: no
        # keys at all (the compiled programs take a None operand); under
        # a mesh the pinned in_shardings need a real (ignored) key even
        # when greedy.
        if temperature > 0.0 or mesh is not None:
            from .service import sampling_keys

            self._keys = sampling_keys(sample_seed)
        else:
            self._keys = itertools.repeat(None)
        if beams > 1:
            self._insert = self._make_beam_insert()
            self._beam_step_fn = self._make_beam_step()
        elif draft_layers:
            self._insert = self._make_spec_insert()
            self._spec = self._make_spec_round()
        else:
            self._insert = self._make_insert()
            self._decode = self._make_decode_step()

    def _make_insert(self):
        statics = dict(
            config=self.config, prompt_len=self.prompt_len,
            family=self.family, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            quantized_kv=self.quantized_kv,
            prefix_len=self.prefix_len,
        )
        if self.mesh is None:
            return lambda params, cache, row, prompt, length, key: (
                _insert_row(params, cache, row, prompt, length, key,
                            prefix_cache=self._prefix_cache, **statics)
            )
        return self._mesh_insert_jit(_insert_row_impl, statics,
                                     (self._cache_shard,))

    def _mesh_insert_jit(self, impl, statics, cache_shards):
        """The one mesh insert wiring the plain and speculative inserts
        share: pinned in/out shardings with the cache operands donated,
        and — under a prefix — the shared batch-1 prefix riding as an
        explicit trailing operand (heads over "model", batch
        replicated), injected by a closure so both returned callables
        keep their prefix-free signature."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        scalar_ops = (rep, rep, rep, rep)  # row, prompt, length, key
        donate = tuple(range(1, 1 + len(cache_shards)))
        if self._prefix_cache is None:
            return jax.jit(
                partial(impl, **statics),
                in_shardings=(p_shard, *cache_shards, *scalar_ops),
                out_shardings=(*cache_shards, rep),
                donate_argnums=donate,
            )
        from .decode import prefix_cache_shardings

        pfx_shard = prefix_cache_shardings(self.mesh, self._prefix_cache)
        placed_prefix = jax.device_put(self._prefix_cache, pfx_shard)

        def _with_prefix(*args):
            *operands, prefix = args
            return impl(*operands, prefix_cache=prefix, **statics)

        fn = jax.jit(
            _with_prefix,
            in_shardings=(p_shard, *cache_shards, *scalar_ops, pfx_shard),
            out_shardings=(*cache_shards, rep),
            donate_argnums=donate,
        )
        return lambda *operands: fn(*operands, placed_prefix)

    def _make_decode_step(self):
        if self.quantized_kv:
            if self.family == "llama":
                from .llama import llama_quantized_decode_step as step_fn
            else:
                from .decode import quantized_decode_step as step_fn
        elif self.family == "llama":
            from .llama import llama_decode_step as step_fn
        else:
            from .decode import decode_step as step_fn

        config = self.config
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p

        # donate the cache: self.cache is reassigned from the result every
        # call, so the multi-layer KV buffers are reused in place instead
        # of copied per generated token (same as compile_serving_fns)
        def step(params, cache, tokens, key):
            logits, cache = step_fn(params, cache, tokens, config)
            return cache, _pick(logits, key, temperature, top_k, top_p)

        if self.mesh is None:
            return jax.jit(step, donate_argnums=(1,))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        return jax.jit(
            step,
            in_shardings=(param_shardings(self.mesh, self.params),
                          self._cache_shard, self._rows_shard, rep),
            out_shardings=(self._cache_shard, self._rows_shard),
            donate_argnums=(1,),
        )

    def _make_spec_insert(self):
        statics = dict(
            config=self.config, prompt_len=self.prompt_len,
            draft_layers=self.draft_layers,
            family=self.family, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            quantized_kv=self.quantized_kv,
            prefix_len=self.prefix_len,
        )
        if self.mesh is None:
            return lambda params, cache, dcache, row, prompt, length, key: (
                _spec_insert_row(params, cache, dcache, row, prompt,
                                 length, key,
                                 prefix_cache=self._prefix_cache,
                                 **statics)
            )
        return self._mesh_insert_jit(
            _spec_insert_row_impl, statics,
            (self._cache_shard, self._draft_cache_shard),
        )

    def _make_spec_round(self):
        """One compiled draft-and-verify round over ALL slots: k draft
        steps + one extra draft consume + one (k+1)-wide target chunk
        verify, per-row acceptance, per-row length advance gated by the
        ``active`` mask (inactive slots neither emit nor advance — their
        chunk writes land in slots their unchanged length keeps masked,
        the same compute-always discipline as the plain decode step).
        Exactly :func:`.speculative.speculative_generate`'s round body,
        re-hosted in the slot machine: greedy rounds emit what plain
        greedy decode would, sampled rounds apply the Leviathan/Chen
        acceptance rule so every emitted token is an exact warped-target
        sample."""
        from .speculative import _accept_and_fixup, _family_ops, _warp

        _, t_step, t_chunk, _ = _family_ops(self.config, self.quantized_kv)
        _, d_step, _, _ = _family_ops(self.draft_config, self.quantized_kv)
        k = self.draft_tokens
        config, dconfig = self.config, self.draft_config
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        sampled = temperature > 0.0

        def round_fn(params_t, params_d, t_cache, d_cache, pending,
                     active, key):
            if sampled:
                keys = jax.random.split(key, k + 1)
                accept_key, draft_keys = keys[0], keys[1:]
            proposals, draft_warped = [], []
            token = pending
            dc = d_cache
            for i in range(k):  # k is small and static — unrolled
                logits, dc = d_step(params_d, dc, token, dconfig)
                if sampled:
                    warped = _warp(logits, temperature, top_k, top_p)
                    draft_warped.append(warped)
                    token = jax.random.categorical(
                        draft_keys[i], warped
                    ).astype(jnp.int32)
                else:
                    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                proposals.append(token)
            drafts = jnp.stack(proposals, axis=1)  # [B, k]
            # extra consume of d_k: the draft cache holds every accepted
            # input even on full acceptance (masked otherwise)
            _, dc = d_step(params_d, dc, drafts[:, -1], dconfig)

            chunk = jnp.concatenate([pending[:, None], drafts], axis=1)
            t_len = t_cache["length"]
            d_len = d_cache["length"]
            logits, t_adv = t_chunk(params_t, t_cache, chunk, config)

            if sampled:
                n, bonus = _accept_and_fixup(
                    accept_key, drafts, jnp.stack(draft_warped, axis=1),
                    _warp(logits, temperature, top_k, top_p),
                )
            else:
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                matches = (drafts == greedy[:, :k]).astype(jnp.int32)
                n = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
                bonus = jnp.take_along_axis(
                    greedy, n[:, None], axis=1
                )[:, 0]

            j = jnp.arange(k + 1)[None, :]
            round_tokens = jnp.where(
                j < n[:, None],
                jnp.pad(drafts, ((0, 0), (0, 1))),
                bonus[:, None],
            )
            advance = jnp.where(active, n + 1, 0)
            t_cache = dict(t_adv, length=t_len + advance)
            d_cache = dict(dc, length=d_len + advance)
            pending_next = jnp.where(active, bonus, pending)
            return (t_cache, d_cache, pending_next, round_tokens,
                    jnp.where(active, n, 0))

        if self.mesh is None:
            return jax.jit(round_fn, donate_argnums=(2, 3))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        p_shard_d = dict(
            p_shard, layers=p_shard["layers"][:self.draft_layers]
        )
        rows_2d = NamedSharding(self.mesh, P("data", None))
        return jax.jit(
            round_fn,
            in_shardings=(p_shard, p_shard_d, self._cache_shard,
                          self._draft_cache_shard, self._rows_shard,
                          self._rows_shard, rep),
            out_shardings=(self._cache_shard, self._draft_cache_shard,
                           self._rows_shard, rows_2d, self._rows_shard),
            donate_argnums=(2, 3),
        )

    def _make_beam_insert(self):
        statics = dict(
            config=self.config, prompt_len=self.prompt_len,
            beams=self.beams, family=self.family,
            quantized_kv=self.quantized_kv,
            prefix_len=self.prefix_len, eos_id=self.eos_id,
        )
        if self.mesh is None:
            return lambda params, cache, scores, out, alive, emitted, \
                    current, row, prompt, length: (
                _beam_insert_row(params, cache, scores, out, alive,
                                 emitted, current, row, prompt, length,
                                 prefix_cache=self._prefix_cache,
                                 **statics)
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        out_shard = NamedSharding(self.mesh, P("data", None, None))
        state_in = (self._slot_shard, out_shard, self._slot_shard,
                    self._slot_shard, self._rows_shard)
        if self._prefix_cache is None:
            # cache + beam state donated, like the single-chip insert:
            # every operand in (1..6) comes back as an output the caller
            # rebinds, so the sharded buffers are reused in place
            return jax.jit(
                partial(_beam_insert_row_impl, **statics),
                in_shardings=(p_shard, self._cache_shard, *state_in,
                              rep, rep, rep),
                out_shardings=(self._cache_shard, *state_in),
                donate_argnums=(1, 2, 3, 4, 5, 6),
            )
        from .decode import prefix_cache_shardings

        pfx_shard = prefix_cache_shardings(self.mesh, self._prefix_cache)
        placed_prefix = jax.device_put(self._prefix_cache, pfx_shard)

        def _ins(params, cache, scores, out, alive, emitted, current,
                 row, prompt, length, prefix):
            return _beam_insert_row_impl(
                params, cache, scores, out, alive, emitted, current, row,
                prompt, length, prefix_cache=prefix, **statics)

        fn = jax.jit(
            _ins,
            in_shardings=(p_shard, self._cache_shard, *state_in, rep,
                          rep, rep, pfx_shard),
            out_shardings=(self._cache_shard, *state_in),
            donate_argnums=(1, 2, 3, 4, 5, 6),
        )
        return lambda *operands: fn(*operands, placed_prefix)

    def _make_beam_step(self):
        """One compiled beam step over ALL slots: advance every beam row
        one position, per-slot top-k over the ``W*V`` expansions with
        frozen-beam handling, in-block parent gathers of cache and
        state — the standalone :func:`.beam.beam_search` scan body,
        re-hosted with an ``active`` mask so free/finished slots neither
        reorder nor emit (the same compute-always discipline as the
        plain and speculative steps)."""
        if self.quantized_kv:
            if self.family == "llama":
                from .llama import llama_quantized_decode_step as step_fn
            else:
                from .decode import quantized_decode_step as step_fn
        elif self.family == "llama":
            from .llama import llama_decode_step as step_fn
        else:
            from .decode import decode_step as step_fn

        config = self.config
        eos_id = self.eos_id
        W = self.beams

        def bstep(params, cache, current, scores, out, alive, emitted,
                  active):
            lengths_in = cache["length"]  # pre-step, for inactive freeze
            logits, cache = step_fn(params, cache, current, config)
            S = scores.shape[0]
            vocab = logits.shape[-1]
            logp = jax.nn.log_softmax(logits, axis=-1).reshape(S, W, vocab)
            if eos_id is not None:
                # a finished beam contributes exactly one continuation —
                # its frozen self emitting eos at no score cost
                frozen = jnp.full((S, W, vocab), -jnp.inf)
                frozen = frozen.at[:, :, eos_id].set(0.0)
                logp = jnp.where(alive[..., None], logp, frozen)
            total = scores[..., None] + logp
            flat_scores, flat_idx = jax.lax.top_k(
                total.reshape(S, W * vocab), W
            )
            parent = flat_idx // vocab
            token = (flat_idx % vocab).astype(jnp.int32)
            # inactive slots: identity parents, no writes, no advance
            act = active[:, None]
            parent = jnp.where(act, parent, jnp.arange(W)[None, :])
            rows = jnp.arange(S)
            flat_parent = (rows[:, None] * W + parent).reshape(-1)
            cache = jax.tree.map(lambda a: a[flat_parent], cache)
            # Gate the length-pointer advance by the active mask, the way
            # the speculative round does (advance = where(active, n+1, 0)):
            # free/finished slots keep their pointer frozen instead of
            # marching toward max_seq_len and leaning on the scatter's
            # out-of-bounds clamp + the attention mask.  (Their identity
            # parent gather kept their own advanced length, so restoring
            # the pre-step value is exact.)
            cache = dict(
                cache,
                length=jnp.where(
                    jnp.repeat(active, W), cache["length"], lengths_in
                ),
            )
            out_g = out[rows[:, None], parent]
            alive_g = alive[rows[:, None], parent]
            emitted_g = emitted[rows[:, None], parent]
            write = jnp.where(
                alive_g, token,
                eos_id if eos_id is not None else token,
            )
            budget = out.shape[-1]
            out_w = jax.vmap(
                jax.vmap(lambda r, t, v: r.at[t].set(v))
            )(out_g, jnp.minimum(emitted_g, budget - 1), write)
            out = jnp.where(act[..., None], out_w, out)
            emitted = jnp.where(
                act, emitted_g + alive_g.astype(jnp.int32), emitted
            )
            new_alive = (
                alive_g & (token != eos_id) if eos_id is not None
                else alive_g
            )
            alive = jnp.where(act, new_alive, alive)
            scores = jnp.where(act, flat_scores, scores)
            current = jnp.where(
                act, token, current.reshape(S, W)
            ).reshape(-1)
            return (cache, current, scores, out, alive, emitted,
                    jnp.any(alive, axis=1))

        if self.mesh is None:
            return jax.jit(bstep, donate_argnums=(1,))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        p_shard = param_shardings(self.mesh, self.params)
        out_shard = NamedSharding(self.mesh, P("data", None, None))
        slot_1d = NamedSharding(self.mesh, P("data"))
        return jax.jit(
            bstep,
            in_shardings=(p_shard, self._cache_shard, self._rows_shard,
                          self._slot_shard, out_shard, self._slot_shard,
                          self._slot_shard, slot_1d),
            out_shardings=(self._cache_shard, self._rows_shard,
                           self._slot_shard, out_shard, self._slot_shard,
                           self._slot_shard, slot_1d),
            donate_argnums=(1,),
        )

    def _beam_best(self, row: int) -> np.ndarray:
        """The finished slot's best beam, ranked exactly like
        :func:`.beam.beam_search` (GNMT length normalization when
        ``length_penalty > 0``; ties resolve to the lowest beam index,
        matching the standalone's stable descending sort)."""
        out = np.asarray(self._beam_out[row])
        scores = np.asarray(self._beam_scores[row])
        if self.length_penalty > 0:
            # float32 throughout, matching the standalone's ranking math
            # bit for bit (a float64 norm could flip ties)
            emitted = np.asarray(self._beam_emitted[row]).astype(
                np.float32
            )
            norm = (
                (np.float32(5.0) + emitted) / np.float32(6.0)
            ) ** np.float32(self.length_penalty)
            ranked = scores / norm
        else:
            ranked = scores
        return out[int(np.argmax(ranked))].astype(np.int32)

    def _step_beam(self) -> list[tuple[Any, np.ndarray]]:
        finished = []
        needs = [
            s.busy and not s.done and s.rounds < s.budget - 1
            for s in self.slots
        ]
        if any(needs):
            active = jnp.asarray(needs)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                active = jax.device_put(
                    active, NamedSharding(self.mesh, P("data"))
                )
            (self.cache, self._current, self._beam_scores,
             self._beam_out, self._beam_alive, self._beam_emitted,
             alive_any) = self._beam_step_fn(
                self.params, self.cache, self._current,
                self._beam_scores, self._beam_out, self._beam_alive,
                self._beam_emitted, active,
            )
            alive_host = np.asarray(alive_any)
            for row, slot in enumerate(self.slots):
                if needs[row]:
                    slot.rounds += 1
                    if not alive_host[row]:
                        # every beam frozen: further steps are no-ops
                        # (frozen beams emit eos at unchanged scores),
                        # so the result is already final
                        slot.done = True
        for row, slot in enumerate(self.slots):
            if slot.busy and (slot.done or slot.rounds >= slot.budget - 1):
                finished.append((slot.payload, self._beam_best(row)))
                self.slots[row] = _Slot()
        return finished

    @property
    def free_slots(self) -> list[int]:
        return [i for i, s in enumerate(self.slots) if not s.busy]

    @property
    def active(self) -> int:
        return sum(s.busy for s in self.slots)

    def submit(self, token_ids: np.ndarray, payload: Any = None) -> int:
        """Prefill one request into a free slot; returns the slot index.

        ``token_ids`` is truncated/right-padded to the batcher's static
        ``prompt_len`` bucket (empty prompts count one pad token).
        """
        free = self.free_slots
        if not free:
            raise RuntimeError("no free slot; call step() until one opens")
        row = free[0]
        ids = np.zeros((self.prompt_len,), np.int32)
        real = np.asarray(token_ids, np.int32).reshape(-1)[: self.prompt_len]
        ids[: real.size] = real
        length = max(1, real.size)
        if self.beams > 1:
            (self.cache, self._beam_scores, self._beam_out,
             self._beam_alive, self._beam_emitted,
             self._current) = self._insert(
                self.params, self.cache, self._beam_scores,
                self._beam_out, self._beam_alive, self._beam_emitted,
                self._current, jnp.asarray(row, jnp.int32),
                jnp.asarray(ids), jnp.asarray(length, jnp.int32),
            )
            # rounds counts beam steps taken; a budget-1 slot finishes
            # without any (the insert's first expansion is the answer)
            self.slots[row] = _Slot(
                busy=True, budget=self.generate_tokens, payload=payload,
            )
            return row
        if self.draft_layers:
            self.cache, self.draft_cache, first = self._insert(
                self.params, self.cache, self.draft_cache,
                jnp.asarray(row, jnp.int32), jnp.asarray(ids),
                jnp.asarray(length, jnp.int32), next(self._keys),
            )
        else:
            self.cache, first = self._insert(
                self.params, self.cache, jnp.asarray(row, jnp.int32),
                jnp.asarray(ids), jnp.asarray(length, jnp.int32),
                next(self._keys),
            )
        first = int(first)
        self._current = self._current.at[row].set(first)
        # a fresh record per request: step() replaces finished slots with
        # new _Slot()s, but resetting here keeps the per-request
        # rounds/accepted contract independent of that cleanup path
        slot = _Slot(
            busy=True, produced=[first], budget=self.generate_tokens,
            done=self.eos_id is not None and first == self.eos_id,
            payload=payload,
        )
        self.slots[row] = slot
        return row

    def _needs_decode(self, slot: _Slot) -> bool:
        return slot.busy and not slot.done and len(slot.produced) < slot.budget

    def step(self) -> list[tuple[Any, np.ndarray]]:
        """Advance every active slot; return finished requests as
        ``(payload, continuation_tokens)`` pairs (their slots are free
        again on return).  Plain slots advance ONE token per step;
        speculative slots (``draft_layers > 0``) advance 1..k+1 tokens —
        one draft-and-verify round.  Finished = budget reached or eos
        emitted; either way the tokens are padded with ``eos_id`` to the
        budget (matching ``generate``'s post-eos padding).  No-op when
        nothing is active."""
        if self.active == 0:
            return []
        if self.beams > 1:
            return self._step_beam()
        finished = []
        needs = [self._needs_decode(s) for s in self.slots]
        # rows whose budget is a single token (or that already hit eos)
        # never need a decode step
        if self.draft_layers and any(needs):
            active = jnp.asarray(needs)
            if self.mesh is not None:
                active = jax.device_put(active, self._rows_shard)
            (self.cache, self.draft_cache, self._current, round_tokens,
             n) = self._spec(
                self.params, self.draft_params, self.cache,
                self.draft_cache, self._current, active, next(self._keys),
            )
            toks_host = np.asarray(round_tokens)
            n_host = np.asarray(n)
            for row, slot in enumerate(self.slots):
                if not needs[row]:
                    continue
                slot.rounds += 1
                slot.accepted += int(n_host[row])
                self.spec_rounds += 1
                self.spec_accepted += int(n_host[row])
                for token in toks_host[row, : int(n_host[row]) + 1]:
                    if slot.done or len(slot.produced) >= slot.budget:
                        break
                    token = int(token)
                    slot.produced.append(token)
                    if self.eos_id is not None and token == self.eos_id:
                        slot.done = True
        elif any(needs):
            self.cache, nxt = self._decode(
                self.params, self.cache, self._current, next(self._keys)
            )
            nxt_host = np.asarray(nxt)
            for row, slot in enumerate(self.slots):
                if needs[row]:
                    token = int(nxt_host[row])
                    slot.produced.append(token)
                    if self.eos_id is not None and token == self.eos_id:
                        slot.done = True
            self._current = nxt
        for row, slot in enumerate(self.slots):
            if slot.busy and (slot.done or len(slot.produced) >= slot.budget):
                tokens = slot.produced
                if len(tokens) < slot.budget:
                    # eos fired early: the slot frees NOW; pad the reply
                    # to the static budget exactly like generate does
                    tokens = tokens + [self.eos_id] * (
                        slot.budget - len(tokens)
                    )
                finished.append(
                    (slot.payload, np.asarray(tokens, np.int32))
                )
                self.slots[row] = _Slot()
        return finished


class ContinuousWorker:
    """A queue-draining worker built on :class:`ContinuousBatcher`.

    Same at-least-once contract as :class:`.service.QueueWorker`: a
    message is deleted only after its continuation is fully generated.
    Unlike the batch worker, a slow batch never blocks fresh messages —
    slots refill the moment they finish (and an ``eos_id`` frees a slot
    early).  Full reply parity with the batch worker: ``tokenizer``
    turns it text-in/text-out, ``result_queue`` +
    ``ServiceConfig.result_queue_url`` publish one JSON reply per
    message ({"tokens": [...]} trimmed at eos, + {"text": ...} with a
    tokenizer, + the request's MessageId as "request_id").
    """

    def __init__(
        self,
        queue,
        params: Any,
        model_config: Any,
        service_config,
        *,
        family: str = "gpt",
        tokenizer=None,
        result_queue=None,
        mesh=None,
        prefix_cache: dict | None = None,
        draft_layers: int = 0,
        draft_tokens: int = 4,
        beams: int = 1,
        length_penalty: float = 0.0,
    ) -> None:
        if service_config.generate_tokens < 1:
            raise ValueError(
                "ContinuousWorker is generate-mode serving; set "
                "ServiceConfig.generate_tokens >= 1"
            )
        if service_config.result_queue_url and result_queue is None:
            # same explicit-client rule as QueueWorker: in-memory queues
            # ignore urls, so defaulting replies onto the input queue
            # object would self-feed
            raise ValueError(
                "result_queue_url is set but no result_queue client was "
                "given"
            )
        self.queue = queue
        self.config = service_config
        self.tokenizer = tokenizer
        self.result_queue = result_queue
        self.batcher = ContinuousBatcher(
            params, model_config,
            batch_size=service_config.batch_size,
            prompt_len=service_config.seq_len,
            generate_tokens=service_config.generate_tokens,
            family=family,
            temperature=service_config.temperature,
            top_k=service_config.top_k,
            top_p=service_config.top_p,
            eos_id=service_config.eos_id,
            sample_seed=service_config.sample_seed,
            mesh=mesh,
            quantized_kv=service_config.quantized_kv,
            prefix_cache=prefix_cache,
            draft_layers=draft_layers,
            draft_tokens=draft_tokens,
            beams=beams,
            length_penalty=length_penalty,
        )
        self.processed = 0
        # wall-clock engine-cycle spans (same metrics surface as
        # QueueWorker: obs attaches this to /metrics)
        from ..utils.profiling import SpanTimer

        self.timer = SpanTimer()
        self._stop = None  # lazily a threading.Event in run_forever
        self._poll_backoff = 0

    # poll throttle: after an EMPTY zero-wait receive while slots are
    # still decoding, skip this many cycles before polling again — one
    # billed ReceiveMessage per generated token would be absurd on SQS
    POLL_BACKOFF_CYCLES = 16

    def _settle(self, message, tokens: np.ndarray | None) -> None:
        """Reply (when configured) and delete one finished message.
        ``tokens=None`` marks a malformed body: error reply, no result."""
        import json

        from .service import build_token_reply, request_id

        if self.config.result_queue_url:
            if tokens is None:
                payload = {"error": "malformed body"}
            else:
                payload = build_token_reply(
                    tokens, self.config.eos_id, self.tokenizer
                )
            payload["request_id"] = request_id(message)
            # reply BEFORE deleting the input (at-least-once: consumers
            # may see duplicates, never lose a result)
            self.result_queue.send_message(
                self.config.result_queue_url, json.dumps(payload)
            )
        self.queue.delete_message(
            self.config.queue_url, message["ReceiptHandle"]
        )

    def _refill(self) -> int:
        """Pull up to free-slot-count messages and prefill them in."""
        from .service import parse_request_body

        free = len(self.batcher.free_slots)
        if not free:
            return 0
        if self._poll_backoff > 0:
            self._poll_backoff -= 1
            return 0
        messages = self.queue.receive_messages(
            self.config.queue_url, max_messages=free,
            wait_time_s=0 if self.batcher.active else
            self.config.receive_wait_s,
        )
        if not messages and self.batcher.active:
            self._poll_backoff = self.POLL_BACKOFF_CYCLES
        for message in messages:
            ids = parse_request_body(message["Body"], self.tokenizer)
            if ids is None:
                # poison messages are consumed (with an error reply when
                # replies are on), not redelivered forever — and not
                # counted as processed work
                self._settle(message, None)
                continue
            self.batcher.submit(ids, payload=message)
        return len(messages)

    def run_once(self) -> int:
        """One engine cycle: refill free slots, advance one token, settle
        finished requests.  Returns messages completed this cycle."""
        self._refill()
        done = self.batcher.step()
        for message, tokens in done:
            self._settle(message, tokens)
        if done:
            self._poll_backoff = 0  # a slot just freed: poll right away
        self.processed += len(done)
        return len(done)

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()

    def run_forever(self) -> None:
        """Serve until :meth:`stop` — same never-dies guarantee as
        :meth:`.service.QueueWorker.run_forever`: a transient queue or
        compute error logs, backs off, and retries (unfinished slots stay
        in flight; their messages reappear after the visibility timeout
        if the process dies)."""
        import threading

        if self._stop is None:
            self._stop = threading.Event()
        while not self._stop.is_set():
            try:
                with self.timer.span("cycle"):
                    idle = self.run_once() == 0 and self.batcher.active == 0
            except Exception as err:
                log.error("Continuous worker cycle failed: %s", err)
                self._stop.wait(self.config.error_backoff_s)
                continue
            if idle:
                self._stop.wait(self.config.idle_sleep_s)

    def drain(self, total: int, max_cycles: int | None = None) -> int:
        """Run cycles until ``total`` messages complete (or the cycle
        budget runs out); returns the number completed."""
        cycles = 0
        while self.processed < total:
            if max_cycles is not None and cycles >= max_cycles:
                break
            cycles += 1
            with self.timer.span("cycle"):
                done = self.run_once()
            if done == 0 and self.batcher.active == 0:
                # the cycle's own refill got nothing and nothing is in
                # flight: the queue is drained
                break
        return self.processed
