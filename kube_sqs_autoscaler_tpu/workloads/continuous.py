"""Continuous batching: rolling decode slots that refill independently.

The batch-generate worker (:mod:`.service` in generate mode) decodes a
whole batch to completion before touching the queue again — one long
prompt or one unlucky batch blocks every other message (head-of-line
blocking).  Real LM serving keeps a *rolling* batch instead: every row of
the KV cache is an independent slot; each engine step advances all active
slots by one token, finished slots emit their continuation immediately,
and new requests are prefilled **into** a free slot while the others keep
decoding.  The per-row cache machinery from :mod:`.decode` (per-row
``length``, per-row write positions, per-row masks) is exactly what makes
this work — and the llama family's compact GQA cache
(:func:`.llama.init_llama_cache`) has the same per-row shape, so both
families serve through one slot machine.

TPU shape discipline: there are only two compiled programs —

- the decode program: at ``decode_block == 1`` one ``decode_step`` that
  advances all ``batch`` slots one position, active or not (inactive
  rows compute garbage that is never read — lockstep static shapes beat
  dynamic batch reshapes); at ``decode_block > 1`` a
  :func:`.decode.block_decode` scan that advances every live slot up to
  ``decode_block`` tokens per device call with on-device per-row
  liveness masks, double-buffered so the host settles/refills cycle N
  while block N+1 is already running;
- ``insert``: prefill a refill cycle's prompts (each padded to a fixed
  bucket) as ONE ``[M, P]`` batch and ``dynamic_update_slice`` their
  layer caches into the slots' rows, folding the per-row lengths,
  pending tokens, and liveness masks into the returned state — no
  per-request device ops, no host sync (first tokens settle in one
  deferred transfer).

Sampling is :func:`.decode._pick` — the one policy every decode path
shares (greedy at temperature 0, else temperature/top-k/top-p), keyed
per engine step from :func:`.service.sampling_keys`.  ``eos_id`` frees a
slot the moment it fires (the continuous-batching win: the row's cache
becomes a fresh slot while its batchmates keep decoding); outputs are
padded with ``eos_id`` to the token budget, exactly like
:func:`.decode.generate`'s post-eos padding, so the greedy
outputs-equal-per-request invariant holds verbatim.

The reference has no serving at all (SURVEY.md §2); this is the TPU-shop
shape of the queue-consumer its README deploys.
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .decode import _pick, init_cache, prefill

log = logging.getLogger(__name__)


def _insert_rows_impl(
    params: dict,
    cache: dict,
    current: jax.Array,
    done: jax.Array,
    remaining: jax.Array,
    rows: jax.Array,
    prompts: jax.Array,
    lengths: jax.Array,
    key: jax.Array | None,
    config: Any,
    prompt_len: int,
    n_rows: int,
    budget: int,
    family: str = "gpt",
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized_kv: bool = False,
    prefix_len: int = 0,
    eos_id: int | None = None,
    prefix_cache: dict | None = None,
    budgets: jax.Array | None = None,
) -> tuple[dict, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Batched admission: prefill ``n_rows`` prompts (int32
    ``[n_rows, prompt_len]``, right-padded to the static bucket) as ONE
    batch and splice each into its slot row of ``cache``.

    The whole refill cycle is one device call: per-row lengths, the
    pending next-token state (``current``), and the block-decode
    liveness masks (``done`` cleared — or set where the first token IS
    ``eos_id`` — and ``remaining`` re-armed to ``budget - 1``; the first
    token spends one) all fold into the returned state, so admission
    costs no per-request device ops and no host sync at all — the first
    tokens come back as a device ``[n_rows]`` array the caller consumes
    in one deferred transfer.

    ``family`` picks the prefill: the gpt path or the llama GQA path —
    the splice is layout-agnostic (every cache entry puts the batch row
    on axis 0 and the POSITION on axis 2: ``[B, H, S, D]`` codes/values
    and ``[B, H, S]`` scales alike, so one axis-2 slice serves both the
    bf16 and the int8 layouts).

    ``prefix_len > 0`` (with ``prefix_cache``): the prompts are SUFFIXES
    continuing from a shared prefix — the prefill runs through
    ``prefill_with_prefix``, only the suffix region ``[prefix_len,
    prefix_len + prompt_len)`` is spliced (the batch cache's rows
    already hold the broadcast prefix, which slot reuse never
    overwrites — decode writes at ``length >= prefix_len``), and each
    slot's length starts past the prefix.

    ``budgets`` (int32 ``[n_rows]``, optional) overrides the static
    ``budget - 1`` re-arm with per-row remaining budgets — the
    evacuation/resume path admits rows mid-request, each with whatever
    budget its first life left unspent.
    """
    logits, rows_cache = _rows_prefill(
        params, prompts, lengths, config, family, quantized_kv, prefix_len,
        prefix_cache,
    )
    new_layers = _splice_rows_layers(cache, rows_cache, rows, prefix_len,
                                     prompt_len, n_rows)
    full_lengths = cache["length"].at[rows].set(prefix_len + lengths)
    firsts = _pick(logits, key, temperature, top_k, top_p)
    current = current.at[rows].set(firsts)
    first_done = (
        firsts == eos_id if eos_id is not None
        else jnp.zeros((n_rows,), bool)
    )
    done = done.at[rows].set(first_done)
    remaining = remaining.at[rows].set(
        budgets if budgets is not None else budget - 1
    )
    return (
        {"layers": new_layers, "length": full_lengths},
        current, done, remaining, firsts,
    )


def _family_chunk_fn(family: str, quantized_kv: bool):
    """The family/layout chunk decoder the pooled insert continues
    suffixes through (the same pick :func:`_rows_prefill` makes for the
    broadcast-prefix path, minus the broadcast)."""
    if quantized_kv:
        if family == "llama":
            from .llama import llama_quantized_chunk_decode as fn
        else:
            from .decode import quantized_chunk_decode as fn
    elif family == "llama":
        from .llama import llama_chunk_decode as fn
    else:
        from .decode import chunk_decode as fn
    return fn


def _insert_rows_pooled_impl(
    params: dict,
    cache: dict,
    current: jax.Array,
    done: jax.Array,
    remaining: jax.Array,
    rows: jax.Array,
    prompts: jax.Array,
    lengths: jax.Array,
    key: jax.Array | None,
    entry_idx: jax.Array,
    pool_layers: list,
    config: Any,
    prompt_len: int,
    n_rows: int,
    budget: int,
    family: str = "gpt",
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized_kv: bool = False,
    pool_prefix_len: int = 0,
    eos_id: int | None = None,
) -> tuple[dict, jax.Array, jax.Array, jax.Array, jax.Array]:
    """:func:`_insert_rows_impl` for the per-tenant prefix-cache pool:
    each row's shared-prefix KV is GATHERED from the pool's stacked
    device rows by ``entry_idx`` (int32 ``[n_rows]``) instead of
    re-prefilled — the prefix forward was paid once at
    :meth:`~.tenancy.PrefixPool.acquire` install time and every request
    that reuses the entry skips it entirely.  The suffix prompts run
    one chunk-decode forward continuing from the gathered per-row
    prefixes (the multi-prefix generalization of
    :func:`~.decode.prefill_with_prefix`, which broadcasts a single
    batch-1 prefix), then prefix + suffix splice into the slot rows and
    the per-row lengths / pending tokens / liveness masks fold in
    exactly as the plain insert folds them.  Still ONE device call and
    ZERO host syncs per refill cycle, whatever mix of tenants the batch
    carries."""
    gathered = [
        {name: buf[entry_idx] for name, buf in layer.items()}
        for layer in pool_layers
    ]
    prefix_rows = {
        "layers": gathered,
        "length": jnp.full((n_rows,), pool_prefix_len, jnp.int32),
    }
    chunk_fn = _family_chunk_fn(family, quantized_kv)
    logits_all, rows_cache = chunk_fn(params, prefix_rows, prompts, config)
    logits = logits_all[jnp.arange(n_rows), lengths.astype(jnp.int32) - 1]
    new_layers = _splice_rows_layers(
        cache, rows_cache, rows, 0, pool_prefix_len + prompt_len, n_rows
    )
    full_lengths = cache["length"].at[rows].set(pool_prefix_len + lengths)
    firsts = _pick(logits, key, temperature, top_k, top_p)
    current = current.at[rows].set(firsts)
    first_done = (
        firsts == eos_id if eos_id is not None
        else jnp.zeros((n_rows,), bool)
    )
    done = done.at[rows].set(first_done)
    remaining = remaining.at[rows].set(budget - 1)
    return (
        {"layers": new_layers, "length": full_lengths},
        current, done, remaining, firsts,
    )


# the shared tenant-label cardinality bound (see workloads/service.py:
# the jax-free fleet pool applies the same bound to its retired fold)
from .service import (  # noqa: E402
    MAX_TENANT_SERIES,
    OTHER_TENANTS,
    bounded_tenant_key as _bounded_tenant_key,
    request_id as _request_id,
)
from ..obs.lifecycle import request_key as _trace_key  # noqa: E402


def _rows_prefill(params, prompts, lengths, config, family, quantized_kv,
                  prefix_len, prefix_cache):
    """``M`` prompts' prefill as one ``[M, P]`` batch through the
    family's layout variant; returns ``(logits [M, V], rows_cache)``.
    Rows never interact across the batch axis, so the results are
    bitwise what ``M`` separate ``[1, P]`` prefills would produce."""
    if prefix_len:
        if quantized_kv:
            if family == "llama":
                from .llama import (
                    llama_quantized_prefill_with_prefix as pf,
                )
            else:
                from .decode import quantized_prefill_with_prefix as pf
        elif family == "llama":
            from .llama import llama_prefill_with_prefix as pf
        else:
            from .decode import prefill_with_prefix as pf
        return pf(params, prefix_cache, prompts, config, lengths=lengths)
    if quantized_kv:
        if family == "llama":
            from .llama import llama_quantized_prefill as prefill_fn
        else:
            from .decode import quantized_prefill as prefill_fn
    elif family == "llama":
        from .llama import llama_prefill as prefill_fn
    else:
        prefill_fn = prefill
    return prefill_fn(params, prompts, config, lengths=lengths)


def _row_prefill(params, prompt, length, config, family, quantized_kv,
                 prefix_len, prefix_cache):
    """One prompt's prefill as a ``[1, P]`` batch (the ``M = 1`` case of
    :func:`_rows_prefill` — kept for the beam/speculative inserts, whose
    per-slot state is seeded one request at a time)."""
    return _rows_prefill(params, prompt[None], length[None], config, family,
                         quantized_kv, prefix_len, prefix_cache)


def _splice_rows_layers(cache, rows_cache, rows, prefix_len, prompt_len,
                        n_rows):
    """Splice each of ``n_rows`` prefilled rows' prompt positions into
    its slot row of the batch cache (the multi-row generalization of
    :func:`_splice_row_layers`: one ``dynamic_update_slice`` per row per
    entry, all inside the one compiled insert); returns the new layers
    list."""
    new_layers = []
    for layer_cache, rows_layer in zip(cache["layers"], rows_cache["layers"]):
        entry = {}
        for name, buf in layer_cache.items():
            # keep only the prompt positions (axis 2 for [M, H, S, D]
            # codes/values and [M, H, S] scales alike; under a prefix,
            # the suffix positions only)
            pieces = jax.lax.slice_in_dim(
                rows_layer[name], prefix_len, prefix_len + prompt_len, axis=2
            )
            for i in range(n_rows):
                start = (rows[i], 0, prefix_len) + (0,) * (buf.ndim - 3)
                buf = jax.lax.dynamic_update_slice(
                    buf, jax.lax.slice_in_dim(pieces, i, i + 1, axis=0),
                    start,
                )
            entry[name] = buf
        new_layers.append(entry)
    return new_layers


def _splice_row_layers(cache, row_cache, row, prefix_len, prompt_len,
                       beams: int = 1):
    """Splice a ``[1, ...]`` row cache's prompt positions into slot
    ``row`` of the batch cache; returns the new layers list.

    ``beams > 1``: the one prefilled row is repeated ``beams`` times and
    spliced into the slot's contiguous row block
    ``[row*beams, (row+1)*beams)`` — every beam of a fresh beam slot
    starts from the same prompt cache (``beams=1`` degenerates to the
    plain single-row splice)."""
    new_layers = []
    for layer_cache, row_layer in zip(cache["layers"], row_cache["layers"]):
        entry = {}
        for name, buf in layer_cache.items():
            piece = row_layer[name]
            # keep only the prompt positions: axis 2 for [1, H, S, D]
            # codes/values, axis 2 for [1, H, S] scales too (under a
            # prefix, the suffix positions only)
            piece = jax.lax.slice_in_dim(
                piece, prefix_len, prefix_len + prompt_len, axis=2
            )
            if beams > 1:
                piece = jnp.repeat(piece, beams, axis=0)
            start = (row * beams, 0, prefix_len) + (0,) * (buf.ndim - 3)
            entry[name] = jax.lax.dynamic_update_slice(buf, piece, start)
        new_layers.append(entry)
    return new_layers


def _spec_insert_row_impl(
    params: dict,
    cache: dict,
    draft_cache: dict,
    current: jax.Array,
    row: jax.Array,
    prompt: jax.Array,
    length: jax.Array,
    key: jax.Array | None,
    config: Any,
    prompt_len: int,
    draft_layers: int,
    family: str = "gpt",
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    quantized_kv: bool = False,
    prefix_len: int = 0,
    prefix_cache: dict | None = None,
) -> tuple[dict, dict, jax.Array, jax.Array]:
    """:func:`_insert_rows_impl` for speculative slots: ONE target
    prefill populates both caches — the early-exit self-draft is the
    target's first ``draft_layers`` layers, and layer ``i``'s k/v depend
    only on layers ``< i``, so the draft's row cache is literally the
    layer-wise prefix of the target's (same trick as
    :func:`.speculative.draft_prefix_from_target`).  The slot's pending
    token folds into the returned ``current`` like the plain and beam
    inserts — no per-submit device op or host sync."""
    logits, row_cache = _row_prefill(
        params, prompt, length, config, family, quantized_kv, prefix_len,
        prefix_cache,
    )
    new_layers = _splice_row_layers(cache, row_cache, row, prefix_len,
                                    prompt_len)
    draft_row = {"layers": row_cache["layers"][:draft_layers],
                 "length": row_cache["length"]}
    new_draft_layers = _splice_row_layers(draft_cache, draft_row, row,
                                          prefix_len, prompt_len)
    lengths = jax.lax.dynamic_update_index_in_dim(
        cache["length"], prefix_len + length, row, 0
    )
    draft_lengths = jax.lax.dynamic_update_index_in_dim(
        draft_cache["length"], prefix_len + length, row, 0
    )
    first = _pick(logits, key, temperature, top_k, top_p)[0]
    current = current.at[row].set(first)
    return (
        {"layers": new_layers, "length": lengths},
        {"layers": new_draft_layers, "length": draft_lengths},
        current,
        first,
    )


_insert_rows = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "n_rows", "budget", "family",
                     "temperature", "top_k", "top_p", "quantized_kv",
                     "prefix_len", "eos_id"),
    donate_argnums=(1, 2, 3, 4),
)(_insert_rows_impl)


_spec_insert_row = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "draft_layers", "family",
                     "temperature", "top_k", "top_p", "quantized_kv",
                     "prefix_len"),
    donate_argnums=(1, 2, 3),
)(_spec_insert_row_impl)


# the pool buffers ride as (undonated) operands: they are shared by
# every future insert — only the batcher's rolling state rolls in place
_insert_rows_pooled = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "n_rows", "budget", "family",
                     "temperature", "top_k", "top_p", "quantized_kv",
                     "pool_prefix_len", "eos_id"),
    donate_argnums=(1, 2, 3, 4),
)(_insert_rows_pooled_impl)


def _beam_insert_row_impl(
    params: dict,
    cache: dict,
    scores: jax.Array,
    out: jax.Array,
    alive: jax.Array,
    emitted: jax.Array,
    current: jax.Array,
    row: jax.Array,
    prompt: jax.Array,
    length: jax.Array,
    config: Any,
    prompt_len: int,
    beams: int,
    family: str = "gpt",
    quantized_kv: bool = False,
    prefix_len: int = 0,
    eos_id: int | None = None,
    prefix_cache: dict | None = None,
) -> tuple[dict, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """:func:`_insert_rows_impl` for beam slots: one prefill seeds the
    slot's ``beams`` cache rows and its device-side search state — the
    first expansion's top-``beams`` tokens become the beams' seeds
    (scores, first output column, alive mask), exactly the standalone
    :func:`.beam.beam_search` seeding re-hosted per slot."""
    logits, row_cache = _row_prefill(
        params, prompt, length, config, family, quantized_kv, prefix_len,
        prefix_cache,
    )
    new_layers = _splice_row_layers(cache, row_cache, row, prefix_len,
                                    prompt_len, beams=beams)
    lengths = jax.lax.dynamic_update_slice(
        cache["length"],
        jnp.full((beams,), prefix_len + length, jnp.int32),
        (row * beams,),
    )
    logp = jax.nn.log_softmax(logits[0].astype(jnp.float32))
    first_scores, first_tokens = jax.lax.top_k(logp, beams)
    first_tokens = first_tokens.astype(jnp.int32)
    out_row = jnp.full((beams, out.shape[-1]),
                       eos_id if eos_id is not None else 0, jnp.int32)
    out_row = out_row.at[:, 0].set(first_tokens)
    alive_row = (
        first_tokens != eos_id if eos_id is not None
        else jnp.ones((beams,), bool)
    )
    scores = jax.lax.dynamic_update_index_in_dim(scores, first_scores,
                                                 row, 0)
    out = jax.lax.dynamic_update_index_in_dim(out, out_row, row, 0)
    alive = jax.lax.dynamic_update_index_in_dim(alive, alive_row, row, 0)
    emitted = jax.lax.dynamic_update_index_in_dim(
        emitted, jnp.ones((beams,), jnp.int32), row, 0
    )
    current = jax.lax.dynamic_update_slice(current, first_tokens,
                                           (row * beams,))
    return ({"layers": new_layers, "length": lengths}, scores, out,
            alive, emitted, current)


# Donate the KV cache AND the five beam-state operands (scores, out,
# alive, emitted, current): all six are returned updated and immediately
# rebound by the caller, so XLA reuses their buffers in place instead of
# copying the whole search state per insert.
_beam_insert_row = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "beams", "family",
                     "quantized_kv", "prefix_len", "eos_id"),
    donate_argnums=(1, 2, 3, 4, 5, 6),
)(_beam_insert_row_impl)


@dataclass
class _Slot:
    busy: bool = False
    produced: list = field(default_factory=list)
    budget: int = 0
    done: bool = False  # hit eos before the budget (frees this step)
    payload: Any = None  # caller's per-request context (receipt handle...)
    # speculative slots: per-request verify rounds and accepted drafts
    # (the serving-side signal for tuning draft_tokens / draft_layers)
    rounds: int = 0
    accepted: int = 0
    # admission wall-clock, for the time-to-first-token gauge
    submitted_at: float = 0.0
    # multi-tenant serving: the admitting tenant's label ("" = tenancy
    # off — the per-tenant attribution below is skipped entirely), and
    # the request's QUEUE arrival time (epoch seconds from its
    # SentTimestamp).  Per-tenant TTFT counts from arrival, not from
    # admission: the queue/staging wait is exactly where a flooding
    # tenant starves its victims, so an admission-based TTFT would
    # define the isolation problem away.
    tenant: str = ""
    arrived_at: float | None = None
    # TTFT already recorded (set at the first settle; pre-set on
    # evacuated/resumed rows so a request's TTFT is measured once, at
    # its FIRST first token, never again on a later shard)
    ttft_done: bool = False
    # overload ladder tier 1: the slot's budget was cut below the
    # engine's static generate_tokens, so the device row outlives the
    # host's completion — _finish_ready quiesces it (see _quiesce_rows)
    degraded: bool = False
    # decode-phase deadline (tenancy.decode_slo_s > 0): armed at the
    # first produced token to first-token time + slo x remaining
    # budget; a slot still decoding past it is shed mid-decode with an
    # explicit error reply (reason="decode_deadline").  None = unarmed.
    decode_deadline_at: float | None = None


class ContinuousBatcher:
    """The slot machine: submit prompts, step the batch, collect results.

    Queue-agnostic and synchronous — drive it from anything that produces
    ``(token_ids, payload)`` requests.  Both model families (``family`` —
    the llama GQA cache is per-row just like the gpt one), greedy or
    sampled decoding (``temperature``/``top_k``/``top_p`` through the
    shared ``_pick`` policy, keyed per engine step), ``eos_id``
    termination per slot.  Greedy outputs are exactly what
    :func:`.decode.generate` / :func:`.llama.llama_generate` produce for
    each prompt alone, eos padding included (pinned by test): continuous
    batching changes *scheduling*, never results.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        batch_size: int,
        prompt_len: int,
        generate_tokens: int,
        *,
        family: str = "gpt",
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int | None = None,
        sample_seed: int = 0,
        mesh=None,
        quantized_kv: bool = False,
        prefix_cache: dict | None = None,
        draft_layers: int = 0,
        draft_tokens: int = 4,
        beams: int = 1,
        length_penalty: float = 0.0,
        decode_block: int = 1,
        tenancy=None,
    ) -> None:
        if beams < 1:
            raise ValueError(f"beams={beams} must be >= 1")
        if tenancy is not None and (beams > 1 or draft_layers):
            raise ValueError(
                "tenancy applies to the plain continuous decode path "
                "(not beams / speculative slots)"
            )
        if decode_block < 1:
            raise ValueError(f"decode_block={decode_block} must be >= 1")
        if decode_block > 1 and (beams > 1 or draft_layers):
            raise ValueError(
                "decode_block > 1 applies to the plain decode path (beam "
                "steps and speculative rounds already amortize their own "
                "device calls)"
            )
        if beams > 1:
            # beam slots: each slot owns `beams` contiguous cache rows
            # and a device-side search state; deterministic by
            # construction, so the sampling/speculative knobs are out
            if draft_layers:
                raise ValueError(
                    "beams do not combine with draft_layers (beam "
                    "search is deterministic; speculative rounds are "
                    "per-row)"
                )
            if temperature > 0.0:
                raise ValueError(
                    "beams are deterministic; temperature must be 0"
                )
        self.prefix_len = 0
        self._prefix_cache = prefix_cache
        if prefix_cache is not None:
            # slots start past a shared, once-prefilled prefix (see
            # decode.prefill_prefix) in the decode path's cache layout —
            # bf16 or int8 (quantized_kv takes a quantized_prefill_prefix
            # cache), single-chip or head-sharded over a (data, model)
            # mesh (the broadcast rows land under cache_shardings in the
            # mesh block below)
            from .decode import _check_prefix_layout

            _check_prefix_layout(prefix_cache, quantized_kv)
            self.prefix_len = int(prefix_cache["length"][0])
        if draft_layers:
            # speculative slots: early-exit self-draft inside the slot
            # machine — each engine step is one draft-and-verify round
            if not 0 < draft_layers < config.n_layers:
                raise ValueError(
                    f"draft_layers={draft_layers} must be in "
                    f"[1, n_layers-1] (model has n_layers="
                    f"{config.n_layers})"
                )
            if draft_tokens < 1:
                raise ValueError(
                    f"draft_tokens={draft_tokens} must be >= 1"
                )
        # speculative rounds can overshoot a slot's budget by up to k and
        # still write k+1 masked positions past the frozen length — the
        # same 2k slack speculative_generate reserves
        spec_slack = 2 * draft_tokens if draft_layers else 0
        budget = self.prefix_len + prompt_len + generate_tokens + spec_slack
        if budget > config.max_seq_len:
            slack = f" + 2*draft_tokens ({spec_slack})" if spec_slack else ""
            raise ValueError(
                f"prefix + prompt_len + generate_tokens{slack} = "
                f"{budget} exceeds max_seq_len={config.max_seq_len}"
            )
        if family not in ("gpt", "llama"):
            raise ValueError(f"unknown family {family!r}")
        # unconditional (decode._pick re-checks at trace time, but that
        # would fire inside a worker's never-dies retry loop; greedy mode
        # must reject bad knobs at construction too)
        if top_k < 0:
            raise ValueError(f"top_k={top_k} must be >= 0")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p={top_p} must be in (0, 1]")
        self.params = params
        self.config = config
        self.family = family
        self.prompt_len = prompt_len
        self.generate_tokens = generate_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.mesh = mesh
        self.quantized_kv = quantized_kv
        self.draft_layers = draft_layers
        self.draft_tokens = draft_tokens
        self.beams = beams
        self.length_penalty = length_penalty
        self.decode_block = decode_block
        # which decode engine was BUILT (the knob seam routes on this,
        # not on the live block size): the block engine's compiled scan
        # is shape-polymorphic in its key operand, so decode_block can
        # change live at the re-dispatch boundary without a rebuild —
        # but only an engine constructed on the block path has one.
        # The sharded plane overrides to True (its gang scan takes any
        # block >= 1).
        self._block_engine = decode_block > 1 and beams == 1 \
            and not draft_layers
        # a live decode_block change staged by the knob actuator
        # (sched/knobs.py), completed inside the next step() at the
        # re-dispatch boundary; None = no change pending
        self._pending_decode_block: int | None = None
        # admission cap (per shard on the sharded plane): free_slots
        # offers at most slot_limit - busy rows.  None = unlimited,
        # the reference path byte for byte.  Rows already above a
        # lowered limit finish normally — drain semantics.
        self.slot_limit: int | None = None
        # audit counter (cheap int): full availability scans / routed
        # orderings computed — the per-cycle bookkeeping tests pin that
        # a host cycle pays O(B) availability work once, not per read
        self.free_slot_scans = 0
        # speculative round overlap (draft engines only): dispatch the
        # provably-needed second draft-and-verify round before
        # consuming the first.  True = today's behavior; the knob seam
        # flips it between rounds.
        self.spec_overlap = True
        # multi-tenant admission (workloads/tenancy.py): per-tenant
        # token/TTFT attribution always-on once configured; the prefix
        # pool below only when tenancy.prefix_pool > 0.  tenancy=None
        # keeps every per-cycle path byte-identical to today.
        self.tenancy = tenancy
        self._prefix_pool = None
        self._pool_prefix_len = 0
        import collections

        self.tenant_tokens: dict[str, int] = {}
        self.tenant_ttft: dict[str, Any] = {}
        self._tenant_ttft_deque = partial(collections.deque, maxlen=1024)
        # cumulative per-tenant TTFT (sum, count) — the source of the
        # tenant_ttft_seconds gauge (the recent-sample deques above stay
        # for the benches' nearest-rank p50/p99, but gauges and
        # histograms must never forget old requests the way a maxlen
        # deque does)
        self.tenant_ttft_sum: dict[str, float] = {}
        self.tenant_ttft_count: dict[str, int] = {}
        # TTFT observations awaiting the metrics registry's cumulative
        # histograms, (tenant-or-None, seconds); bounded so a worker
        # without attached metrics cannot grow
        self._pending_ttft_obs: collections.deque = collections.deque(
            maxlen=16384
        )
        # request-lifecycle tracing (obs/lifecycle.py): None = off =
        # byte-identical engine path (same contract as tenancy=None);
        # the worker's attach_lifecycle wires it
        self.lifecycle = None
        # epoch clock for arrival-based per-tenant TTFT — the worker
        # rebinds it to its request-TTL clock so FakeClock episodes and
        # SQS SentTimestamps share one time base
        self._epoch_now = time.time
        # tenant -> home shard for sticky routing (bounded; the sharded
        # plane's router consults it, the plain batcher never does)
        self._tenant_home: Any = collections.OrderedDict()
        if tenancy is not None and tenancy.prefix_pool > 0:
            if prefix_cache is not None:
                raise ValueError(
                    "the per-tenant prefix pool and the single global "
                    "prefix_cache are mutually exclusive (the pool IS "
                    "the generalization of the broadcast prefix)"
                )
            if mesh is not None:
                # the pooled gather IS mesh-sharded (comms/ PR): pool
                # buffers place heads over "model" with the stacked
                # entry axis replicated, so validate the layout divides
                # — a head count the model axis can't split would make
                # XLA silently pad-and-reshard every admission gather
                from .decode import require_serving_mesh

                require_serving_mesh(mesh)
                kv_heads = (
                    config.n_kv_heads if family == "llama"
                    else config.n_heads
                )
                model_axis = mesh.shape["model"]
                if kv_heads % model_axis:
                    raise ValueError(
                        f"prefix pool KV heads ({kv_heads}) not "
                        f"divisible by the mesh's model axis "
                        f"({model_axis}) — the pooled gather shards "
                        "heads over 'model'"
                    )
            if tenancy.prefix_len < 1:
                raise ValueError(
                    "tenancy.prefix_len must be >= 1 when prefix_pool "
                    "is enabled (the pool's static prefix bucket)"
                )
            pooled_budget = (tenancy.prefix_len + prompt_len
                             + generate_tokens)
            if pooled_budget > config.max_seq_len:
                raise ValueError(
                    f"pool prefix_len + prompt_len + generate_tokens = "
                    f"{pooled_budget} exceeds max_seq_len="
                    f"{config.max_seq_len}"
                )
            shard_slots = getattr(self, "shard_slots", batch_size)
            if tenancy.prefix_pool < shard_slots:
                # one refill can admit shard_slots distinct prefixes to
                # a shard; with entries >= shard_slots every same-batch
                # entry sits at the LRU's MRU end when the next install
                # picks a victim, so an eviction can never overwrite a
                # pool row an earlier request in the SAME batched insert
                # is about to gather (silent cross-tenant KV corruption)
                raise ValueError(
                    f"prefix_pool={tenancy.prefix_pool} must be >= the "
                    f"per-shard slot count ({shard_slots}) so a single "
                    "admission batch can never LRU-evict an entry "
                    "another row of the same batch still references"
                )
            from .tenancy import PrefixPool

            self._pool_prefix_len = tenancy.prefix_len
            self._prefix_pool = PrefixPool(
                params, config,
                entries=tenancy.prefix_pool,
                prefix_len=tenancy.prefix_len,
                shards=getattr(self, "shards", 1),
                family=family, quantized_kv=quantized_kv,
                mesh=mesh,
            )
        # aggregate speculative stats (per-request stats ride the slots)
        self.spec_rounds = 0
        self.spec_accepted = 0
        # serving stats (the worker's metrics gauges read these)
        self.tokens_emitted = 0
        self.ttft_sum = 0.0
        self.ttft_count = 0
        self.last_ttft_s: float | None = None
        # recent per-request TTFT samples (bounded: long-lived workers
        # must not grow with requests served) — the fleet bench scores
        # time-over-TTFT-SLO from these
        import collections

        self.ttft_samples: collections.deque[float] = collections.deque(
            maxlen=4096
        )
        # block-decode utilization: kept tokens vs dispatched positions
        self.block_tokens = 0
        self.block_capacity = 0
        # host-sync instrumentation (cheap ints, test-pinned): device
        # program launches and host-blocking transfers over the engine's
        # lifetime.  The serving contract these pin: admission costs ONE
        # insert dispatch and ZERO transfers per refill cycle however
        # many requests it admits, and a decode cycle costs one decode
        # dispatch plus one bounded settle transfer — never per-request,
        # never per-shard.
        self.decode_dispatches = 0
        self.insert_dispatches = 0
        self.host_transfers = 0
        # scheduled collectives (comms/CollectiveScheduler): None = off
        # = the pre-comms engine byte for byte, counters included;
        # attach_comms wires it.  With comms on, settle pulls dispatch
        # device-side inside the dispatch-ahead window and the settle
        # that consumes a prefetched array stops counting as a blocking
        # host transfer.
        self.comms = None
        # in-flight TransferOps covering deferred first-token arrays,
        # keyed by id(array) — safe because the arrays stay alive in
        # _pending_firsts until the settle pops both together
        self._first_ops: dict[int, Any] = {}
        # the op covering the in-flight decode/gang block's settle
        # arrays (one per cycle at most)
        self._block_op: Any = None
        # rows quiesced mid-budget (a degraded slot finished before its
        # DEVICE budget ran out): excluded from admission until the
        # block that was in flight at quiesce time settles, because
        # that block still computed them live — re-admitting sooner
        # would let its stale tokens land in the new request's slot.
        # Always empty outside the overload ladder's tier 1, so the
        # reference path never pays the membership check.
        self._tainted: set[int] = set()
        # deferred first tokens: (device array, slot rows), consumed in
        # one batched transfer at the next step()
        self._pending_firsts: list[tuple[Any, list[int]]] = []
        # in-flight decode block: (tokens, counts, busy-at-dispatch)
        self._pending_block: tuple[Any, Any, int] | None = None
        # beam slots own `beams` contiguous cache rows each
        cache_rows = batch_size * beams
        if prefix_cache is not None:
            # every slot row starts as a copy of the shared prefix (the
            # broadcast is layout-agnostic: gpt and llama caches both
            # put rows on axis 0)
            from .decode import broadcast_prefix

            self.cache = broadcast_prefix(prefix_cache, cache_rows)
        elif quantized_kv:
            # slots store int8 codes + per-position scales: half the
            # bytes every engine step streams (see decode's int8 cache),
            # allocated directly — no transient bf16 buffers at startup
            from .decode import init_quantized_cache

            self.cache = init_quantized_cache(
                config, cache_rows,
                kv_heads=(config.n_kv_heads if family == "llama"
                          else None),
            )
        elif family == "llama":
            from .llama import init_llama_cache

            self.cache = init_llama_cache(config, cache_rows)
        else:
            self.cache = init_cache(config, cache_rows)
        if draft_layers:
            # the draft is the target's first layers: its params are a
            # layer slice, its cache the same layout with fewer layers
            import dataclasses

            self.draft_config = dataclasses.replace(
                config, n_layers=draft_layers
            )
            self.draft_params = dict(
                params, layers=params["layers"][:draft_layers]
            )
            if prefix_cache is not None:
                from .decode import broadcast_prefix
                from .speculative import draft_prefix_from_target

                self.draft_cache = broadcast_prefix(
                    draft_prefix_from_target(prefix_cache, draft_layers),
                    batch_size,
                )
            elif quantized_kv:
                from .decode import init_quantized_cache

                self.draft_cache = init_quantized_cache(
                    self.draft_config, batch_size,
                    kv_heads=(config.n_kv_heads if family == "llama"
                              else None),
                )
            elif family == "llama":
                from .llama import init_llama_cache

                self.draft_cache = init_llama_cache(
                    self.draft_config, batch_size
                )
            else:
                self.draft_cache = init_cache(self.draft_config,
                                              batch_size)
        self.slots = [_Slot() for _ in range(batch_size)]
        # each slot's pending input token(s) for the next decode step
        self._current = jnp.zeros((cache_rows,), jnp.int32)
        if beams == 1 and not draft_layers:
            # plain slots keep their liveness ON DEVICE: done marks a
            # free/finished row (admission clears it), remaining is the
            # row's unspent token budget — what lets a decode block (and
            # its dispatch-ahead overlap) run without consulting the
            # host between tokens
            self._done = jnp.ones((cache_rows,), bool)
            self._remaining = jnp.zeros((cache_rows,), jnp.int32)
        if beams > 1:
            # device-side per-slot search state (the standalone
            # beam_search's scan carry, re-hosted as rolling state)
            self._beam_scores = jnp.zeros((batch_size, beams), jnp.float32)
            self._beam_out = jnp.full(
                (batch_size, beams, generate_tokens),
                eos_id if eos_id is not None else 0, jnp.int32,
            )
            self._beam_alive = jnp.zeros((batch_size, beams), bool)
            self._beam_emitted = jnp.zeros((batch_size, beams), jnp.int32)
        if mesh is not None:
            # mesh-sharded slots: batch rows over "data", heads over
            # "model" (the serving layout of decode.cache_shardings);
            # the one-prompt insert prefill replicates over data — tp is
            # the axis that matters for a model too big for one chip
            from .decode import require_serving_mesh

            require_serving_mesh(mesh)
            if batch_size % mesh.shape["data"]:
                raise ValueError(
                    f"batch_size {batch_size} not divisible by the "
                    f"mesh's data axis ({mesh.shape['data']})"
                )
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .decode import cache_shardings

            self._cache_shard = cache_shardings(mesh, self.cache)
            self._rows_shard = NamedSharding(mesh, P("data"))
            self.cache = jax.device_put(self.cache, self._cache_shard)
            self._current = jax.device_put(self._current, self._rows_shard)
            if beams == 1 and not draft_layers:
                self._done = jax.device_put(self._done, self._rows_shard)
                self._remaining = jax.device_put(self._remaining,
                                                 self._rows_shard)
            if beams > 1:
                # slot-major state: slots over "data" (each slot's beam
                # rows stay contiguous within one shard because
                # batch_size % data == 0)
                self._slot_shard = NamedSharding(mesh, P("data", None))
                self._beam_scores = jax.device_put(self._beam_scores,
                                                   self._slot_shard)
                self._beam_out = jax.device_put(
                    self._beam_out, NamedSharding(mesh, P("data", None,
                                                          None)))
                self._beam_alive = jax.device_put(self._beam_alive,
                                                  self._slot_shard)
                self._beam_emitted = jax.device_put(self._beam_emitted,
                                                    self._slot_shard)
            if draft_layers:
                self._draft_cache_shard = cache_shardings(
                    mesh, self.draft_cache
                )
                self.draft_cache = jax.device_put(
                    self.draft_cache, self._draft_cache_shard
                )
        # one PRNG key per engine step / insert.  Greedy single-chip: no
        # keys at all (the compiled programs take a None operand); under
        # a mesh the pinned in_shardings need a real (ignored) key even
        # when greedy.
        if temperature > 0.0 or mesh is not None:
            from .service import sampling_keys

            self._keys = sampling_keys(sample_seed)
        else:
            self._keys = itertools.repeat(None)
        if beams > 1:
            self._insert = self._make_beam_insert()
            self._beam_step_fn = self._make_beam_step()
        elif draft_layers:
            self._insert = self._make_spec_insert()
            self._spec = self._make_spec_round()
        else:
            self._insert_many = self._make_insert_many()
            # the evacuation/resume insert: building the closure is free
            # (compilation stays lazy per resume size), and building it
            # HERE lets adopt_engine share one compile cache across a
            # fleet — an evacuation wave hits one compile, not one per
            # engine
            self._resume_insert = self._make_insert_many(resume=True)
            if self._prefix_pool is not None:
                self._pooled_insert = self._make_insert_pooled()
            if decode_block > 1:
                self._block_fn = self._make_block_fn()
            else:
                self._decode = self._make_decode_step()

    def adopt_engine(self, source: "ContinuousBatcher") -> None:
        """Rebind this batcher's compiled programs to ``source``'s.

        The jitted insert/decode callables close over *static* knobs only
        (config, bucket sizes, sampling policy) — never over a batcher's
        rolling device state — so two batchers constructed with the same
        knobs can share one set of compiled executables.  That is what
        makes replica spin-up O(1) host work (BLITZSCALE-style): a new
        fleet replica shares the already-built params by reference AND
        the already-compiled programs by adoption, paying only its own
        KV-cache allocation instead of a retrace + recompile per replica.

        Plain decode slots only (the fleet path); every static knob must
        match, or the adopted programs would silently compute the wrong
        policy.
        """
        if self.beams > 1 or self.draft_layers or source.beams > 1 \
                or source.draft_layers:
            raise ValueError(
                "adopt_engine supports the plain decode path only"
            )
        mine = self._engine_key()
        theirs = source._engine_key()
        if mine != theirs:
            raise ValueError(
                f"engine mismatch: {mine} != {theirs} (a replica must be "
                "constructed with the donor's exact serving knobs)"
            )
        if (self.config is not source.config
                or self.params is not source.params
                or self.mesh is not source.mesh
                or self._prefix_cache is not source._prefix_cache):
            raise ValueError(
                "adopt_engine requires the donor's exact params/config/"
                "mesh/prefix objects (the compiled programs close over "
                "them)"
            )
        self._insert_many = source._insert_many
        self._resume_insert = source._resume_insert
        if (self._prefix_pool is not None
                and source._prefix_pool is not None):
            # the pooled insert closes over statics only (pool buffers
            # ride as operands), so replicas share one compile for it
            # too — each keeps its OWN pool rows and LRU state
            self._pooled_insert = source._pooled_insert
        # copy whichever decode program both sides BUILT: the engine
        # key above matches live decode_block values, but a live knob
        # change can leave a block-engine donor at block 1 — a fresh
        # single-step replica must be told apart from it, not handed a
        # program it cannot run
        if hasattr(source, "_block_fn") and hasattr(self, "_block_fn"):
            self._block_fn = source._block_fn
        elif hasattr(source, "_decode") and hasattr(self, "_decode"):
            self._decode = source._decode
        else:
            raise ValueError(
                "engine mismatch: donor and replica were constructed "
                "on different decode paths (block-scan vs single-step) "
                "— construct the replica with the donor's engine class"
            )

    def _engine_key(self) -> tuple:
        """The static knobs the plain path's compiled programs depend on."""
        return (
            len(self.slots), self.prompt_len, self.generate_tokens,
            self.family, self.temperature, self.top_k, self.top_p,
            self.eos_id, self.quantized_kv, self.prefix_len,
            self.decode_block, self.mesh is None,
            self._pool_prefix_len,
        )

    # ------------------------------------------------------------------
    # Live engine knobs (sched/knobs.py KnobActuator): each change is
    # requested between cycles and lands at the knob's safe point.
    # Unused, every flag keeps the per-cycle paths byte-identical.
    # ------------------------------------------------------------------

    def request_decode_block(self, block: int) -> bool:
        """Stage a live decode-block change, completed inside the next
        :meth:`step` at the RE-DISPATCH boundary: the engine skips one
        dispatch-ahead so the in-flight block settles at the old size,
        then dispatches the next block at the new one.  The compiled
        block scan derives its length from the key operand's shape, so
        a new size is one cached retrace — never a rebuild, never a
        mid-block tear.  Block/gang engines only (an engine constructed
        at ``decode_block == 1`` runs the single-step path and has no
        block program to resize).  Returns False when ``block`` is
        already the live (or staged) size."""
        if not self._block_engine:
            raise ValueError(
                "decode_block is a live knob only on the block/gang "
                "decode engine (construct with decode_block > 1, or "
                "the sharded plane)"
            )
        block = int(block)
        if block < 1:
            raise ValueError(f"decode_block={block} must be >= 1")
        current = (
            self._pending_decode_block
            if self._pending_decode_block is not None
            else self.decode_block
        )
        if block == current:
            return False
        if self._pending_block is None and self.active == 0:
            # idle engine: nothing in flight at any size — swap now
            # (step() early-outs while idle, so a staged swap would
            # otherwise wait for the next admission's first step)
            self.decode_block = block
            self._pending_decode_block = None
            return True
        self._pending_decode_block = block
        return True

    def _apply_pending_decode_block(self) -> None:
        """Complete a staged block swap — called by the step bodies
        AFTER the old-size block settled and only when nothing is in
        flight (``_pending_block is None``)."""
        if self._pending_decode_block is None:
            return
        self.decode_block = self._pending_decode_block
        self._pending_decode_block = None

    def set_slot_limit(self, limit: int | None) -> None:
        """Cap admission at ``limit`` busy rows (per shard on the
        sharded plane); ``None`` = unlimited (the reference path).
        Pure host bookkeeping at the availability scan — rows already
        above a lowered limit decode to completion (drain, never a
        kill), and raising the limit re-offers the parked rows on the
        very next refill."""
        if limit is not None:
            limit = int(limit)
            per_shard = getattr(self, "shard_slots", len(self.slots))
            if not 1 <= limit <= per_shard:
                raise ValueError(
                    f"slot_limit={limit} must be in [1, {per_shard}] "
                    "(or None = unlimited)"
                )
        self.slot_limit = limit
        self._invalidate_admission_cache()

    def set_speculative(self, enabled: bool) -> None:
        """Toggle the speculative engine's second-round overlap (the
        dispatch-ahead of provably-needed draft-and-verify rounds).
        Safe between rounds — the flag is read once per :meth:`step`.
        Draft engines only."""
        if not self.draft_layers:
            raise ValueError(
                "the speculative knob needs the draft-and-verify "
                "engine (draft_layers > 0)"
            )
        self.spec_overlap = bool(enabled)

    # ------------------------------------------------------------------
    # Scheduled collectives (comms/): the engine's transfer seam.
    # ------------------------------------------------------------------

    def attach_comms(self, comms) -> None:
        """Wire a :class:`~..comms.CollectiveScheduler` (None detaches).

        With a scheduler attached, the block/gang step flushes queued
        transfer ops inside its dispatch-ahead window — the settle
        pulls start device-side while the next block computes — and
        the prefix pool records its installs.  Detached (the default),
        every per-cycle path is byte-identical to the pre-comms
        engine, counters included."""
        self.comms = comms
        if self._prefix_pool is not None:
            self._prefix_pool.comms = comms

    def _comms_flush(self, *, overlapped: bool) -> None:
        """Submit every not-yet-scheduled deferred first-token array
        as a settle-pull op and dispatch the comms queue device-side.
        Called by the block/gang step right AFTER the next block's
        dispatch (``overlapped=True``: the copies hide behind its
        device time); a flush with nothing in flight passes False and
        the counters stay honest."""
        comms = self.comms
        if comms is None or not comms.enabled:
            return
        for arr, rows in self._pending_firsts:
            if id(arr) in self._first_ops:
                continue
            rids = [
                _trace_key(self.slots[row].payload) for row in rows
            ]
            op = comms.settle_pull(
                arr,
                destination="host",
                source=self._comms_source(rows),
                rids=[r for r in rids if r is not None],
                args={"rows": list(rows)},
            )
            if op is not None:
                self._first_ops[id(arr)] = op
        if self._block_op is None:
            arrs = self._block_settle_arrays()
            if arrs is not None:
                rids = [
                    _trace_key(slot.payload)
                    for slot in self.slots if slot.busy
                ]
                self._block_op = comms.settle_pull(
                    arrs, destination="host",
                    source=self._comms_source(None),
                    rids=[r for r in rids if r is not None],
                    args={"block": True},
                )
        comms.flush(overlapped=overlapped)

    def _comms_source(self, rows) -> str:
        """The routing endpoint a settle pull leaves from — the
        topology node whose links the route planner charges.  The flat
        engine is one device (``rows`` unused); the sharded plane
        overrides this to attribute single-shard pulls to their shard
        (a gang-wide pull stays ``device``)."""
        return "device"

    def _block_settle_arrays(self):
        """The in-flight block's device arrays its settle will fetch
        (None when nothing is in flight) — what the comms flush
        prefetches.  The block was dispatched a full cycle ago, so by
        flush time its results exist device-side and an async host
        copy genuinely overlaps the block dispatched this cycle."""
        if self._pending_block is None:
            return None
        tokens, counts, _ = self._pending_block
        return (tokens, counts)

    def _row_kv_nbytes(self) -> int:
        """One cache row's KV bytes across every layer — the payload
        size of a per-row KV move (evacuation, handoff) for the comms
        accounting; layout-agnostic (bf16 k/v or int8 codes+scales)."""
        total = 0
        for layer in self.cache["layers"]:
            for buf in layer.values():
                total += buf.nbytes // max(1, buf.shape[0])
        return total

    def _make_insert_many(self, resume: bool = False):
        """The plain path's batched-admission jit: ``(params, cache,
        current, done, remaining, rows, prompts, lengths, key, n_rows)``
        with ``n_rows`` static (one compiled program per refill size —
        at most ``batch_size`` of them).

        ``resume=True`` builds the evacuation/resume variant of the SAME
        machinery: the static prompt bucket widens to :attr:`resume_len`
        (a resumed row prefills prompt + already-produced tokens) and a
        trailing ``budgets`` int32 ``[n_rows]`` operand replaces the
        static ``budget - 1`` re-arm with each row's unspent budget."""
        statics = dict(
            config=self.config,
            prompt_len=self.resume_len if resume else self.prompt_len,
            budget=self.generate_tokens,
            family=self.family, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            quantized_kv=self.quantized_kv,
            prefix_len=self.prefix_len, eos_id=self.eos_id,
        )
        if self.mesh is None:
            if resume:
                return lambda *operands, n_rows: _insert_rows(
                    *operands[:-1], n_rows=n_rows, budgets=operands[-1],
                    prefix_cache=self._prefix_cache, **statics,
                )
            return lambda *operands, n_rows: _insert_rows(
                *operands, n_rows=n_rows,
                prefix_cache=self._prefix_cache, **statics,
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        rows = self._rows_shard
        # rows/prompts/lengths/key are tiny per-refill operands — they
        # replicate, like the single-prompt insert's scalars did
        in_ops = (p_shard, self._cache_shard, rows, rows, rows,
                  rep, rep, rep, rep)
        if resume:
            in_ops = in_ops + (rep,)  # the trailing budgets operand
        out_ops = (self._cache_shard, rows, rows, rows, rep)
        if self._prefix_cache is not None:
            from .decode import prefix_cache_shardings

            pfx_shard = prefix_cache_shardings(self.mesh, self._prefix_cache)
            placed_prefix = jax.device_put(self._prefix_cache, pfx_shard)
        jits: dict[int, Any] = {}

        def impl(*args, _n, _prefix=None):
            # peel the optional trailing operands back into keywords
            # (pjit rejects kwargs when in_shardings is set)
            if resume:
                *ops, budgets = args
            else:
                ops, budgets = args, None
            return _insert_rows_impl(
                *ops, n_rows=_n, budgets=budgets, prefix_cache=_prefix,
                **statics,
            )

        def insert_many(*operands, n_rows):
            fn = jits.get(n_rows)
            if fn is None:
                if self._prefix_cache is None:
                    fn = jax.jit(
                        partial(impl, _n=n_rows),
                        in_shardings=in_ops, out_shardings=out_ops,
                        donate_argnums=(1, 2, 3, 4),
                    )
                else:
                    def _with_prefix(*args, _n=n_rows):
                        *ops, prefix = args
                        return impl(*ops, _n=_n, _prefix=prefix)

                    inner = jax.jit(
                        _with_prefix,
                        in_shardings=(*in_ops, pfx_shard),
                        out_shardings=out_ops,
                        donate_argnums=(1, 2, 3, 4),
                    )
                    fn = lambda *ops, _f=inner: _f(*ops, placed_prefix)
                jits[n_rows] = fn
            return fn(*operands)

        return insert_many

    def _make_insert_pooled(self):
        """The prefix-pool admission jit: same shape discipline as
        :meth:`_make_insert_many` (one compiled program per refill
        size), plus the per-row pool entry indices and the pool's
        stacked layer buffers as operands.

        Under a mesh the gather is sharding-aware (ROADMAP item 2):
        pool buffers place heads over "model" with the stacked entry
        axis replicated (the :func:`~.decode.prefix_cache_shardings`
        layout applied per layer — any entry may be gathered to any
        data-shard row), the slot cache keeps its serving layout, and
        the whole insert stays ONE device call.  The gather's entry
        axis never crosses the head axis, so outputs are byte-identical
        to the single-chip pooled path (gated by the forced-CPU-mesh
        parity tests)."""
        statics = dict(
            config=self.config, prompt_len=self.prompt_len,
            budget=self.generate_tokens, family=self.family,
            temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, quantized_kv=self.quantized_kv,
            pool_prefix_len=self._pool_prefix_len, eos_id=self.eos_id,
        )
        if self.mesh is None:
            return lambda *operands, n_rows: _insert_rows_pooled(
                *operands, n_rows=n_rows, **statics,
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        rows = self._rows_shard
        pool_shard = self._prefix_pool.layer_shardings(self.mesh)
        # operand order mirrors _insert_rows_pooled_impl: params, the
        # four donated state operands, then the tiny replicated
        # per-refill operands (rows/prompts/lengths/key/entry_idx) and
        # the pool's stacked layers
        in_ops = (p_shard, self._cache_shard, rows, rows, rows,
                  rep, rep, rep, rep, rep, pool_shard)
        out_ops = (self._cache_shard, rows, rows, rows, rep)
        jits: dict[int, Any] = {}

        def impl(*args, _n):
            return _insert_rows_pooled_impl(*args, n_rows=_n, **statics)

        def insert_pooled(*operands, n_rows):
            fn = jits.get(n_rows)
            if fn is None:
                fn = jax.jit(
                    partial(impl, _n=n_rows),
                    in_shardings=in_ops, out_shardings=out_ops,
                    donate_argnums=(1, 2, 3, 4),
                )
                jits[n_rows] = fn
            return fn(*operands)

        return insert_pooled

    def _mesh_insert_jit(self, impl, statics, cache_shards):
        """The speculative insert's mesh wiring: pinned in/out shardings
        with the cache operands AND the folded ``current`` donated, and —
        under a prefix — the shared batch-1 prefix riding as an explicit
        trailing operand (heads over "model", batch replicated),
        injected by a closure so the returned callable keeps its
        prefix-free signature."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        # current, then row, prompt, length, key
        state_ops = (self._rows_shard, rep, rep, rep, rep)
        donate = tuple(range(1, 2 + len(cache_shards)))
        if self._prefix_cache is None:
            return jax.jit(
                partial(impl, **statics),
                in_shardings=(p_shard, *cache_shards, *state_ops),
                out_shardings=(*cache_shards, self._rows_shard, rep),
                donate_argnums=donate,
            )
        from .decode import prefix_cache_shardings

        pfx_shard = prefix_cache_shardings(self.mesh, self._prefix_cache)
        placed_prefix = jax.device_put(self._prefix_cache, pfx_shard)

        def _with_prefix(*args):
            *operands, prefix = args
            return impl(*operands, prefix_cache=prefix, **statics)

        fn = jax.jit(
            _with_prefix,
            in_shardings=(p_shard, *cache_shards, *state_ops, pfx_shard),
            out_shardings=(*cache_shards, self._rows_shard, rep),
            donate_argnums=donate,
        )
        return lambda *operands: fn(*operands, placed_prefix)

    def _family_step_fn(self):
        """The family/layout decode step every plain-path program shares
        (single-step, block scan, and the beam step pick theirs the same
        way)."""
        if self.quantized_kv:
            if self.family == "llama":
                from .llama import llama_quantized_decode_step as step_fn
            else:
                from .decode import quantized_decode_step as step_fn
        elif self.family == "llama":
            from .llama import llama_decode_step as step_fn
        else:
            from .decode import decode_step as step_fn
        return step_fn

    def _make_decode_step(self):
        step_fn = self._family_step_fn()
        config = self.config
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p

        # donate the cache: self.cache is reassigned from the result every
        # call, so the multi-layer KV buffers are reused in place instead
        # of copied per generated token (same as compile_serving_fns)
        def step(params, cache, tokens, key):
            logits, cache = step_fn(params, cache, tokens, config)
            return cache, _pick(logits, key, temperature, top_k, top_p)

        if self.mesh is None:
            return jax.jit(step, donate_argnums=(1,))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        return jax.jit(
            step,
            in_shardings=(param_shardings(self.mesh, self.params),
                          self._cache_shard, self._rows_shard, rep),
            out_shardings=(self._cache_shard, self._rows_shard),
            donate_argnums=(1,),
        )

    def _make_block_fn(self):
        """The compiled decode block (``decode_block > 1``): a
        :func:`.decode.block_decode` scan over the family step, cache and
        per-row liveness state donated so the buffers roll in place
        block after block."""
        from .decode import block_decode

        step_fn = self._family_step_fn()
        config = self.config
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        eos_id = self.eos_id

        def blk(params, cache, current, done, remaining, keys):
            return block_decode(
                params, cache, current, done, remaining, keys, config,
                step_fn, temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id,
            )

        if self.mesh is None:
            return jax.jit(blk, donate_argnums=(1, 2, 3, 4))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        rows = self._rows_shard
        tokens_shard = NamedSharding(self.mesh, P(None, "data"))
        return jax.jit(
            blk,
            in_shardings=(param_shardings(self.mesh, self.params),
                          self._cache_shard, rows, rows, rows, rep),
            out_shardings=(self._cache_shard, rows, rows, rows,
                           tokens_shard, rows),
            donate_argnums=(1, 2, 3, 4),
        )

    def _make_spec_insert(self):
        statics = dict(
            config=self.config, prompt_len=self.prompt_len,
            draft_layers=self.draft_layers,
            family=self.family, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p,
            quantized_kv=self.quantized_kv,
            prefix_len=self.prefix_len,
        )
        if self.mesh is None:
            return lambda params, cache, dcache, current, row, prompt, \
                    length, key: (
                _spec_insert_row(params, cache, dcache, current, row,
                                 prompt, length, key,
                                 prefix_cache=self._prefix_cache,
                                 **statics)
            )
        return self._mesh_insert_jit(
            _spec_insert_row_impl, statics,
            (self._cache_shard, self._draft_cache_shard),
        )

    def _make_spec_round(self):
        """One compiled draft-and-verify round over ALL slots: k draft
        steps + one extra draft consume + one (k+1)-wide target chunk
        verify, per-row acceptance, per-row length advance gated by the
        ``active`` mask (inactive slots neither emit nor advance — their
        chunk writes land in slots their unchanged length keeps masked,
        the same compute-always discipline as the plain decode step).
        Exactly :func:`.speculative.speculative_generate`'s round body,
        re-hosted in the slot machine: greedy rounds emit what plain
        greedy decode would, sampled rounds apply the Leviathan/Chen
        acceptance rule so every emitted token is an exact warped-target
        sample."""
        from .speculative import _accept_and_fixup, _family_ops, _warp

        _, t_step, t_chunk, _ = _family_ops(self.config, self.quantized_kv)
        _, d_step, _, _ = _family_ops(self.draft_config, self.quantized_kv)
        k = self.draft_tokens
        config, dconfig = self.config, self.draft_config
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        sampled = temperature > 0.0

        def round_fn(params_t, params_d, t_cache, d_cache, pending,
                     active, key):
            if sampled:
                keys = jax.random.split(key, k + 1)
                accept_key, draft_keys = keys[0], keys[1:]
            proposals, draft_warped = [], []
            token = pending
            dc = d_cache
            for i in range(k):  # k is small and static — unrolled
                logits, dc = d_step(params_d, dc, token, dconfig)
                if sampled:
                    warped = _warp(logits, temperature, top_k, top_p)
                    draft_warped.append(warped)
                    token = jax.random.categorical(
                        draft_keys[i], warped
                    ).astype(jnp.int32)
                else:
                    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                proposals.append(token)
            drafts = jnp.stack(proposals, axis=1)  # [B, k]
            # extra consume of d_k: the draft cache holds every accepted
            # input even on full acceptance (masked otherwise)
            _, dc = d_step(params_d, dc, drafts[:, -1], dconfig)

            chunk = jnp.concatenate([pending[:, None], drafts], axis=1)
            t_len = t_cache["length"]
            d_len = d_cache["length"]
            logits, t_adv = t_chunk(params_t, t_cache, chunk, config)

            if sampled:
                n, bonus = _accept_and_fixup(
                    accept_key, drafts, jnp.stack(draft_warped, axis=1),
                    _warp(logits, temperature, top_k, top_p),
                )
            else:
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                matches = (drafts == greedy[:, :k]).astype(jnp.int32)
                n = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
                bonus = jnp.take_along_axis(
                    greedy, n[:, None], axis=1
                )[:, 0]

            j = jnp.arange(k + 1)[None, :]
            round_tokens = jnp.where(
                j < n[:, None],
                jnp.pad(drafts, ((0, 0), (0, 1))),
                bonus[:, None],
            )
            advance = jnp.where(active, n + 1, 0)
            t_cache = dict(t_adv, length=t_len + advance)
            d_cache = dict(dc, length=d_len + advance)
            pending_next = jnp.where(active, bonus, pending)
            return (t_cache, d_cache, pending_next, round_tokens,
                    jnp.where(active, n, 0))

        if self.mesh is None:
            return jax.jit(round_fn, donate_argnums=(2, 3))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        p_shard_d = dict(
            p_shard, layers=p_shard["layers"][:self.draft_layers]
        )
        rows_2d = NamedSharding(self.mesh, P("data", None))
        return jax.jit(
            round_fn,
            in_shardings=(p_shard, p_shard_d, self._cache_shard,
                          self._draft_cache_shard, self._rows_shard,
                          self._rows_shard, rep),
            out_shardings=(self._cache_shard, self._draft_cache_shard,
                           self._rows_shard, rows_2d, self._rows_shard),
            donate_argnums=(2, 3),
        )

    def _make_beam_insert(self):
        statics = dict(
            config=self.config, prompt_len=self.prompt_len,
            beams=self.beams, family=self.family,
            quantized_kv=self.quantized_kv,
            prefix_len=self.prefix_len, eos_id=self.eos_id,
        )
        if self.mesh is None:
            return lambda params, cache, scores, out, alive, emitted, \
                    current, row, prompt, length: (
                _beam_insert_row(params, cache, scores, out, alive,
                                 emitted, current, row, prompt, length,
                                 prefix_cache=self._prefix_cache,
                                 **statics)
            )
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        p_shard = param_shardings(self.mesh, self.params)
        out_shard = NamedSharding(self.mesh, P("data", None, None))
        state_in = (self._slot_shard, out_shard, self._slot_shard,
                    self._slot_shard, self._rows_shard)
        if self._prefix_cache is None:
            # cache + beam state donated, like the single-chip insert:
            # every operand in (1..6) comes back as an output the caller
            # rebinds, so the sharded buffers are reused in place
            return jax.jit(
                partial(_beam_insert_row_impl, **statics),
                in_shardings=(p_shard, self._cache_shard, *state_in,
                              rep, rep, rep),
                out_shardings=(self._cache_shard, *state_in),
                donate_argnums=(1, 2, 3, 4, 5, 6),
            )
        from .decode import prefix_cache_shardings

        pfx_shard = prefix_cache_shardings(self.mesh, self._prefix_cache)
        placed_prefix = jax.device_put(self._prefix_cache, pfx_shard)

        def _ins(params, cache, scores, out, alive, emitted, current,
                 row, prompt, length, prefix):
            return _beam_insert_row_impl(
                params, cache, scores, out, alive, emitted, current, row,
                prompt, length, prefix_cache=prefix, **statics)

        fn = jax.jit(
            _ins,
            in_shardings=(p_shard, self._cache_shard, *state_in, rep,
                          rep, rep, pfx_shard),
            out_shardings=(self._cache_shard, *state_in),
            donate_argnums=(1, 2, 3, 4, 5, 6),
        )
        return lambda *operands: fn(*operands, placed_prefix)

    def _make_beam_step(self):
        """One compiled beam step over ALL slots: advance every beam row
        one position, per-slot top-k over the ``W*V`` expansions with
        frozen-beam handling, in-block parent gathers of cache and
        state — the standalone :func:`.beam.beam_search` scan body,
        re-hosted with an ``active`` mask so free/finished slots neither
        reorder nor emit (the same compute-always discipline as the
        plain and speculative steps)."""
        step_fn = self._family_step_fn()
        config = self.config
        eos_id = self.eos_id
        W = self.beams

        def bstep(params, cache, current, scores, out, alive, emitted,
                  active):
            lengths_in = cache["length"]  # pre-step, for inactive freeze
            logits, cache = step_fn(params, cache, current, config)
            S = scores.shape[0]
            vocab = logits.shape[-1]
            logp = jax.nn.log_softmax(logits, axis=-1).reshape(S, W, vocab)
            if eos_id is not None:
                # a finished beam contributes exactly one continuation —
                # its frozen self emitting eos at no score cost
                frozen = jnp.full((S, W, vocab), -jnp.inf)
                frozen = frozen.at[:, :, eos_id].set(0.0)
                logp = jnp.where(alive[..., None], logp, frozen)
            total = scores[..., None] + logp
            flat_scores, flat_idx = jax.lax.top_k(
                total.reshape(S, W * vocab), W
            )
            parent = flat_idx // vocab
            token = (flat_idx % vocab).astype(jnp.int32)
            # inactive slots: identity parents, no writes, no advance
            act = active[:, None]
            parent = jnp.where(act, parent, jnp.arange(W)[None, :])
            rows = jnp.arange(S)
            flat_parent = (rows[:, None] * W + parent).reshape(-1)
            cache = jax.tree.map(lambda a: a[flat_parent], cache)
            # Gate the length-pointer advance by the active mask, the way
            # the speculative round does (advance = where(active, n+1, 0)):
            # free/finished slots keep their pointer frozen instead of
            # marching toward max_seq_len and leaning on the scatter's
            # out-of-bounds clamp + the attention mask.  (Their identity
            # parent gather kept their own advanced length, so restoring
            # the pre-step value is exact.)
            cache = dict(
                cache,
                length=jnp.where(
                    jnp.repeat(active, W), cache["length"], lengths_in
                ),
            )
            out_g = out[rows[:, None], parent]
            alive_g = alive[rows[:, None], parent]
            emitted_g = emitted[rows[:, None], parent]
            write = jnp.where(
                alive_g, token,
                eos_id if eos_id is not None else token,
            )
            budget = out.shape[-1]
            out_w = jax.vmap(
                jax.vmap(lambda r, t, v: r.at[t].set(v))
            )(out_g, jnp.minimum(emitted_g, budget - 1), write)
            out = jnp.where(act[..., None], out_w, out)
            emitted = jnp.where(
                act, emitted_g + alive_g.astype(jnp.int32), emitted
            )
            new_alive = (
                alive_g & (token != eos_id) if eos_id is not None
                else alive_g
            )
            alive = jnp.where(act, new_alive, alive)
            scores = jnp.where(act, flat_scores, scores)
            current = jnp.where(
                act, token, current.reshape(S, W)
            ).reshape(-1)
            return (cache, current, scores, out, alive, emitted,
                    jnp.any(alive, axis=1))

        if self.mesh is None:
            return jax.jit(bstep, donate_argnums=(1,))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        p_shard = param_shardings(self.mesh, self.params)
        out_shard = NamedSharding(self.mesh, P("data", None, None))
        slot_1d = NamedSharding(self.mesh, P("data"))
        return jax.jit(
            bstep,
            in_shardings=(p_shard, self._cache_shard, self._rows_shard,
                          self._slot_shard, out_shard, self._slot_shard,
                          self._slot_shard, slot_1d),
            out_shardings=(self._cache_shard, self._rows_shard,
                           self._slot_shard, out_shard, self._slot_shard,
                           self._slot_shard, slot_1d),
            donate_argnums=(1,),
        )

    def _beam_best(self, row: int) -> np.ndarray:
        """The finished slot's best beam, ranked exactly like
        :func:`.beam.beam_search` (GNMT length normalization when
        ``length_penalty > 0``; ties resolve to the lowest beam index,
        matching the standalone's stable descending sort)."""
        out = np.asarray(self._beam_out[row])
        scores = np.asarray(self._beam_scores[row])
        if self.length_penalty > 0:
            # float32 throughout, matching the standalone's ranking math
            # bit for bit (a float64 norm could flip ties)
            emitted = np.asarray(self._beam_emitted[row]).astype(
                np.float32
            )
            norm = (
                (np.float32(5.0) + emitted) / np.float32(6.0)
            ) ** np.float32(self.length_penalty)
            ranked = scores / norm
        else:
            ranked = scores
        return out[int(np.argmax(ranked))].astype(np.int32)

    def _step_beam(self) -> list[tuple[Any, np.ndarray]]:
        finished = []
        needs = [
            s.busy and not s.done and s.rounds < s.budget - 1
            for s in self.slots
        ]
        if any(needs):
            active = jnp.asarray(needs)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                active = jax.device_put(
                    active, NamedSharding(self.mesh, P("data"))
                )
            (self.cache, self._current, self._beam_scores,
             self._beam_out, self._beam_alive, self._beam_emitted,
             alive_any) = self._beam_step_fn(
                self.params, self.cache, self._current,
                self._beam_scores, self._beam_out, self._beam_alive,
                self._beam_emitted, active,
            )
            self.decode_dispatches += 1
            alive_host = np.asarray(alive_any)
            self.host_transfers += 1
            for row, slot in enumerate(self.slots):
                if needs[row]:
                    slot.rounds += 1
                    if not alive_host[row]:
                        # every beam frozen: further steps are no-ops
                        # (frozen beams emit eos at unchanged scores),
                        # so the result is already final
                        slot.done = True
        for row, slot in enumerate(self.slots):
            if slot.busy and (slot.done or slot.rounds >= slot.budget - 1):
                best = self._beam_best(row)
                # count kept tokens like _emit does for the other paths:
                # everything up to and including the first eos, never the
                # padding after it (a budget-64 request that ends at
                # token 3 emitted 3 tokens, not 64)
                kept = int(best.size)
                if self.eos_id is not None:
                    hits = np.flatnonzero(best == self.eos_id)
                    if hits.size:
                        kept = int(hits[0]) + 1
                self.tokens_emitted += kept
                # beam search has no incremental first token — the best
                # beam is only known at completion — so TTFT is the time
                # until the request's first token is *available*: finish
                ttft = time.perf_counter() - slot.submitted_at
                self.ttft_sum += ttft
                self.ttft_count += 1
                self.last_ttft_s = ttft
                self.ttft_samples.append(ttft)
                self._pending_ttft_obs.append((None, ttft))
                finished.append((slot.payload, best))
                self.slots[row] = _Slot()
        return finished

    @property
    def free_slots(self) -> list[int]:
        self.free_slot_scans += 1
        if self._tainted:
            rows = [
                i for i, s in enumerate(self.slots)
                if not s.busy and i not in self._tainted
            ]
        else:
            rows = [i for i, s in enumerate(self.slots) if not s.busy]
        if self.slot_limit is not None:
            # the active-slot knob: offer at most limit - busy rows
            # (never negative — rows above a freshly-lowered limit
            # simply finish, admission just stops offering headroom)
            busy = sum(s.busy for s in self.slots)
            rows = rows[: max(0, self.slot_limit - busy)]
        return rows

    def _invalidate_admission_cache(self) -> None:
        """Hook for planes that memoize admission availability (the
        sharded plane's per-refill ``_admission_rows_by_shard`` cache).
        Called at every mutation that can change which rows are
        admission-eligible: slot assignment, slot release, taint
        changes, shard mask/probe flips.  No-op here — the single-plane
        ``free_slots`` scan is already O(B) and uncached."""

    def _quiesce_rows(self, rows: list[int]) -> None:
        """Freeze the device twins of host-finished rows whose DEVICE
        budget has not run out (the degraded-completion case): mark
        them done with no remaining budget so the next dispatched block
        skips them, and taint them out of admission until the block
        already in flight settles (its tokens for these rows were
        computed live and must drain onto non-busy slots, never into a
        re-admitted request).  One tiny device op per cycle, and only
        on cycles where a degraded slot actually finished."""
        if not rows:
            return
        self._invalidate_admission_cache()
        idx = jnp.asarray(rows, jnp.int32)
        self._done = self._done.at[idx].set(True)
        self._remaining = self._remaining.at[idx].set(0)
        if self._pending_block is not None:
            # only the dispatch-ahead engines have a block in flight;
            # the single-step engine consumes every token in the same
            # cycle, so its quiesced rows are immediately re-admissible
            self._tainted.update(rows)

    @property
    def active(self) -> int:
        return sum(s.busy for s in self.slots)

    def _pad_prompt(self, token_ids) -> tuple[np.ndarray, int]:
        """Truncate/right-pad one prompt to the static ``prompt_len``
        bucket (empty prompts count one pad token)."""
        ids = np.zeros((self.prompt_len,), np.int32)
        real = np.asarray(token_ids, np.int32).reshape(-1)[: self.prompt_len]
        ids[: real.size] = real
        return ids, max(1, real.size)

    def submit(self, token_ids: np.ndarray, payload: Any = None) -> int:
        """Prefill one request into a free slot; returns the slot index.

        ``token_ids`` is truncated/right-padded to the batcher's static
        ``prompt_len`` bucket (empty prompts count one pad token).  The
        single-request case of :meth:`submit_many` — like it, the first
        token stays on device until the next :meth:`step` (no per-submit
        host sync).
        """
        return self.submit_many([(token_ids, payload)])[0]

    def submit_many(
        self, requests: list[tuple[np.ndarray, Any]]
    ) -> list[int]:
        """Admit ``requests`` (``(token_ids, payload)`` pairs) into free
        slots; returns their slot indices in order.

        Plain slots: ONE jitted multi-row insert prefills every prompt
        as an ``[M, P]`` batch and folds the per-row lengths, pending
        tokens, and block-liveness masks into the returned device state —
        one device call and ZERO host syncs per refill cycle, where
        per-request :meth:`submit` used to pay a blocking ``int(first)``
        plus an extra ``.at[row].set`` dispatch each.  First tokens are
        consumed in a single batched transfer at the next :meth:`step`.

        Beam and speculative slots admit sequentially (their inserts
        seed per-slot search/draft state) but share the deferred
        first-token sync.
        """
        if not requests:
            return []
        free = self.free_slots
        if len(requests) > len(free):
            raise RuntimeError(
                f"no free slot for {len(requests)} request(s) "
                f"({len(free)} free); call step() until slots open"
            )
        rows = free[: len(requests)]
        now = time.perf_counter()
        if self.lifecycle is not None:
            for _, payload in requests:
                self.lifecycle.stamp(_trace_key(payload), "prefill")
        if self.beams > 1 or self.draft_layers:
            for row, (token_ids, payload) in zip(rows, requests):
                self._submit_one(row, token_ids, payload, now)
            return rows
        padded = [self._pad_prompt(ids) for ids, _ in requests]
        prompts = np.stack([ids for ids, _ in padded])
        lengths = np.asarray([ln for _, ln in padded], np.int32)
        (self.cache, self._current, self._done, self._remaining,
         firsts) = self._insert_many(
            self.params, self.cache, self._current, self._done,
            self._remaining, jnp.asarray(rows, jnp.int32),
            jnp.asarray(prompts), jnp.asarray(lengths),
            next(self._keys), n_rows=len(rows),
        )
        self.insert_dispatches += 1
        self._pending_firsts.append((firsts, list(rows)))
        for row, (_, payload) in zip(rows, requests):
            # a fresh record per request: step() replaces finished slots
            # with new _Slot()s, but resetting here keeps the per-request
            # contract independent of that cleanup path
            self.slots[row] = _Slot(
                busy=True, budget=self.generate_tokens, payload=payload,
                submitted_at=now,
            )
        self._invalidate_admission_cache()
        return rows

    @property
    def prefix_pool(self):
        """The per-tenant :class:`~.tenancy.PrefixPool` (None when
        tenancy is off or ``prefix_pool == 0``)."""
        return self._prefix_pool

    def export_tenant_homes(self) -> dict:
        """Sticky-home assignments as durable state (core/durable.py);
        see :func:`~.tenancy.export_tenant_homes`."""
        from .tenancy import export_tenant_homes

        return export_tenant_homes(self._tenant_home)

    def import_tenant_homes(self, state: dict) -> int:
        from .tenancy import import_tenant_homes

        return import_tenant_homes(
            self._tenant_home, state, shards=getattr(self, "shards", 1)
        )

    def _route_prefixed(self, keys: list) -> list[int]:
        """Rows for a prefixed admission batch, one per pool key.  The
        single-plane batcher has nowhere to be sticky TO — admission
        order is exactly :attr:`free_slots` order, like
        :meth:`submit_many`.  The sharded plane overrides this with
        affinity-first-then-freest routing."""
        return self.free_slots[: len(keys)]

    def _free_slot_count(self) -> int:
        """Admission capacity as a bare count — the sharded plane
        overrides this with a sum over its per-shard availability so
        the capacity guard never pays the full routed ordering."""
        return len(self.free_slots)

    def _pool_shard_of(self, row: int) -> int:
        """Which pool partition a slot row draws prefix entries from
        (the sharded plane maps rows to their engine shard)."""
        return 0

    def submit_many_prefixed(
        self, requests: list[tuple[str, np.ndarray, np.ndarray, Any]]
    ) -> list[int]:
        """Admit ``(tenant, prefix_ids, token_ids, payload)`` requests
        through the per-tenant prefix pool; returns their slot rows.

        Routing first (:meth:`_route_prefixed` — sticky on the sharded
        plane), then each row's prefix entry is acquired on its row's
        pool partition (LRU hit, or a one-time install prefill on
        miss), then the WHOLE batch prefills as ONE pooled insert: the
        compiled call gathers each row's prefix KV from the pool by
        entry index and runs one suffix chunk forward — a pool hit
        never re-prefills the shared prefix region.  Same zero
        per-request host syncs as :meth:`submit_many`; first tokens
        settle in the same deferred batched transfer."""
        if self._prefix_pool is None:
            raise ValueError(
                "submit_many_prefixed needs tenancy with prefix_pool > 0"
            )
        if not requests:
            return []
        free = self._free_slot_count()
        if len(requests) > free:
            raise RuntimeError(
                f"no free slot for {len(requests)} request(s) "
                f"({free} free); call step() until slots open"
            )
        from .tenancy import prefix_pool_key

        keys = [
            prefix_pool_key(tenant, prefix_ids)
            for tenant, prefix_ids, _, _ in requests
        ]
        rows = self._route_prefixed(keys)
        entry_idx = [
            self._prefix_pool.acquire(
                self._pool_shard_of(row), key, prefix_ids
            )
            for row, key, (_, prefix_ids, _, _) in zip(rows, keys,
                                                       requests)
        ]
        now = time.perf_counter()
        if self.lifecycle is not None:
            for tenant, _, _, payload in requests:
                self.lifecycle.stamp(
                    _trace_key(payload), "prefill", tenant=tenant
                )
        padded = [self._pad_prompt(ids) for _, _, ids, _ in requests]
        prompts = np.stack([ids for ids, _ in padded])
        lengths = np.asarray([ln for _, ln in padded], np.int32)
        (self.cache, self._current, self._done, self._remaining,
         firsts) = self._pooled_insert(
            self.params, self.cache, self._current, self._done,
            self._remaining, jnp.asarray(rows, jnp.int32),
            jnp.asarray(prompts), jnp.asarray(lengths),
            next(self._keys), jnp.asarray(entry_idx, jnp.int32),
            self._prefix_pool.layers, n_rows=len(rows),
        )
        self.insert_dispatches += 1
        self._pending_firsts.append((firsts, list(rows)))
        for row, (tenant, _, _, payload) in zip(rows, requests):
            self.slots[row] = _Slot(
                busy=True, budget=self.generate_tokens, payload=payload,
                submitted_at=now, tenant=tenant,
            )
        self._invalidate_admission_cache()
        return rows

    def tag_tenant(self, rows: list[int], tenants: list[str]) -> None:
        """Label freshly-admitted slots with their tenants (the
        pool-less tenancy path: plain :meth:`submit_many` admission,
        per-tenant attribution still on)."""
        for row, tenant in zip(rows, tenants):
            self.slots[row].tenant = tenant

    @property
    def resume_len(self) -> int:
        """The resume insert's static prompt bucket: a resumed row
        prefills its original (truncated) prompt plus everything it had
        produced, which is at most ``prompt_len + generate_tokens`` —
        within ``max_seq_len`` by the construction-time budget check."""
        return self.prompt_len + self.generate_tokens

    def submit_resume(
        self, resumes: list[tuple[np.ndarray, Any, list, int, float]]
    ) -> list[int]:
        """Re-admit evacuated mid-flight requests into free slots.

        Each resume is ``(token_ids, payload, produced, budget,
        submitted_at)``: the request's original prompt, its payload, the
        tokens it had already produced (and which the final reply must
        keep), its original token budget, and its original admission
        time.  The whole batch re-prefills prompt + produced as ONE
        ``[M, resume_len]`` insert through the same admission plane as
        :meth:`submit_many` — on the sharded plane the rows route
        through :attr:`free_slots`, i.e. onto healthy admitting shards —
        with per-row remaining budgets, so a resumed row decodes exactly
        the continuation its first life had left (greedy: byte-identical
        to never having been interrupted, up to the prefill-vs-decode
        reduction-order caveat every chunked path here carries).
        TTFT is not re-recorded: the request's first token already
        reached the consumer-visible state once.  Plain decode path
        only, like :meth:`adopt_engine`.
        """
        if self.beams > 1 or self.draft_layers:
            raise ValueError(
                "submit_resume supports the plain decode path only"
            )
        if not resumes:
            return []
        free = self.free_slots
        if len(resumes) > len(free):
            raise RuntimeError(
                f"no free slot for {len(resumes)} resumed request(s) "
                f"({len(free)} free); release the rest to the queue"
            )
        rows = free[: len(resumes)]
        prompts = np.zeros((len(resumes), self.resume_len), np.int32)
        lengths = np.zeros((len(resumes),), np.int32)
        budgets = np.zeros((len(resumes),), np.int32)
        for i, (ids, _, produced, budget, _) in enumerate(resumes):
            prior = np.asarray(ids, np.int32).reshape(-1)[: self.prompt_len]
            full = np.concatenate(
                [prior, np.asarray(produced, np.int32)]
            )
            if not 0 <= len(produced) < budget:
                raise ValueError(
                    f"resumed row produced {len(produced)} of budget "
                    f"{budget} tokens — a complete request settles, it "
                    "does not resume"
                )
            if full.size > self.resume_len:
                raise ValueError(
                    f"resume prompt of {full.size} tokens exceeds the "
                    f"resume bucket ({self.resume_len})"
                )
            prompts[i, : full.size] = full
            lengths[i] = max(1, full.size)
            # the insert's first token spends one of the remaining budget
            budgets[i] = budget - len(produced) - 1
        (self.cache, self._current, self._done, self._remaining,
         firsts) = self._resume_insert(
            self.params, self.cache, self._current, self._done,
            self._remaining, jnp.asarray(rows, jnp.int32),
            jnp.asarray(prompts), jnp.asarray(lengths),
            next(self._keys), jnp.asarray(budgets),
            n_rows=len(rows),
        )
        self.insert_dispatches += 1
        self._pending_firsts.append((firsts, list(rows)))
        for row, (_, payload, produced, budget, submitted_at) in zip(
            rows, resumes
        ):
            self.slots[row] = _Slot(
                busy=True, budget=budget, payload=payload,
                produced=list(produced), submitted_at=submitted_at,
                ttft_done=bool(produced),
            )
            if self.lifecycle is not None:
                # the evacuation→resume seam: the trace keeps its first
                # life's stamps; resumes only annotate
                self.lifecycle.note(_trace_key(payload), "resumed")
        self._invalidate_admission_cache()
        return rows

    def _submit_one(self, row, token_ids, payload, now) -> None:
        """Sequential admission for beam and speculative slots."""
        ids, length = self._pad_prompt(token_ids)
        if self.beams > 1:
            (self.cache, self._beam_scores, self._beam_out,
             self._beam_alive, self._beam_emitted,
             self._current) = self._insert(
                self.params, self.cache, self._beam_scores,
                self._beam_out, self._beam_alive, self._beam_emitted,
                self._current, jnp.asarray(row, jnp.int32),
                jnp.asarray(ids), jnp.asarray(length, jnp.int32),
            )
            self.insert_dispatches += 1
            # rounds counts beam steps taken; a budget-1 slot finishes
            # without any (the insert's first expansion is the answer)
            self.slots[row] = _Slot(
                busy=True, budget=self.generate_tokens, payload=payload,
                submitted_at=now,
            )
            self._invalidate_admission_cache()
            return
        (self.cache, self.draft_cache, self._current,
         first) = self._insert(
            self.params, self.cache, self.draft_cache, self._current,
            jnp.asarray(row, jnp.int32), jnp.asarray(ids),
            jnp.asarray(length, jnp.int32), next(self._keys),
        )
        self.insert_dispatches += 1
        self._pending_firsts.append((first, [row]))
        self.slots[row] = _Slot(
            busy=True, budget=self.generate_tokens, payload=payload,
            submitted_at=now,
        )
        self._invalidate_admission_cache()

    def _emit(self, slot: _Slot, token: int) -> None:
        """Append one kept token to a slot — THE one place the eos check
        and the emitted-token counter live (every decode mode's host
        loop funnels through here, so parity across modes is parity of
        device programs, not of bookkeeping)."""
        slot.produced.append(token)
        self.tokens_emitted += 1
        if slot.tenant:
            tenant = _bounded_tenant_key(slot.tenant, self.tenant_tokens)
            self.tenant_tokens[tenant] = (
                self.tenant_tokens.get(tenant, 0) + 1
            )
        if self.lifecycle is not None:
            # host-side timestamp of a token that already settled — no
            # extra dispatch or transfer, the value is in hand
            self.lifecycle.token(_trace_key(slot.payload))
        if self.eos_id is not None and token == self.eos_id:
            slot.done = True

    def _settle_pending_firsts(self) -> None:
        """Consume deferred first tokens — one batched device transfer
        per admission call instead of one blocking sync per request —
        and record time-to-first-token.

        With comms attached, an array whose settle-pull op was already
        dispatched inside the dispatch-ahead window arrived (or is
        arriving) via an async copy that overlapped device compute:
        consuming it is not a blocking host round-trip, so
        ``host_transfers`` counts only the arrays nothing prefetched —
        the strict decrease the comms bench gates on."""
        if not self._pending_firsts:
            return
        pending, self._pending_firsts = self._pending_firsts, []
        comms = self.comms
        blocking = 0
        host: list[tuple[np.ndarray, list[int]]] = []
        for arr, rows in pending:
            op = self._first_ops.pop(id(arr), None)
            host.append((np.asarray(arr), rows))
            if comms is not None and op is not None and op.dispatched:
                comms.finish(op)
            else:
                blocking += 1
        self.host_transfers += blocking
        self._record_firsts(host)

    def _record_firsts(
        self, pending_host: list[tuple[np.ndarray, list[int]]]
    ) -> None:
        """Emit already-host-resident first tokens and record TTFT (the
        transfer-free half of :meth:`_settle_pending_firsts`, split out
        so the sharded plane can fold the fetch into its one combined
        settle transfer per cycle)."""
        now = time.perf_counter()
        for vals, rows in pending_host:
            for token, row in zip(np.asarray(vals).reshape(-1), rows):
                slot = self.slots[row]
                self._emit(slot, int(token))
                if slot.ttft_done:
                    # a resumed (evacuated) row: its TTFT was recorded
                    # in its first life — this is a mid-request token
                    continue
                slot.ttft_done = True
                ttft = now - slot.submitted_at
                self.ttft_sum += ttft
                self.ttft_count += 1
                self.last_ttft_s = ttft
                self.ttft_samples.append(ttft)
                self._pending_ttft_obs.append((None, ttft))
                if self.lifecycle is not None:
                    self.lifecycle.stamp(
                        _trace_key(slot.payload), "first_token",
                        tenant=slot.tenant or None,
                    )
                if slot.tenant:
                    tenant = _bounded_tenant_key(
                        slot.tenant, self.tenant_ttft
                    )
                    samples = self.tenant_ttft.get(tenant)
                    if samples is None:
                        samples = self.tenant_ttft[tenant] = (
                            self._tenant_ttft_deque()
                        )
                    # arrival-based when the queue stamped the request
                    # (SentTimestamp), admission-based otherwise
                    sample = (
                        max(0.0, self._epoch_now() - slot.arrived_at)
                        if slot.arrived_at is not None else ttft
                    )
                    samples.append(sample)
                    self.tenant_ttft_sum[tenant] = (
                        self.tenant_ttft_sum.get(tenant, 0.0) + sample
                    )
                    self.tenant_ttft_count[tenant] = (
                        self.tenant_ttft_count.get(tenant, 0) + 1
                    )
                    self._pending_ttft_obs.append((tenant, sample))
                self._note_ttft(row, ttft)

    def _note_ttft(self, row: int, ttft: float) -> None:
        """Per-row TTFT hook (no-op here; the sharded plane attributes
        the sample to the row's shard for the healthy-shard SLO gate)."""

    def _needs_decode(self, slot: _Slot) -> bool:
        return slot.busy and not slot.done and len(slot.produced) < slot.budget

    def _finish_ready(self) -> list[tuple[Any, np.ndarray]]:
        """Free every slot whose request completed; returns the finished
        ``(payload, tokens)`` pairs, eos-padded to the budget exactly
        like ``generate``."""
        finished = []
        quiesce = []
        for row, slot in enumerate(self.slots):
            if slot.busy and (slot.done or len(slot.produced) >= slot.budget):
                tokens = slot.produced
                if len(tokens) < slot.budget:
                    # eos fired early: the slot frees NOW; pad the reply
                    # to the static budget exactly like generate does
                    tokens = tokens + [self.eos_id] * (
                        slot.budget - len(tokens)
                    )
                if slot.degraded and not slot.done:
                    # finished at a DEGRADED budget (not eos): the
                    # device row still thinks it has budget left
                    quiesce.append(row)
                if self.lifecycle is not None:
                    self.lifecycle.stamp(
                        _trace_key(slot.payload), "completed"
                    )
                finished.append(
                    (slot.payload, np.asarray(tokens, np.int32))
                )
                self.slots[row] = _Slot()
        if finished:
            self._invalidate_admission_cache()
        if quiesce:
            self._quiesce_rows(quiesce)
        return finished

    def step(self) -> list[tuple[Any, np.ndarray]]:
        """Advance every active slot; return finished requests as
        ``(payload, continuation_tokens)`` pairs (their slots are free
        again on return).  Plain slots advance ONE token per step
        (``decode_block`` of them per device call when ``decode_block >
        1`` — results identical, scheduling coarser); speculative slots
        (``draft_layers > 0``) advance 1..k+1 tokens per round, two
        rounds pipelined when completion is provable in advance.
        Finished = budget reached or eos emitted; either way the tokens
        are padded with ``eos_id`` to the budget (matching ``generate``'s
        post-eos padding).  No-op when nothing is active."""
        if self.active == 0 and not self._tainted:
            # tainted rows need one more settle to clear even with no
            # active request (the reference path never taints, so its
            # early-out is byte-identical to today's)
            return []
        if self.beams > 1:
            return self._step_beam()
        if self.draft_layers:
            return self._step_spec()
        if self._block_engine:
            # routed on the CONSTRUCTED engine, not the live block size:
            # a live decode_block knob change can take the block engine
            # to 1 (a one-step scan), which is not the single-step path
            return self._step_block()
        return self._step_single()

    def _step_single(self) -> list[tuple[Any, np.ndarray]]:
        """The unpipelined engine cycle (``decode_block == 1``): one
        token per device call, host-consumed immediately — today's
        behavior, byte for byte, and the bench's comparison baseline."""
        self._settle_pending_firsts()
        # rows whose budget is a single token (or that already hit eos)
        # never need a decode step
        needs = [self._needs_decode(s) for s in self.slots]
        if any(needs):
            self.cache, nxt = self._decode(
                self.params, self.cache, self._current, next(self._keys)
            )
            self.decode_dispatches += 1
            nxt_host = np.asarray(nxt)
            self.host_transfers += 1
            for row, slot in enumerate(self.slots):
                if needs[row]:
                    self._emit(slot, int(nxt_host[row]))
            self._current = nxt
        return self._finish_ready()

    def _block_keys(self):
        if self.temperature > 0.0 or self.mesh is not None:
            return jnp.stack(
                [next(self._keys) for _ in range(self.decode_block)]
            )
        # greedy single-chip: _pick ignores the key operand (same dummy
        # generate() scans over)
        return jnp.zeros((self.decode_block, 2), jnp.uint32)

    def _step_block(self) -> list[tuple[Any, np.ndarray]]:
        """The pipelined engine cycle (``decode_block > 1``): dispatch
        block N+1 BEFORE consuming block N.

        The on-device ``done``/``remaining`` masks make the dispatch
        independent of block N's outcome — rows that finish mid-block
        stay frozen on device, rows admitted this cycle were folded in
        by the insert — so the host's entire settle/reply/refill pass
        for cycle N overlaps device compute for cycle N+1.  The sync is
        one ``np.asarray`` of an already-dispatched (usually finished)
        block, not an eager wait on the block just launched.
        """
        new_block = None
        busy = sum(s.busy for s in self.slots)
        if busy and self._pending_decode_block is None:
            # a staged decode_block swap skips exactly one dispatch:
            # the in-flight block settles below at the OLD size, the
            # swap lands, and the next cycle dispatches at the new one
            # — the re-dispatch boundary, never a mid-block resize
            (self.cache, self._current, self._done, self._remaining,
             tokens, counts) = self._block_fn(
                self.params, self.cache, self._current, self._done,
                self._remaining, self._block_keys(),
            )
            self.decode_dispatches += 1
            new_block = (tokens, counts, busy)
        if self.comms is not None:
            # the dispatch-ahead window: the block dispatched above (or
            # the one still in flight) occupies the device — start the
            # queued transfer pulls now so their copies hide behind it
            self._comms_flush(
                overlapped=(new_block is not None
                            or self._pending_block is not None),
            )
        self._settle_pending_firsts()
        pending, self._pending_block = self._pending_block, new_block
        if pending is not None:
            tokens, counts, dispatched_busy = pending
            block_op, self._block_op = self._block_op, None
            # ONE host sync for the whole settled block (tokens + counts
            # fetched together), not one per array
            toks_host, counts_host = jax.device_get((tokens, counts))
            if (self.comms is not None and block_op is not None
                    and block_op.dispatched):
                # the comms flush prefetched this block's arrays while
                # the next block computed — not a blocking round-trip
                self.comms.finish(block_op)
            else:
                self.host_transfers += 1
            self.block_capacity += self.decode_block * dispatched_busy
            self.block_tokens += int(counts_host.sum())
            for row, slot in enumerate(self.slots):
                if not slot.busy:
                    continue
                # rows admitted after this block was dispatched idled
                # through it frozen (count 0); post-eos positions were
                # never counted — the host keeps a contiguous prefix
                for token in toks_host[: int(counts_host[row]), row]:
                    if slot.done or len(slot.produced) >= slot.budget:
                        break
                    self._emit(slot, int(token))
        # every block dispatched before the last quiesce has now
        # settled (there is only ever one in flight), so tainted rows
        # are safe to admit again; rows quiesced by the finish below
        # re-taint for the next cycle
        if self._tainted:
            self._invalidate_admission_cache()
        self._tainted.clear()
        if self._pending_block is None:
            # nothing in flight at the old size: a staged decode_block
            # swap is safe to land — the next dispatch uses it
            self._apply_pending_decode_block()
        return self._finish_ready()

    def _dispatch_spec_round(self, mask: list[bool]):
        """Launch one draft-and-verify round over the masked rows;
        returns the (device-resident) ``(round_tokens, n)`` pair."""
        active = jnp.asarray(mask)
        if self.mesh is not None:
            active = jax.device_put(active, self._rows_shard)
        (self.cache, self.draft_cache, self._current, round_tokens,
         n) = self._spec(
            self.params, self.draft_params, self.cache,
            self.draft_cache, self._current, active, next(self._keys),
        )
        self.decode_dispatches += 1
        return round_tokens, n

    def _consume_spec_round(self, mask: list[bool], handle) -> None:
        toks_host, n_host = jax.device_get(handle)
        self.host_transfers += 1
        for row, slot in enumerate(self.slots):
            if not mask[row]:
                continue
            slot.rounds += 1
            slot.accepted += int(n_host[row])
            self.spec_rounds += 1
            self.spec_accepted += int(n_host[row])
            for token in toks_host[row, : int(n_host[row]) + 1]:
                if slot.done or len(slot.produced) >= slot.budget:
                    break
                self._emit(slot, int(token))

    def _step_spec(self) -> list[tuple[Any, np.ndarray]]:
        """One (or two, pipelined) draft-and-verify rounds.

        Deferred sync: a row that will need another round even on FULL
        acceptance of the in-flight one (``produced + k + 1 < budget``)
        is known NOW, so its next round is dispatched before the host
        consumes this round's ``(round_tokens, n)`` — the first consume
        then overlaps the second round's device time.  ``eos_id`` makes
        any row's completion unknowable in advance, so the overlap only
        engages for eos-free serving; masked-off rows keep their pending
        token and catch up next cycle, which also caps the cache
        overshoot at the same ``budget + k`` bound a single worst-case
        round already has (the 2k slack reserved at construction).
        """
        self._settle_pending_firsts()
        needs = [self._needs_decode(s) for s in self.slots]
        if any(needs):
            first_round = self._dispatch_spec_round(needs)
            k1 = self.draft_tokens + 1
            certain = [
                needs[row] and self.eos_id is None
                and len(slot.produced) + k1 < slot.budget
                for row, slot in enumerate(self.slots)
            ]
            second_round = (
                self._dispatch_spec_round(certain)
                if any(certain) and self.spec_overlap else None
            )
            self._consume_spec_round(needs, first_round)
            if second_round is not None:
                self._consume_spec_round(certain, second_round)
        return self._finish_ready()


def drain_ttft_histograms(batcher, metrics) -> None:
    """Drain a batcher's pending TTFT samples into the cumulative
    histogram families (unlabeled engine-wide ``ttft_seconds`` plus the
    per-tenant ``tenant_time_to_first_token_seconds``, label-bounded
    upstream by ``_bounded_tenant_key``).  Module-level because TWO
    consumers drain on their own cadence: the worker's own
    ``_update_metrics`` and the fleet pool's (pool replicas never get a
    worker-level metrics registry — unlabeled worker gauges would stomp
    each other — but cumulative histograms MERGE correctly across
    replicas, so the pool drains every member into one family)."""
    pending = getattr(batcher, "_pending_ttft_obs", None)
    if not pending:
        return
    while pending:
        tenant, seconds = pending.popleft()
        if tenant is None:
            metrics.observe_histogram(
                "ttft_seconds", seconds,
                "Seconds from request admission to its first "
                "generated token being host-visible (cumulative "
                "histogram over the worker's lifetime).",
            )
        else:
            metrics.observe_histogram(
                "tenant_time_to_first_token_seconds", seconds,
                "Seconds from queue arrival (SentTimestamp when "
                "the queue stamps it, else admission) to the first "
                "generated token, per tenant — the cumulative-"
                "histogram form of the tenant_ttft_seconds gauge.",
                labels=(("tenant", tenant),),
            )


class ContinuousWorker:
    """A queue-draining worker built on :class:`ContinuousBatcher`.

    Same at-least-once contract as :class:`.service.QueueWorker`: a
    message is deleted only after its continuation is fully generated.
    Unlike the batch worker, a slow batch never blocks fresh messages —
    slots refill the moment they finish (and an ``eos_id`` frees a slot
    early).  Full reply parity with the batch worker: ``tokenizer``
    turns it text-in/text-out, ``result_queue`` +
    ``ServiceConfig.result_queue_url`` publish one JSON reply per
    message ({"tokens": [...]} trimmed at eos, + {"text": ...} with a
    tokenizer, + the request's MessageId as "request_id").
    """

    def __init__(
        self,
        queue,
        params: Any,
        model_config: Any,
        service_config,
        *,
        family: str = "gpt",
        tokenizer=None,
        result_queue=None,
        mesh=None,
        prefix_cache: dict | None = None,
        draft_layers: int = 0,
        draft_tokens: int = 4,
        beams: int = 1,
        length_penalty: float = 0.0,
        sharded: bool | None = None,
        now_fn=None,
        tenancy=None,
    ) -> None:
        if service_config.generate_tokens < 1:
            raise ValueError(
                "ContinuousWorker is generate-mode serving; set "
                "ServiceConfig.generate_tokens >= 1"
            )
        if service_config.result_queue_url and result_queue is None:
            # same explicit-client rule as QueueWorker: in-memory queues
            # ignore urls, so defaulting replies onto the input queue
            # object would self-feed
            raise ValueError(
                "result_queue_url is set but no result_queue client was "
                "given"
            )
        self.queue = queue
        self.config = service_config
        self.tokenizer = tokenizer
        self.result_queue = result_queue
        if tenancy is not None and tenancy.prefix_pool > 0 \
                and tenancy.prefix_len < 1:
            # the pool's static prefix bucket defaults to the prompt
            # bucket — one knob fewer, and the bench/demo traffic
            # generators size their shared prefixes to it
            import dataclasses

            tenancy = dataclasses.replace(
                tenancy, prefix_len=service_config.seq_len
            )
        self.tenancy = tenancy
        batcher_kwargs = dict(
            family=family,
            temperature=service_config.temperature,
            top_k=service_config.top_k,
            top_p=service_config.top_p,
            eos_id=service_config.eos_id,
            sample_seed=service_config.sample_seed,
            mesh=mesh,
            quantized_kv=service_config.quantized_kv,
            prefix_cache=prefix_cache,
            draft_layers=draft_layers,
            draft_tokens=draft_tokens,
            beams=beams,
            length_penalty=length_penalty,
            decode_block=service_config.decode_block,
            tenancy=tenancy,
        )
        shards = getattr(service_config, "shards", 1)
        if sharded is None:
            sharded = shards > 1
        if draft_layers > 0 and (sharded or tenancy is not None):
            # speculative x shards/tenancy: these combinations run on
            # the decode-plane engine (planes/engine.py), which gang-
            # steps draft-and-verify rounds over the whole [S*B] row
            # axis — tenancy without --shards rides the S=1 end of the
            # same plane (the plain spec engine has no tenant staging
            # surface).  The fused single-tenant path below is
            # unchanged.
            from ..planes.engine import DecodePlaneBatcher

            plane_kwargs = dict(batcher_kwargs)
            plane_kwargs.pop("draft_layers")
            plane_kwargs.pop("draft_tokens")
            self.batcher = DecodePlaneBatcher(
                params, model_config,
                shards=shards,
                shard_slots=service_config.batch_size,
                prompt_len=service_config.seq_len,
                generate_tokens=service_config.generate_tokens,
                spec_layers=draft_layers,
                spec_tokens=draft_tokens,
                **plane_kwargs,
            )
        elif sharded:
            # the sharded serving plane: `shards` gang-stepped engine
            # shards of batch_size slots each behind this one worker's
            # admission loop (ONE decode dispatch per cycle however many
            # shards; see workloads/shard_plane.py).  `sharded=True`
            # forces the plane even at shards=1 — the S=1 end of the
            # scaling curve, and a ShardedWorkerPool pinned to one shard,
            # must run the gang engine, not the plain block engine.
            from .shard_plane import ShardedBatcher

            self.batcher: ContinuousBatcher = ShardedBatcher(
                params, model_config,
                shards=shards,
                shard_slots=service_config.batch_size,
                prompt_len=service_config.seq_len,
                generate_tokens=service_config.generate_tokens,
                **batcher_kwargs,
            )
        else:
            self.batcher = ContinuousBatcher(
                params, model_config,
                batch_size=service_config.batch_size,
                prompt_len=service_config.seq_len,
                generate_tokens=service_config.generate_tokens,
                **batcher_kwargs,
            )
        self.processed = 0
        # fair admission: the staging/DRR layer between the queue and
        # the batcher (tenancy only; None keeps _refill on the exact
        # reference code path).  Staging is bounded at one refill's
        # lookahead per tenant and two engine-fulls total — overflow
        # hands messages back to the queue (visibility 0), never drops.
        self._fair = None
        if tenancy is not None:
            from .tenancy import FairAdmission

            total_slots = len(self.batcher.slots)
            fair_limits = dict(
                per_tenant_limit=(
                    tenancy.staging_per_tenant
                    or max(1, total_slots)
                ),
                total_limit=(
                    tenancy.staging_total or max(2, 2 * total_slots)
                ),
            )
            if getattr(tenancy, "admission_shards", 1) > 1:
                # the sharded admission plane (ISSUE 19): N crash-
                # tolerant staging shards behind the same facade —
                # admission_shards=1 never imports the module, so the
                # single plane stays byte-identical to PR 11
                from .admission_shards import ShardedAdmission

                self._fair = ShardedAdmission(tenancy, **fair_limits)
            else:
                self._fair = FairAdmission(tenancy, **fair_limits)
        # uniquely-answered completions per tenant (exactly-once: the
        # fleet's duplicate-suppression path never reaches the counter,
        # and TTL sheds / malformed drops are answered but not counted)
        self.completed_by_tenant: dict[str, int] = {}
        # every tenant label ever exported as a Prometheus series —
        # bounded by _bounded_tenant_key, re-exported every cycle so no
        # series goes permanently stale (see _update_metrics)
        self._gauge_tenants: dict[str, bool] = {}
        # request-TTL clock (``ServiceConfig.request_ttl_s``): must share
        # a time base with the queue's SentTimestamp stamps — epoch
        # seconds for AWS SQS (the default), a FakeClock's now for
        # deterministic tests/benches
        self._now = now_fn or time.time
        # per-tenant TTFT shares the TTL clock's epoch base (so
        # FakeClock episodes and SQS SentTimestamps agree)
        self.batcher._epoch_now = self._now
        # requests shed per reason — "ttl" (already older than
        # request_ttl_s at admission), "degraded" (overload tier 1 cut
        # the request's token budget; answered short, never dropped),
        # "pressure" (overload tier 3 shed it from staging with an
        # explicit error reply), "decode_deadline" (the decode phase
        # blew its per-token SLO budget; shed mid-decode with an
        # explicit error reply).  `shed` (the dashboard-compatible
        # unlabeled requests_shed_total) is their sum.
        self.shed_by_reason: dict[str, int] = {
            "ttl": 0, "degraded": 0, "pressure": 0, "decode_deadline": 0,
        }
        # the overload ladder (tenancy.shed_tiers > 0): _run_ladder
        # measures pressure and applies the active tier's actions once
        # per tenant refill cycle; None = no ladder, the PR 8 TTL shed
        # stays the only degradation.  On the SHARDED admission plane
        # each AdmissionShard owns its own ladder instead (one shard's
        # overload degrades its tenants, not everyone's) — see
        # _run_shard_ladders.
        self.ladder = None
        self._degrade_tenants: frozenset = frozenset()
        self._degraded_tokens = max(
            1, service_config.generate_tokens // 2
        )
        if tenancy is not None and tenancy.shed_tiers > 0 \
                and getattr(tenancy, "admission_shards", 1) == 1:
            from .tenancy import OverloadLadder

            self.ladder = OverloadLadder(tenancy.shed_tiers)
        # liveness counter the fleet's idle-wedge watchdog keys on: a
        # healthy worker bumps it every refill pass (poll, poll-backoff
        # tick, or full-slots early-out alike); a wedged run_once never
        # reaches _refill, so the counter freezes
        self.refill_cycles = 0
        # wall-clock engine-cycle spans (same metrics surface as
        # QueueWorker: obs attaches this to /metrics)
        from ..utils.profiling import SpanTimer

        self.timer = SpanTimer()
        import threading

        # Created eagerly (not lazily in run_forever) so a stop() landing
        # before run_forever starts is sticky, like ControlLoop.stop —
        # the lazy event silently dropped pre-start stops.
        self._stop = threading.Event()
        self._running = False
        self._poll_backoff = 0
        # optional WorkloadMetrics registry (attach_metrics); gauges
        # refresh once per engine cycle
        self.metrics = None
        self._served_since: float | None = None
        # optional request-lifecycle registry (attach_lifecycle);
        # None = tracing off = the reference path byte for byte
        self.lifecycle = None

    # poll throttle: after an EMPTY zero-wait receive while slots are
    # still decoding, skip this many cycles before polling again — one
    # billed ReceiveMessage per generated token would be absurd on SQS
    POLL_BACKOFF_CYCLES = 16

    def _settle(
        self, message, tokens: np.ndarray | None, *,
        error: str | None = None, counted: bool = True,
    ) -> bool:
        """Reply (when configured) and delete one finished message.
        ``tokens=None`` marks a request answered with an error instead
        of a result: ``error`` names it (default "malformed body"; the
        TTL shed path passes "expired").  ``counted=False`` marks a
        settle that does NOT ride the run_once completion count
        (admission-time sheds and malformed drops) — unused here, but
        the fleet override's duplicate accounting depends on it.
        Returns True when this call answered the request; the fleet
        override returns False when it consumed an already-replied
        duplicate instead (the TTL shed counter keys off this, so a
        redelivered-then-expired copy is counted as a duplicate, not
        double-booked as a shed too)."""
        import json

        from .service import build_token_reply, request_id

        tenant = message.get("_tenant", "")
        if self.config.result_queue_url:
            if tokens is None:
                payload = {"error": error or "malformed body"}
            else:
                payload = build_token_reply(
                    tokens, self.config.eos_id, self.tokenizer
                )
            payload["request_id"] = request_id(message)
            if tenant:
                # replies carry the tenant label so consumers (and the
                # bench) can account completions per tenant — dedup by
                # request_id still decides exactly-once, the label only
                # attributes it
                payload["tenant"] = tenant
            # reply BEFORE deleting the input (at-least-once: consumers
            # may see duplicates, never lose a result)
            self.result_queue.send_message(
                self.config.result_queue_url, json.dumps(payload)
            )
        self.queue.delete_message(
            self.config.queue_url, message["ReceiptHandle"]
        )
        if tenant and tokens is not None:
            tenant = _bounded_tenant_key(tenant, self.completed_by_tenant)
            self.completed_by_tenant[tenant] = (
                self.completed_by_tenant.get(tenant, 0) + 1
            )
        lc = self.lifecycle
        if lc is not None:
            # THE reply stamp: this call answered the request (sent the
            # reply, deleted the input).  Error settles (TTL sheds,
            # malformed bodies) may never have been admitted, so their
            # arrival is stamped here too (idempotent).  The fleet's
            # duplicate-consuming override never reaches this line.
            rid = request_id(message)
            lc.arrival(
                rid, sent=self._sent_epoch(message),
                tenant=message.get("_tenant") or None,
            )
            lc.settle(
                rid,
                error=(
                    (error or "malformed body") if tokens is None
                    else None
                ),
            )
        return True

    @property
    def staged(self) -> int:
        """Requests parked in fair-admission staging (0 with tenancy
        off): received from the queue — their receipt handles are live —
        but not yet admitted to a slot.  Idleness and drain decisions
        must count them as in-flight work."""
        return self._fair.staged if self._fair is not None else 0

    @property
    def shed(self) -> int:
        """Requests shed over the worker's lifetime, all reasons summed
        (the unlabeled ``requests_shed_total`` series — per-reason
        counts live in :attr:`shed_by_reason`)."""
        return sum(self.shed_by_reason.values())

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py): the worker's admission
    # plane — DRR/EDF accounting + flood classification (FairAdmission),
    # the overload ladder, and the sticky tenant→home-shard map.  Staged
    # message CONTENTS never serialize (live receipt handles; the queue
    # redelivers them), only the accounting that a crash must not reset.
    # ------------------------------------------------------------------

    def export_admission_state(self) -> dict:
        state: dict = {"records": 0}
        if self._fair is not None:
            state["fair"] = self._fair.export_state()
            state["records"] += state["fair"].get("records", 0)
        if self.ladder is not None:
            state["ladder"] = self.ladder.export_state()
            state["records"] += state["ladder"].get("records", 0)
        homes = self.batcher.export_tenant_homes()
        if homes.get("records"):
            state["homes"] = homes
            state["records"] += homes["records"]
        return state

    def import_admission_state(
        self, state: dict, *, rebase: float = 0.0,
        now: "float | None" = None, max_age_s: float = 0.0,
    ) -> int:
        recovered = 0
        fair = state.get("fair")
        if self._fair is not None and isinstance(fair, dict):
            recovered += self._fair.import_state(
                fair, rebase=rebase, now=now, max_age_s=max_age_s
            )
        ladder = state.get("ladder")
        if self.ladder is not None and isinstance(ladder, dict):
            recovered += self.ladder.import_state(ladder)
        homes = state.get("homes")
        if isinstance(homes, dict):
            recovered += self.batcher.import_tenant_homes(homes)
        return recovered

    def _note_shed(self, reason: str) -> None:
        self.shed_by_reason[reason] += 1

    def _refill(self) -> int:
        """Pull up to free-slot-count messages and prefill them in.
        With tenancy configured the pull goes through the fair-admission
        staging layer instead (:meth:`_refill_tenant`); tenancy=None is
        the reference path, byte for byte."""
        if self.tenancy is not None:
            return self._refill_tenant()
        self.refill_cycles += 1  # liveness: this worker's loop is running
        # capacity only — the bare count, not the routed ordering (the
        # sharded plane's free_slots pays a freest-first merge over
        # S x B rows; the refill only needs to size its receive, and
        # the actual admission consumes the ordering once inside
        # submit_many).  ROADMAP item 1's remaining per-cycle debt.
        free = self.batcher._free_slot_count()
        if not free:
            return 0
        if self._poll_backoff > 0:
            self._poll_backoff -= 1
            return 0
        messages = self.queue.receive_messages(
            self.config.queue_url, max_messages=free,
            wait_time_s=0 if self.batcher.active else
            self.config.receive_wait_s,
        )
        if not messages and self.batcher.active:
            self._poll_backoff = self.POLL_BACKOFF_CYCLES
        self._admit(messages)
        return len(messages)

    def _refill_tenant(self) -> int:
        """The fair-admission refill: receive into bounded per-tenant
        staging, then PICK this cycle's admission batch by deficit
        round robin instead of arrival order.  The picked batch still
        prefills as one insert (:meth:`_submit_parsed`) — fairness is
        host bookkeeping, not device work.  Staging overflow (a tenant
        flooding past its lookahead cap) hands messages back to the
        queue with visibility 0: backpressure, never loss."""
        self.refill_cycles += 1  # liveness: this worker's loop is running
        self._fair.note_cycle()  # decay the arrival-rate classifier
        # capacity only (see _refill): the DRR pick is sized by the
        # count; the routed ordering is paid once by the admission
        free = self.batcher._free_slot_count()
        messages = []
        if self._poll_backoff > 0:
            self._poll_backoff -= 1
        elif self._fair.room > 0:
            messages = self.queue.receive_messages(
                self.config.queue_url, max_messages=self._fair.room,
                wait_time_s=0 if (self.batcher.active
                                  or self._fair.staged) else
                self.config.receive_wait_s,
            )
            if not messages and self.batcher.active:
                self._poll_backoff = self.POLL_BACKOFF_CYCLES
        nack = getattr(self.queue, "change_message_visibility", None)
        for message in messages:
            if self._shed_if_expired(message):
                continue
            parsed = self._parse_for_admit(message)
            if parsed is None:
                self._settle(message, None, counted=False)
                continue
            tenant = parsed[0]
            if self.lifecycle is not None:
                # arrival must precede the staged stamp even when the
                # queue does not stamp SentTimestamp (then it is the
                # receive time) — stamped here, not at admission
                self.lifecycle.arrival(
                    _request_id(message),
                    sent=self._sent_epoch(message), tenant=tenant,
                )
            # the arrival-based TTFT deadline rides into staging so the
            # EDF blend can see it at pick time (None = no SLO / no
            # queue stamp — the request can never jump the quantum)
            deadline = self.tenancy.deadline_of(
                tenant, self._sent_epoch(message)
            )
            if not self._fair.stage(tenant, parsed + (message,),
                                    deadline=deadline,
                                    message_id=_request_id(message)):
                # the tenant's staging cap is the fairness backstop:
                # hand the message back NOW so other tenants' traffic
                # gets received next cycle (no nack support = stage
                # anyway; bounded-memory beats a redelivery stall)
                if nack is not None:
                    nack(self.config.queue_url,
                         message["ReceiptHandle"], 0)
                    self._fair.overflow_total += 1
                else:
                    self._fair.drr.push(tenant, parsed + (message,),
                                        deadline=deadline)
            self._poll_backoff = 0  # staged work: keep the loop hot
        if self.ladder is not None:
            self._run_ladder()
        elif self.tenancy.shed_tiers > 0 and \
                hasattr(self._fair, "shards"):
            self._run_shard_ladders()
        now = self._now()
        admit: list = []
        while len(admit) < free:
            picked = self._fair.pick(free - len(admit), now=now)
            if not picked:
                break
            shed_any = False
            for tenant, item in picked:
                # expired while staged: the same shed contract as
                # arrival-time sheds (answered, never dropped) — but
                # the pick CHARGED the tenant's deficit for a request
                # that consumes no slot, so the charge is refunded
                # (without it a flood of expired/redelivered copies
                # silently shrinks the tenant's future share) and the
                # freed room is re-picked so no slot idles while other
                # tenants still have staged work
                if self._shed_if_expired(item[3]):
                    self._fair.drr.refund(tenant, item)
                    shed_any = True
                else:
                    if self.lifecycle is not None:
                        self.lifecycle.stamp(
                            _request_id(item[3]), "picked",
                            tenant=tenant,
                        )
                    admit.append(item)
            if not shed_any:
                break
        if admit:
            self._submit_parsed(admit)
        return len(admit)

    def _overload_pressure(self) -> float:
        """The ladder's scalar pressure: staged-backlog fraction gated
        by slot occupancy AFTER the imminent admission.  A full
        staging area behind genuinely idle slots is a transient (the
        next pick drains it) and a full engine with empty staging is
        just steady-state load — overload is BOTH at once.  Free slots
        that this very cycle's pick is about to fill count as occupied
        (raw at-this-instant occupancy dips to near zero every time a
        synchronized batch completes, which would make the pressure
        flap at full overload).  The prefix pool's memory enters the
        ladder as tier 2's action target (its resident fraction is
        what the tier shrinks), not as a pressure term: a warm pool is
        healthy, not overloaded."""
        slots = len(self.batcher.slots)
        if not slots:
            return 0.0
        staged = self._fair.staged
        free = slots - self.batcher.active
        occupancy = min(
            1.0, (self.batcher.active + min(staged, free)) / slots
        )
        staged_frac = min(1.0, staged / self._fair.total_limit)
        return staged_frac * occupancy

    def _run_ladder(self) -> None:
        """Measure pressure, advance the ladder, apply the active
        tier's actions (tier 1: mark over-share tenants for degraded
        budgets at admission; tier 2: + evict cold prefix-pool entries;
        tier 3: + shed staged requests with explicit error replies).
        Runs once per tenant refill cycle, before the pick."""
        # no explicit `now`: the ladder stamps transition events with
        # time.perf_counter(), the same timebase every other trace
        # producer (PrefixPool, fleet events) uses — passing the epoch
        # TTL clock here would put overload instants decades off the
        # merged Chrome-trace timeline
        tier = self.ladder.update(self._overload_pressure())
        self._degrade_tenants = (
            self._fair.over_share() if tier >= 1 else frozenset()
        )
        pool = self.batcher.prefix_pool
        if tier >= 2 and pool is not None:
            pool.evict_cold(max(1, pool.entries // 2))
        if tier >= 3:
            target = int(
                self.ladder.exit_threshold(3) * self._fair.total_limit
            )
            # tier 3 implies tier 1: reuse the over-share set computed
            # above instead of re-running the O(tenants) classifier
            self._shed_pressure(target, self._degrade_tenants)

    def _run_shard_ladders(self) -> None:
        """The sharded admission plane's ladder pass: each alive
        AdmissionShard measures its OWN pressure (its staged fraction,
        gated by the shared engine's occupancy) and advances its own
        ladder — one shard's flood engages tier actions for its slice
        of tenants without degrading another shard's.  The degrade set
        is the union across shards, and tier-3 sheds run per shard
        against that shard's staging; gossip then shares every flood
        classification plane-wide (a coalition classified on its home
        shard stays classified wherever a kill fails it over)."""
        fair = self._fair
        slots = len(self.batcher.slots)
        if not slots:
            return
        free = slots - self.batcher.active
        occupancy = min(
            1.0,
            (self.batcher.active + min(fair.staged, free)) / slots,
        )
        degrade: set = set()
        pool = self.batcher.prefix_pool
        for shard in fair.shards:
            if not shard.alive or shard.ladder is None:
                continue
            staged_frac = min(
                1.0, shard.fair.staged / shard.fair.total_limit
            )
            tier = shard.ladder.update(staged_frac * occupancy)
            if tier < 1:
                continue
            flood = shard.fair.over_share()
            degrade |= set(flood)
            if tier >= 2 and pool is not None:
                pool.evict_cold(max(1, pool.entries // 2))
            if tier >= 3:
                target = int(
                    shard.ladder.exit_threshold(3)
                    * shard.fair.total_limit
                )
                self._shed_pressure(target, flood, fair=shard.fair)
        fair.gossip()
        self._degrade_tenants = frozenset(degrade)

    def _shed_pressure(self, target: int, over_share,
                       fair=None) -> None:
        """Tier 3: shed staged requests down to ``target`` — ONLY from
        tenants currently over their weight share (the flood
        signature; a compliant tenant's requests are served late, not
        dropped, however overloaded the plane is).  Within the
        over-share set, first the requests already past their TTFT
        deadline (most over-SLO first: nobody is waiting for them),
        then the NEWEST arrivals of the most-over-share (staged depth
        / weight) tenant, so the lowest-weight deepest-backlog flooder
        absorbs the shed.  Every shed is an explicit error reply
        through the normal settle path — exactly-once (the fleet's
        reply registry dedups redelivered copies before the counter),
        never a silent drop.  ``fair`` scopes the shed to one
        admission shard's staging (the sharded plane's per-shard
        tier 3); None = the worker's whole plane."""
        fair = fair if fair is not None else self._fair
        drr = fair.drr
        now = self._now()
        # eligibility comes from the SUSTAINED unique-message offered
        # rate (FairAdmission.over_share), never instantaneous staged
        # depth: the staging caps flatten every backlogged tenant to
        # similar depths, so depth ratios cannot tell a coalition
        # member from a victim queued behind it — sustained NEW-work
        # rate can.  Two classes within the flood set:
        # - best-effort (no-SLO) flooders absorb the shed (tail pass);
        # - SLO-carrying tenants are near-unsheddable (an SLO is the
        #   no-shed contract): only an UNAMBIGUOUS premium flood
        #   (PREMIUM_FLOOD_FACTOR x the rate floor — a victim's
        #   backlog clump can never sustain that on unique messages)
        #   loses requests, and then only ones already past deadline.
        over = {t for t in over_share if drr.depth(t) > 0}
        best_effort = {
            t for t in over if self.tenancy.slo_of(t) <= 0
        }
        premium_bar = (
            fair.PREMIUM_FLOOD_FACTOR * fair.OVER_SHARE_MIN_RATE
        )
        premium_flood = {
            t for t in over - best_effort
            if fair.arrival_rate.get(t, 0.0) >= premium_bar
        }
        if not best_effort and not premium_flood:
            return  # uniform overload: everyone is compliant — serve
        # one staged count and one depths snapshot, decremented as the
        # loops pop — the shed loop runs on already-overloaded cycles,
        # so an O(tenants)/O(queues) rescan per shed would pile host
        # work on exactly the wrong cycles
        staged = fair.staged
        while premium_flood and staged > target:
            popped = drr.pop_over_deadline(now, eligible=premium_flood)
            if popped is None:
                break
            staged -= 1
            self._shed_item(popped[1])
        depths = {
            t: d for t, d in drr.depths().items()
            if d > 0 and t in best_effort
        }
        while depths and staged > target:
            victim = max(
                depths,
                key=lambda t: (
                    depths[t] / self.tenancy.weight_of(t), t
                ),
            )
            item = drr.pop_tail(victim)
            if item is None:
                depths.pop(victim)
                continue
            staged -= 1
            depths[victim] -= 1
            if depths[victim] <= 0:
                depths.pop(victim)
            self._shed_item(item)

    def _shed_item(self, item) -> None:
        if self._settle(item[3], None,
                        error="shed under overload pressure",
                        counted=False):
            self._note_shed("pressure")

    def _parse_for_admit(self, message: dict):
        """One message -> ``(tenant, prefix_ids, ids)`` (tenancy) or
        ``("", None, ids)`` (reference path); None = malformed."""
        from .service import parse_request_body, parse_tenant_request

        if self.tenancy is None:
            ids = parse_request_body(message["Body"], self.tokenizer)
            return None if ids is None else ("", None, ids)
        tenant, prefix_ids, ids = parse_tenant_request(
            message["Body"], self.tokenizer,
            default_tenant=self.tenancy.tenants[0],
        )
        if ids is None:
            return None
        message["_tenant"] = tenant
        return (tenant, prefix_ids, ids)

    def _submit_parsed(
        self, parsed: list[tuple[str, Any, np.ndarray, dict]]
    ) -> int:
        """Prefill already-parsed ``(tenant, prefix_ids, ids, message)``
        records: pool-bucket prefixes go through the pooled insert
        (sticky-routed on the sharded plane), everything else through
        the plain insert — off-bucket prefixes are PREPENDED to the
        prompt (identical results, just uncached).  At most one insert
        dispatch per admission class per cycle."""
        lc = self.lifecycle
        if lc is not None:
            # ONE seam covers every admission path — refill, tenant
            # refill, and the fleet's orphan re-dispatch: arrival
            # (backdated to SentTimestamp, idempotent across
            # redeliveries of a still-open request) + the admitted
            # stamp that closes the queue-wait phase
            for tenant, _, _, message in parsed:
                rid = _request_id(message)
                lc.arrival(
                    rid, sent=self._sent_epoch(message),
                    tenant=tenant or None,
                )
                lc.stamp(rid, "admitted")
        pool = self.batcher.prefix_pool
        plain, plain_tenants, prefixed = [], [], []
        for tenant, prefix_ids, ids, message in parsed:
            if (pool is not None and prefix_ids is not None
                    and prefix_ids.size == pool.prefix_len):
                prefixed.append((tenant, prefix_ids, ids, message))
                continue
            if prefix_ids is not None and prefix_ids.size:
                ids = np.concatenate(
                    [np.asarray(prefix_ids, np.int32).reshape(-1),
                     np.asarray(ids, np.int32).reshape(-1)]
                )
                if ids.size > self.batcher.prompt_len:
                    # the prepended request no longer fits the prompt
                    # bucket: _pad_prompt would silently truncate away
                    # the user's actual prompt.  Shed it with an
                    # explicit error instead — answered, never
                    # silently corrupted (the poison-body idiom)
                    self._settle(
                        message, None,
                        error="prefix + prompt exceeds the prompt "
                              "bucket (shrink the prefix or size "
                              "--seq-len / the prefix pool for it)",
                        counted=False,
                    )
                    continue
            plain.append((ids, message))
            plain_tenants.append(tenant)
        admitted = []
        if prefixed:
            rows = self.batcher.submit_many_prefixed(prefixed)
            admitted += [
                (row, t, m)
                for row, (t, _, _, m) in zip(rows, prefixed)
            ]
        if plain:
            rows = self.batcher.submit_many(plain)
            if self.tenancy is not None:
                self.batcher.tag_tenant(rows, plain_tenants)
                admitted += [
                    (row, t, m)
                    for row, t, (_, m) in zip(rows, plain_tenants, plain)
                ]
        if self.tenancy is not None:
            # arrival stamps for per-tenant TTFT (host bookkeeping
            # only; the reference path never reaches here), plus the
            # ladder's tier-1 action: an over-share tenant's fresh
            # admissions get a degraded token budget — answered short
            # with an honest (shorter) reply, never dropped
            degrade = self._degrade_tenants
            for row, tenant, message in admitted:
                slot = self.batcher.slots[row]
                slot.arrived_at = self._sent_epoch(message)
                if (degrade and tenant in degrade
                        and self._degraded_tokens < slot.budget):
                    slot.budget = self._degraded_tokens
                    slot.degraded = True
                    self._note_shed("degraded")
        return len(parsed)

    def _sent_epoch(self, message: dict) -> float | None:
        """The request's queue arrival in epoch seconds; delegates to
        the one shared parse (:func:`~.service.sent_epoch`)."""
        from .service import sent_epoch

        return sent_epoch(message)

    def _admit(self, messages: list[dict]) -> int:
        """Parse and prefill already-received ``messages`` (at most the
        current free-slot count) into the batcher; returns the number
        admitted.  Poison bodies are consumed (with an error reply when
        replies are on), not redelivered forever — and not counted as
        processed work.  Shared by :meth:`_refill` and the fleet router's
        direct re-dispatch path (which is why it stays tenant-aware:
        re-dispatched orphans keep their tenant attribution)."""
        admit = []
        for message in messages:
            # older than --request-ttl already on arrival: shed instead
            # of occupying a slot (see _shed_if_expired for the
            # exactly-once contract)
            if self._shed_if_expired(message):
                continue
            parsed = self._parse_for_admit(message)
            if parsed is None:
                self._settle(message, None, counted=False)
                continue
            admit.append(parsed + (message,))
        if admit:
            # batched admission: the whole refill prefills in ONE jitted
            # multi-row insert (plain slots; beam/speculative admit
            # sequentially inside submit_many)
            self._submit_parsed(admit)
        return len(admit)

    def _shed_if_expired(self, message: dict) -> bool:
        """TTL-shed ``message`` if it is already older than
        ``request_ttl_s``: answered with an explicit expired error
        through the normal settle path (exactly-once, never silently
        dropped) and counted in :attr:`shed` — the ONE shed contract
        every admission path (arrival, staged, re-dispatch) shares.
        Returns True when the message was shed."""
        if not self._expired(message):
            return False
        if self._settle(message, None, error="expired", counted=False):
            self._note_shed("ttl")
        return True

    def _expired(self, message: dict) -> bool:
        """Deadline check at admission: the message's queue-stamped
        ``SentTimestamp`` (epoch milliseconds, the SQS attribute) is
        older than ``ServiceConfig.request_ttl_s``.  Messages without
        the attribute never expire (a queue that doesn't stamp cannot
        age its messages)."""
        ttl = getattr(self.config, "request_ttl_s", 0.0)
        if ttl <= 0:
            return False
        sent = self._sent_epoch(message)
        if sent is None:
            return False
        return self._now() - sent > ttl

    def evacuate_shard(self, shard: int) -> tuple[int, int]:
        """Move a quarantined shard's un-finished rows off it: re-admit
        prompt + produced-so-far onto healthy shards through ONE batched
        resume insert, and hand anything un-evacuable (no healthy free
        slot, or a prompt that no longer parses) back to the queue.
        Returns ``(evacuated, released)``; the shard must already be
        masked out of admission (the caller quarantines first, so the
        resume rows cannot route straight back onto the sick shard).
        Sharded-plane workers only."""
        from .service import parse_request_body

        taken = self.batcher.take_shard_inflight(shard)
        capacity = len(self.batcher.free_slots)
        resumes, handback = [], []
        for payload, produced, budget, submitted_at in taken:
            ids = parse_request_body(payload["Body"], self.tokenizer)
            fits = (
                ids is not None
                and len(resumes) < capacity
                and min(ids.size, self.batcher.prompt_len) + len(produced)
                <= self.batcher.resume_len
            )
            if fits:
                resumes.append((ids, payload, produced, budget,
                                submitted_at))
            else:
                handback.append(payload)
        if resumes:
            self.batcher.submit_resume(resumes)
        nack = getattr(self.queue, "change_message_visibility", None)
        if handback and nack is None:
            # still handed back — redelivery just waits out the full
            # visibility timeout instead of happening immediately
            log.warning(
                "Queue has no change_message_visibility; %d released "
                "request(s) will redeliver only after the visibility "
                "timeout", len(handback),
            )
        for payload in handback:
            # back through the queue: the produced prefix is abandoned
            # and a survivor decodes the request from scratch — slower,
            # never lost (and the reply registry still dedups if the
            # queue redelivers a copy racing this hand-back)
            if nack is not None:
                nack(self.config.queue_url, payload["ReceiptHandle"], 0)
        return len(resumes), len(handback)

    def kill_admission_shard(self, shard: int) -> int:
        """Chaos seam (``FleetFaultPlan.admission_kills``): kill one
        admission shard mid-cycle.  Its staged requests hand back to
        the queue via ``change_message_visibility(0)`` (redelivered,
        never lost — and the reply registry still dedups, so
        exactly-once holds), its deficit/credit/flood accounting
        tombstones, and the next refill cycle rehydrates it.  Sharded
        admission plane only; returns the hand-back count."""
        fair = self._fair
        if fair is None or not hasattr(fair, "kill_shard"):
            raise ValueError(
                "no sharded admission plane to kill a shard of "
                "(tenancy.admission_shards must be >= 2)"
            )
        nack = getattr(self.queue, "change_message_visibility", None)
        if nack is None:
            log.warning(
                "Queue has no change_message_visibility; the killed "
                "admission shard's staged requests will redeliver only "
                "after the visibility timeout"
            )

        def handback(message) -> None:
            if nack is not None:
                nack(self.config.queue_url, message["ReceiptHandle"], 0)

        return fair.kill_shard(shard, handback)

    def partition_admission_shard(
        self, shard: int, partitioned: bool = True,
    ) -> None:
        """Chaos seam (``FleetFaultPlan.admission_partitions``): flip
        one admission shard's gossip partition — it keeps admitting
        its tenant slice but is excluded from flood-classification
        gossip both ways until healed."""
        fair = self._fair
        if fair is None or not hasattr(fair, "partition_shard"):
            raise ValueError(
                "no sharded admission plane to partition a shard of "
                "(tenancy.admission_shards must be >= 2)"
            )
        fair.partition_shard(shard, partitioned)

    def attach_metrics(self, metrics) -> None:
        """Report the serving gauges (tokens/s, time-to-first-token,
        active slots, block utilization) to a
        :class:`~..obs.WorkloadMetrics` registry, refreshed every engine
        cycle."""
        self.metrics = metrics
        self._update_metrics()

    def attach_lifecycle(self, registry) -> None:
        """Wire a :class:`~..obs.LifecycleRegistry` through every stamp
        site this worker owns — the batcher's admission/emit/settle
        funnels and the fair-admission staging layer — and rebind the
        registry's clock to the worker's epoch clock (the request-TTL
        time base), so stamps, ``SentTimestamp`` arrivals, and FakeClock
        episodes agree on one time base.  ``None`` detaches."""
        self.lifecycle = registry
        if registry is not None:
            registry.now_fn = self._now
        self.batcher.lifecycle = registry
        if self._fair is not None:
            self._fair.lifecycle = registry

    def _update_metrics(self) -> None:
        if self.metrics is None:
            return
        batcher = self.batcher
        elapsed = (
            time.perf_counter() - self._served_since
            if self._served_since is not None else 0.0
        )
        self.metrics.set_serving_gauges(
            tokens_per_second=(
                batcher.tokens_emitted / elapsed if elapsed > 0 else 0.0
            ),
            time_to_first_token_seconds=(
                batcher.ttft_sum / batcher.ttft_count
                if batcher.ttft_count else 0.0
            ),
            active_slots=batcher.active,
            decode_block_utilization=(
                batcher.block_tokens / batcher.block_capacity
                if batcher.block_capacity else 0.0
            ),
        )
        comms = getattr(batcher, "comms", None)
        if comms is not None:
            export = getattr(comms, "export_gauges", None)
            if export is not None:
                # per-link routing gauges (topology-attached comms
                # only) refresh on the same cadence as the serving set
                export(self.metrics)
        shed_help = (
            "Requests shed or degraded at admission, by reason: ttl = "
            "older than --request-ttl on arrival (explicit expired "
            "reply), degraded = overload tier 1 cut the token budget "
            "(answered short), pressure = overload tier 3 shed it from "
            "staging (explicit error reply), decode_deadline = the "
            "decode phase blew its per-token SLO budget (shed "
            "mid-decode with an explicit error reply).  The unlabeled "
            "series is their sum (pre-ladder dashboards keep working)."
        )
        self.metrics.set_gauge(
            "requests_shed_total", self.shed, shed_help, kind="counter",
        )
        for reason, count in sorted(self.shed_by_reason.items()):
            self.metrics.set_gauge(
                "requests_shed_total", count, shed_help,
                labels=(("reason", reason),), kind="counter",
            )
        if self.ladder is not None:
            self.metrics.set_gauge(
                "overload_tier", self.ladder.tier,
                "Active overload-ladder tier (0 = serving normally, "
                "1 = degrading over-share tenants, 2 = + evicting cold "
                "prefix entries, 3 = + shedding staged requests).",
            )
            self.metrics.set_gauge(
                "overload_pressure", self.ladder.last_pressure,
                "Measured overload pressure (staged-backlog fraction "
                "gated by slot occupancy) the ladder last acted on.",
            )
            self.metrics.set_gauge(
                "overload_tier_transitions_total",
                self.ladder.transitions,
                "Ladder tier transitions (enter + exit) over the "
                "worker's lifetime.",
                kind="counter",
            )
        elif self._fair is not None and hasattr(self._fair, "shards"):
            # the sharded admission plane: plane-wide ladder rollup
            # (max tier / pressure, summed transitions — the pre-shard
            # dashboards keep reading one series) plus per-shard
            # labeled gauges.  Shard-index labels are bounded by
            # construction (N is a config knob, not request input), so
            # they need no bounded_tenant_key fold.
            shards = self._fair.shards
            ladders = [s.ladder for s in shards if s.ladder is not None]
            if ladders:
                self.metrics.set_gauge(
                    "overload_tier",
                    max(ladder.tier for ladder in ladders),
                    "Active overload-ladder tier (0 = serving normally, "
                    "1 = degrading over-share tenants, 2 = + evicting "
                    "cold prefix entries, 3 = + shedding staged "
                    "requests).  Sharded admission: the MAX across "
                    "per-shard ladders.",
                )
                self.metrics.set_gauge(
                    "overload_pressure",
                    max(ladder.last_pressure for ladder in ladders),
                    "Measured overload pressure the ladder last acted "
                    "on.  Sharded admission: the MAX across per-shard "
                    "ladders.",
                )
                self.metrics.set_gauge(
                    "overload_tier_transitions_total",
                    sum(ladder.transitions for ladder in ladders),
                    "Ladder tier transitions (enter + exit), summed "
                    "across admission shards.",
                    kind="counter",
                )
            for shard in shards:
                labels = (("shard", str(shard.index)),)
                self.metrics.set_gauge(
                    "admission_shard_staged", shard.fair.staged,
                    "Requests parked in this admission shard's staging "
                    "slice.",
                    labels=labels,
                )
                self.metrics.set_gauge(
                    "admission_shard_tenants",
                    sum(
                        1 for depth in shard.fair.drr.depths().values()
                        if depth > 0
                    ),
                    "Tenants with staged work on this admission shard.",
                    labels=labels,
                )
                self.metrics.set_gauge(
                    "admission_shard_state",
                    0 if not shard.alive
                    else (1 if shard.partitioned else 2),
                    "Admission-shard liveness: 2 = serving, 1 = "
                    "gossip-partitioned (still admitting), 0 = killed "
                    "(staged work handed back; rehydrates next cycle).",
                    labels=labels,
                )
        if self.tenancy is not None:
            # the gauge label registry is persistent AND bounded: raw
            # staged labels fold through bounded_tenant_key before they
            # can mint a Prometheus series (set_gauge keeps every
            # (name, labels) row forever), and every registered label
            # is re-exported each cycle so a pruned tenant's depth
            # series resets to 0 instead of sticking at its last value
            depths: dict[str, int] = {}
            for tenant, depth in self._fair.depths().items():
                label = _bounded_tenant_key(tenant, self._gauge_tenants)
                self._gauge_tenants[label] = True
                depths[label] = depths.get(label, 0) + depth
            for tenant in set(batcher.tenant_tokens) | \
                    set(batcher.tenant_ttft):
                self._gauge_tenants.setdefault(tenant, True)
            for tenant in sorted(self._gauge_tenants):
                # cumulative mean (sum/count over the tenant's whole
                # lifetime), not the mean of a bounded recent-sample
                # window: the gauge no longer forgets the flood it
                # measured an hour ago (the recent-sample deques stay
                # for the benches' nearest-rank quantiles)
                count = batcher.tenant_ttft_count.get(tenant, 0)
                self.metrics.set_tenant_gauges(
                    tenant,
                    queue_depth=depths.get(tenant, 0),
                    ttft_seconds=(
                        batcher.tenant_ttft_sum.get(tenant, 0.0) / count
                        if count else 0.0
                    ),
                    tokens_per_second=(
                        batcher.tenant_tokens.get(tenant, 0) / elapsed
                        if elapsed > 0 else 0.0
                    ),
                )
            pool = batcher.prefix_pool
            if pool is not None:
                self.metrics.set_gauge(
                    "prefix_cache_hits_total", pool.hits,
                    "Prefix-pool admissions that reused a resident "
                    "prefix entry (the shared-prefix prefill skipped "
                    "entirely).",
                    kind="counter",
                )
                self.metrics.set_gauge(
                    "prefix_cache_misses_total", pool.misses,
                    "Prefix-pool admissions that had to install (prefill "
                    "once + LRU-evict) their prefix entry.",
                    kind="counter",
                )
        # decode-plane serving (planes/engine.py): the measured-
        # economics accept rate (per tenant through the same bounded
        # label registry as every other tenant series) and the KV
        # handoff counter
        if getattr(batcher, "spec_layers", 0):
            accept_help = (
                "Accepted-draft fraction of proposed speculative "
                "tokens in [0, 1] (labeled rows are per-tenant, "
                "bounded like every tenant series; the unlabeled row "
                "is plane-wide)."
            )
            rate = batcher.accept_rate()
            if rate is not None:
                self.metrics.set_gauge(
                    "speculative_accept_rate", rate, accept_help,
                )
            for tenant in sorted(batcher.tenant_spec_rounds):
                self.metrics.set_gauge(
                    "speculative_accept_rate",
                    batcher.accept_rate(tenant) or 0.0, accept_help,
                    labels=(("tenant", tenant),),
                )
        if getattr(batcher, "kv_transfers", None) is not None:
            self.metrics.set_gauge(
                "plane_kv_transfers_total", batcher.kv_transfers,
                "KV rows this decode plane adopted from prefill-plane "
                "donors over the handoff transport.",
                kind="counter",
            )
        # TTFT cumulative histograms (the real replacement for the
        # sample-deque gauges: counts never reset, quantiles compose
        # across scrapes) — unlabeled engine-wide plus per-tenant,
        # label-bounded upstream by _bounded_tenant_key
        drain_ttft_histograms(batcher, self.metrics)
        if self.lifecycle is not None:
            # drained here so lifecycle histograms refresh on the same
            # cadence as every other serving gauge
            self.lifecycle.export_metrics(self.metrics)

    def _enforce_decode_deadlines(self) -> None:
        """Deadlines past TTFT (``tenancy.decode_slo_s`` > 0): once a
        slot has its first token, it must finish its remaining budget
        at ``decode_slo_s`` seconds per token or be shed MID-decode
        with an explicit error reply — the enforcement side of the
        PR 17 decode-phase histograms.  The shed settles the reply
        here (exactly-once through the normal settle path), then cuts
        the slot's budget to what it already produced so the engine
        frees — and quiesces — the row on its next step; run_once
        skips the resulting payload-None done pair so the request is
        neither double-settled nor counted as a completion."""
        slo = self.tenancy.decode_slo_s
        now = self._now()
        for slot in self.batcher.slots:
            if not slot.busy or slot.done or slot.payload is None:
                continue
            produced = len(slot.produced)
            if produced < 1:
                continue  # pre-first-token is the TTFT SLO's territory
            if slot.decode_deadline_at is None:
                slot.decode_deadline_at = now + slo * max(
                    1, slot.budget - produced
                )
                continue
            if now <= slot.decode_deadline_at:
                continue
            message = slot.payload
            slot.payload = None
            slot.budget = produced  # finishes (and quiesces) next step
            slot.degraded = True
            if self._settle(
                message, None,
                error=(
                    "decode deadline exceeded (the decode phase blew "
                    "its per-token SLO budget)"
                ),
                counted=False,
            ):
                self._note_shed("decode_deadline")

    def run_once(self) -> int:
        """One engine cycle: refill free slots, advance the decode block
        (one token per slot at ``decode_block=1``), settle finished
        requests.  Returns messages completed this cycle."""
        if self._served_since is None:
            self._served_since = time.perf_counter()
        self._refill()
        if self.tenancy is not None and self.tenancy.decode_slo_s > 0:
            self._enforce_decode_deadlines()
        done = self.batcher.step()
        completed = 0
        for message, tokens in done:
            if message is None:
                # a decode-deadline shed: the error reply settled at
                # enforcement time; the engine just freed the row
                continue
            self._settle(message, tokens)
            completed += 1
        if done:
            self._poll_backoff = 0  # a slot just freed: poll right away
        self.processed += completed
        self._update_metrics()
        return completed

    def stop(self) -> None:
        """Ask the serve loop to exit after its current cycle.

        Idempotent, and sticky like :meth:`..core.loop.ControlLoop.stop`:
        a stop requested before :meth:`run_forever` starts still takes
        effect (the event is created at construction, not lazily)."""
        self._stop.set()

    def run_forever(self) -> None:
        """Serve until :meth:`stop` — same never-dies guarantee as
        :meth:`.service.QueueWorker.run_forever`: a transient queue or
        compute error logs, backs off, and retries (unfinished slots stay
        in flight; their messages reappear after the visibility timeout
        if the process dies).

        Raises :class:`RuntimeError` on a double start: two concurrent
        serve loops over one batcher would interleave refill/step state
        nondeterministically — the second caller must be told, not
        silently raced."""
        if self._running:
            raise RuntimeError(
                "ContinuousWorker is already running; one serve loop per "
                "worker (spawn another replica to add capacity)"
            )
        self._running = True
        try:
            while not self._stop.is_set():
                try:
                    with self.timer.span("cycle"):
                        idle = (self.run_once() == 0
                                and self.batcher.active == 0)
                except Exception as err:
                    log.error("Continuous worker cycle failed: %s", err)
                    self._stop.wait(self.config.error_backoff_s)
                    continue
                if idle:
                    self._stop.wait(self.config.idle_sleep_s)
        finally:
            self._running = False

    def drain(
        self,
        total: int,
        max_cycles: int | None = None,
        timeout_s: float | None = None,
    ) -> int:
        """Run cycles until ``total`` messages complete (or the cycle /
        wall-clock budget runs out); returns the number completed.

        ``timeout_s`` bounds the drain in wall time: when the queue (or
        the engine) stalls with requests still in flight, the call
        returns instead of hanging — the un-finished messages stay
        in-flight on the queue and reappear after its visibility
        timeout, so giving up on a drain never loses work."""
        cycles = 0
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        while self.processed < total:
            if max_cycles is not None and cycles >= max_cycles:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            cycles += 1
            with self.timer.span("cycle"):
                done = self.run_once()
            if done == 0 and self.batcher.active == 0:
                # the cycle's own refill got nothing and nothing is in
                # flight: the queue is drained
                break
        return self.processed
