"""Continuous batching: rolling decode slots that refill independently.

The batch-generate worker (:mod:`.service` in generate mode) decodes a
whole batch to completion before touching the queue again — one long
prompt or one unlucky batch blocks every other message (head-of-line
blocking).  Real LM serving keeps a *rolling* batch instead: every row of
the KV cache is an independent slot; each engine step advances all active
slots by one token, finished slots emit their continuation immediately,
and new requests are prefilled **into** a free slot while the others keep
decoding.  The per-row cache machinery from :mod:`.decode` (per-row
``length``, per-row write positions, per-row masks) is exactly what makes
this work.

TPU shape discipline: there are only two compiled programs —

- ``decode_step`` (the existing one): advances all ``batch`` slots one
  position, active or not (inactive rows compute garbage that is never
  read — lockstep static shapes beat dynamic batch reshapes);
- ``insert`` : prefill one prompt (padded to a fixed bucket) as a
  ``[1, P]`` batch and ``dynamic_update_slice`` its layer caches into the
  slot's row, set the row's length, and return the first sampled token.

The reference has no serving at all (SURVEY.md §2); this is the TPU-shop
shape of the queue-consumer its README deploys.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .decode import _pick, init_cache, prefill
from .model import ModelConfig

log = logging.getLogger(__name__)


@partial(
    jax.jit, static_argnames=("config", "prompt_len"), donate_argnums=(1,)
)
def _insert_row(
    params: dict,
    cache: dict,
    row: jax.Array,
    prompt: jax.Array,
    length: jax.Array,
    config: ModelConfig,
    prompt_len: int,
) -> tuple[dict, jax.Array]:
    """Prefill ``prompt`` (int32 ``[prompt_len]``, right-padded to the
    static bucket) and splice it into slot ``row`` of ``cache``.

    Returns ``(cache, first_token)`` — the slot's length is the prompt's
    real length and its first greedy continuation token is ready to feed
    the next ``decode_step``.
    """
    logits, row_cache = prefill(
        params, prompt[None], config, lengths=length[None]
    )
    new_layers = []
    for layer_cache, row_layer in zip(cache["layers"], row_cache["layers"]):
        new_layers.append({
            "k": jax.lax.dynamic_update_slice(
                layer_cache["k"], row_layer["k"][:, :, :prompt_len],
                (row, 0, 0, 0),
            ),
            "v": jax.lax.dynamic_update_slice(
                layer_cache["v"], row_layer["v"][:, :, :prompt_len],
                (row, 0, 0, 0),
            ),
        })
    lengths = jax.lax.dynamic_update_index_in_dim(
        cache["length"], length, row, 0
    )
    first = _pick(logits, None, 0.0)[0]
    return {"layers": new_layers, "length": lengths}, first


@dataclass
class _Slot:
    busy: bool = False
    produced: list = field(default_factory=list)
    budget: int = 0
    payload: Any = None  # caller's per-request context (receipt handle...)


class ContinuousBatcher:
    """The slot machine: submit prompts, step the batch, collect results.

    Queue-agnostic and synchronous — drive it from anything that produces
    ``(token_ids, payload)`` requests.  Greedy decoding (the generate-mode
    worker's semantics).  Outputs are exactly what :func:`.decode.generate`
    produces for each prompt alone (pinned by test): continuous batching
    changes *scheduling*, never results.
    """

    def __init__(
        self,
        params: Any,
        config: ModelConfig,
        batch_size: int,
        prompt_len: int,
        generate_tokens: int,
    ) -> None:
        if prompt_len + generate_tokens > config.max_seq_len:
            raise ValueError(
                f"prompt_len + generate_tokens = "
                f"{prompt_len + generate_tokens} exceeds max_seq_len="
                f"{config.max_seq_len}"
            )
        self.params = params
        self.config = config
        self.prompt_len = prompt_len
        self.generate_tokens = generate_tokens
        self.cache = init_cache(config, batch_size)
        self.slots = [_Slot() for _ in range(batch_size)]
        # each slot's pending input token for the next decode step
        self._current = jnp.zeros((batch_size,), jnp.int32)
        self._decode = self._make_decode_step()

    def _make_decode_step(self):
        from .decode import decode_step

        # donate the cache: self.cache is reassigned from the result every
        # call, so the multi-layer KV buffers are reused in place instead
        # of copied per generated token (same as compile_serving_fns)
        @partial(jax.jit, donate_argnums=(1,))
        def step(params, cache, tokens):
            logits, cache = decode_step(params, cache, tokens, self.config)
            return cache, _pick(logits, None, 0.0)

        return step

    @property
    def free_slots(self) -> list[int]:
        return [i for i, s in enumerate(self.slots) if not s.busy]

    @property
    def active(self) -> int:
        return sum(s.busy for s in self.slots)

    def submit(self, token_ids: np.ndarray, payload: Any = None) -> int:
        """Prefill one request into a free slot; returns the slot index.

        ``token_ids`` is truncated/right-padded to the batcher's static
        ``prompt_len`` bucket (empty prompts count one pad token).
        """
        free = self.free_slots
        if not free:
            raise RuntimeError("no free slot; call step() until one opens")
        row = free[0]
        ids = np.zeros((self.prompt_len,), np.int32)
        real = np.asarray(token_ids, np.int32).reshape(-1)[: self.prompt_len]
        ids[: real.size] = real
        length = max(1, real.size)
        self.cache, first = _insert_row(
            self.params, self.cache, jnp.asarray(row, jnp.int32),
            jnp.asarray(ids), jnp.asarray(length, jnp.int32), self.config,
            self.prompt_len,
        )
        self._current = self._current.at[row].set(first)
        slot = self.slots[row]
        slot.busy = True
        slot.produced = [first]
        slot.budget = self.generate_tokens
        slot.payload = payload
        return row

    def step(self) -> list[tuple[Any, np.ndarray]]:
        """Advance every active slot one token; return finished requests
        as ``(payload, continuation_tokens)`` pairs (their slots are free
        again on return).  No-op when nothing is active."""
        if self.active == 0:
            return []
        finished = []
        # rows whose budget is a single token never need a decode step
        pending_decode = any(
            s.busy and len(s.produced) < s.budget for s in self.slots
        )
        if pending_decode:
            self.cache, nxt = self._decode(
                self.params, self.cache, self._current
            )
            nxt_host = np.asarray(nxt)
            for row, slot in enumerate(self.slots):
                if slot.busy and len(slot.produced) < slot.budget:
                    slot.produced.append(int(nxt_host[row]))
            self._current = nxt
        for row, slot in enumerate(self.slots):
            if slot.busy and len(slot.produced) >= slot.budget:
                finished.append(
                    (slot.payload, np.asarray(slot.produced, np.int32))
                )
                self.slots[row] = _Slot()
        return finished


class ContinuousWorker:
    """A queue-draining worker built on :class:`ContinuousBatcher`.

    Same at-least-once contract as :class:`.service.QueueWorker`: a
    message is deleted only after its continuation is fully generated.
    Unlike the batch worker, a slow batch never blocks fresh messages —
    slots refill the moment they finish.
    """

    def __init__(
        self,
        queue,
        params: Any,
        model_config: ModelConfig,
        service_config,
    ) -> None:
        if service_config.generate_tokens < 1:
            raise ValueError(
                "ContinuousWorker is generate-mode serving; set "
                "ServiceConfig.generate_tokens >= 1"
            )
        self.queue = queue
        self.config = service_config
        self.batcher = ContinuousBatcher(
            params, model_config,
            batch_size=service_config.batch_size,
            prompt_len=service_config.seq_len,
            generate_tokens=service_config.generate_tokens,
        )
        self.processed = 0
        # wall-clock engine-cycle spans (same metrics surface as
        # QueueWorker: obs attaches this to /metrics)
        from ..utils.profiling import SpanTimer

        self.timer = SpanTimer()
        self._stop = None  # lazily a threading.Event in run_forever
        self._poll_backoff = 0

    # poll throttle: after an EMPTY zero-wait receive while slots are
    # still decoding, skip this many cycles before polling again — one
    # billed ReceiveMessage per generated token would be absurd on SQS
    POLL_BACKOFF_CYCLES = 16

    def _refill(self) -> int:
        """Pull up to free-slot-count messages and prefill them in."""
        import json

        free = len(self.batcher.free_slots)
        if not free:
            return 0
        if self._poll_backoff > 0:
            self._poll_backoff -= 1
            return 0
        messages = self.queue.receive_messages(
            self.config.queue_url, max_messages=free,
            wait_time_s=0 if self.batcher.active else
            self.config.receive_wait_s,
        )
        if not messages and self.batcher.active:
            self._poll_backoff = self.POLL_BACKOFF_CYCLES
        for message in messages:
            try:
                ids = np.asarray(
                    json.loads(message["Body"]), np.int32
                ).reshape(-1)
            except Exception:
                log.error("Dropping malformed message body: %.64r",
                          message["Body"])
                # poison messages are consumed, not redelivered forever
                self.queue.delete_message(
                    self.config.queue_url, message["ReceiptHandle"]
                )
                continue
            self.batcher.submit(ids, payload=message["ReceiptHandle"])
        return len(messages)

    def run_once(self) -> int:
        """One engine cycle: refill free slots, advance one token, settle
        finished requests.  Returns messages completed this cycle."""
        self._refill()
        done = self.batcher.step()
        for receipt, _tokens in done:
            self.queue.delete_message(self.config.queue_url, receipt)
        if done:
            self._poll_backoff = 0  # a slot just freed: poll right away
        self.processed += len(done)
        return len(done)

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()

    def run_forever(self) -> None:
        """Serve until :meth:`stop` — same never-dies guarantee as
        :meth:`.service.QueueWorker.run_forever`: a transient queue or
        compute error logs, backs off, and retries (unfinished slots stay
        in flight; their messages reappear after the visibility timeout
        if the process dies)."""
        import threading

        if self._stop is None:
            self._stop = threading.Event()
        while not self._stop.is_set():
            try:
                with self.timer.span("cycle"):
                    idle = self.run_once() == 0 and self.batcher.active == 0
            except Exception as err:
                log.error("Continuous worker cycle failed: %s", err)
                self._stop.wait(self.config.error_backoff_s)
                continue
            if idle:
                self._stop.wait(self.config.idle_sleep_s)

    def drain(self, total: int, max_cycles: int | None = None) -> int:
        """Run cycles until ``total`` messages complete (or the cycle
        budget runs out); returns the number completed."""
        cycles = 0
        while self.processed < total:
            if max_cycles is not None and cycles >= max_cycles:
                break
            cycles += 1
            with self.timer.span("cycle"):
                done = self.run_once()
            if done == 0 and self.batcher.active == 0:
                # the cycle's own refill got nothing and nothing is in
                # flight: the queue is drained
                break
        return self.processed
