"""Speculative decoding: draft-and-verify greedy generation.

Token-by-token decode is HBM-bandwidth-bound — every step streams the
whole model (and cache) for one token's worth of MXU work.  Speculative
decoding converts that into compute: a small *draft* model proposes ``k``
tokens autoregressively (cheap — k small-model steps), then the *target*
model scores all ``k+1`` positions in ONE chunk-wide forward
(:func:`.decode.chunk_decode` — k+1 times the MXU work of a decode step
for roughly the same HBM traffic).  Accepted drafts advance the sequence
several tokens per target pass; rejected tails cost nothing extra
(no reference counterpart: the reference has no model code, SURVEY.md §2).

**Exactness.** This implements greedy speculative decoding: the output
equals :func:`.decode.generate`'s greedy output token for token for any
draft model — the draft only decides how many target-forward passes are
needed, never what is emitted.  The one caveat: the verify pass computes
the target's logits through :func:`.decode.chunk_decode` (a ``T``-wide
batch of the same math), so positions where the target's top-2 logits
are within floating-point reassociation error of each other can resolve
the argmax differently than the sequential decode would — exactness is
"up to argmax ties", not bitwise on the logits.  Per round, with pending
token ``p`` and draft proposals ``d_1..d_k``:

- the target chunk-decodes inputs ``[p, d_1..d_k]`` into greedy picks
  ``g_0..g_k`` (``g_i`` = target's choice after consuming input ``i``);
- ``d_j`` is accepted while every earlier draft matched: the accepted
  count is ``n = Σ_j Π_{i<=j} [d_i == g_{i-1}]``;
- ``d_1..d_n`` plus the bonus ``g_n`` are emitted (``n+1 >= 1`` tokens —
  a round can never stall), and ``g_n`` becomes the next pending token;
- both caches roll back by *length*, not by rewriting: the chunk's k/v
  entries past the accepted prefix stay in HBM but are masked out by the
  per-row ``length`` (the same mechanism that makes ragged batches work),
  so rollback is one scalar update per row.

Rows accept independently (per-row ``n``), so a batch decodes in
lockstep with per-row progress — the same ragged-batch contract as
:mod:`.decode`.  The whole generate loop is one ``lax.while_loop`` under
jit: static shapes (the output buffer is over-allocated by one round and
sliced), no host round-trips.  Rows that reach ``num_tokens`` freeze
(zero advance, writes masked) while slower rows finish, so cache
positions never grow past the validated budget.

The draft runs one extra consume step per round (input ``d_k``) so its
cache always holds every accepted input even on full acceptance; like
the rejected entries, it is masked out when not needed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .decode import chunk_decode, decode_step, prefill
from .model import ModelConfig


def _family_ops(config, quantized_cache: bool = False):
    """(prefill, decode_step, chunk_decode, prefill_with_prefix) for
    the config's family —
    llama configs (they carry ``n_kv_heads``) get the GQA/RoPE cache ops,
    everything else the gpt-family ops.  Target and draft dispatch
    independently, so a llama target can use a gpt draft and vice versa
    (the only shared contract is the vocabulary).

    ``quantized_cache`` swaps in the int8-cache triple: per-position
    quantization writes IDENTICAL codes whether a position arrives via a
    draft step or the chunk-wide verify, so greedy speculative over int8
    caches still equals plain quantized greedy decode token for token
    (up to argmax ties)."""
    if hasattr(config, "n_kv_heads"):
        from .llama import llama_prefill_with_prefix

        if quantized_cache:
            from .llama import (
                llama_quantized_chunk_decode,
                llama_quantized_decode_step,
                llama_quantized_prefill,
            )

            from .llama import llama_quantized_prefill_with_prefix

            return (llama_quantized_prefill, llama_quantized_decode_step,
                    llama_quantized_chunk_decode,
                    llama_quantized_prefill_with_prefix)
        from .llama import (
            llama_chunk_decode,
            llama_decode_step,
            llama_prefill,
        )

        # llama_prefill's (params, tokens, config, prompt_attention,
        # lengths) lines up with the gpt prefill call shape directly
        return (llama_prefill, llama_decode_step, llama_chunk_decode,
                llama_prefill_with_prefix)
    from .decode import prefill_with_prefix

    if quantized_cache:
        from .decode import (
            quantized_chunk_decode,
            quantized_decode_step,
            quantized_prefill,
        )

        from .decode import quantized_prefill_with_prefix

        return (quantized_prefill, quantized_decode_step,
                quantized_chunk_decode, quantized_prefill_with_prefix)
    return prefill, decode_step, chunk_decode, prefill_with_prefix


def draft_prefix_from_target(prefix_cache: dict, n_layers: int) -> dict:
    """The early-exit self-draft's prefix cache, for free: the draft IS
    the target's first ``n_layers``, so its prefix KV is the layer-wise
    slice of the target's already-computed prefix cache — no second
    prefix prefill."""
    return {
        "layers": prefix_cache["layers"][:n_layers],
        "length": prefix_cache["length"],
    }


def _warp(logits, temperature: float, top_k: int, top_p: float):
    """The warped sampling distribution — delegates to
    ``decode.warp_logits``, the single definition ``_pick`` also uses,
    so the sampled and speculative paths cannot disagree on what 'the
    target distribution' means."""
    from .decode import warp_logits

    return warp_logits(logits, temperature, top_k, top_p)


def _accept_and_fixup(key, drafts, draft_warped, target_warped):
    """One round of the speculative-sampling acceptance rule
    (Leviathan et al. / Chen et al.): accept draft ``d_i ~ p_i`` with
    probability ``min(1, q_{i-1}(d_i) / p_i(d_i))`` while every earlier
    draft was accepted; on the first rejection emit a token from the
    residual ``(q - p)+`` (renormalized), and on full acceptance from
    ``q_k`` directly.  Returns ``(n, fixup)`` — the accepted count
    ``[B]`` and the replacement/bonus token ``[B]``.

    The identity ``min(p, q) + (1 - Σ min(p, q)) · (q-p)+/Z = q`` makes
    each emitted position an exact sample from the (warped) target
    distribution, independent of the draft — the draft only buys
    throughput (``tests/test_speculative.py`` checks the marginal
    empirically over 10^5 rows).
    """
    batch, k = drafts.shape
    p_d = jax.nn.softmax(draft_warped, axis=-1)  # [B, k, V]
    q = jax.nn.softmax(target_warped, axis=-1)  # [B, k+1, V]
    p_chosen = jnp.take_along_axis(
        p_d, drafts[..., None], axis=-1
    )[..., 0]  # [B, k]
    q_chosen = jnp.take_along_axis(
        q[:, :k], drafts[..., None], axis=-1
    )[..., 0]
    key_u, key_f = jax.random.split(key)
    u = jax.random.uniform(key_u, (batch, k))
    # u ~ U[0,1): accept iff u < q/p, i.e. u * p < q (p > 0 a.s. since
    # d was sampled from p)
    accept = (u * p_chosen < q_chosen).astype(jnp.int32)
    n = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)  # [B] in [0, k]
    # fixup distribution: the residual at the first rejected position,
    # or q_k itself on full acceptance
    q_n = jnp.take_along_axis(
        q, n[:, None, None], axis=1
    )[:, 0]  # [B, V]
    p_n = jnp.take_along_axis(
        p_d, jnp.clip(n, 0, k - 1)[:, None, None], axis=1
    )[:, 0]
    residual = jnp.maximum(q_n - p_n, 0.0)
    z = jnp.sum(residual, axis=-1, keepdims=True)
    # numeric fallback: a residual that underflowed to zero mass means
    # p ≈ q there — sampling q directly is the same distribution
    resid_dist = jnp.where(z > 1e-9, residual / jnp.maximum(z, 1e-9), q_n)
    dist = jnp.where((n < k)[:, None], resid_dist, q_n)
    fixup = jax.random.categorical(
        key_f, jnp.log(dist + 1e-38), axis=-1
    ).astype(jnp.int32)
    return n, fixup


def speculative_generate(
    params_target: dict,
    config_target: ModelConfig,
    params_draft: dict,
    config_draft: ModelConfig,
    prompt: jax.Array,
    num_tokens: int,
    *,
    draft_tokens: int = 4,
    attention_fn=None,
    lengths: jax.Array | None = None,
    return_stats: bool = False,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
    draft_prefix_cache: dict | None = None,
) -> jax.Array:
    """Greedy generation through the draft-and-verify loop — or, with
    ``temperature > 0`` (and ``rng``), full *speculative sampling*: the
    draft proposes from its own warped distribution, the target accepts
    with the Leviathan/Chen rejection rule (:func:`_accept_and_fixup`),
    and every emitted token is an exact sample from the target's
    warped distribution (temperature/top-k/top-p, same policy as
    ``decode._pick``) — the draft only changes throughput, never the
    distribution.

    Returns int32 ``[batch, num_tokens]`` — the greedy sequence of
    ``generate(params_target, prompt, num_tokens, config_target)``,
    exact up to argmax ties in the verify logits (module docstring).
    ``draft_tokens`` (k) is the proposals-per-round knob: each round runs
    k draft steps + 1 extra draft consume + one (k+1)-wide target chunk,
    and emits between 1 and k+1 tokens.  The models must share a
    vocabulary; ``lengths`` marks ragged right-padded prompts (both
    models prefill with it).  ``return_stats=True`` additionally
    returns ``{"rounds": [B] int32, "acceptance_rate": [B] fp32}`` — the
    per-row target-pass count and mean fraction of drafts accepted, the
    serving-side signal for tuning ``draft_tokens`` and the draft model.

    ``eos_id`` carries :func:`.decode.generate`'s eos contract into the
    speculative loop: a row that emits the id freezes (no further draft
    or verify work charged to it) and its later positions are pinned to
    the id — the pre-eos prefix is untouched, so greedy speculative with
    eos still equals plain greedy generate with eos token for token.

    ``prefix_cache``/``draft_prefix_cache`` (both or neither): each
    model continues its suffix prompts from a shared, once-prefilled
    prefix (:func:`.decode.prefill_prefix`); an early-exit self-draft
    gets its prefix cache for free via
    :func:`draft_prefix_from_target`.  The speculative loop itself is
    length-based and cache-agnostic, so everything downstream of the
    prefill is unchanged.
    """
    if config_target.vocab_size != config_draft.vocab_size:
        raise ValueError(
            f"target vocab {config_target.vocab_size} != draft vocab "
            f"{config_draft.vocab_size}"
        )
    if draft_tokens < 1:
        raise ValueError(f"draft_tokens must be >= 1, got {draft_tokens}")
    batch, prompt_len = prompt.shape
    if num_tokens < 1:
        raise ValueError(f"num_tokens must be >= 1, got {num_tokens}")
    if (prefix_cache is None) != (draft_prefix_cache is None):
        raise ValueError(
            "prefix_cache and draft_prefix_cache come together (the "
            "draft model needs its own prefix KV — "
            "draft_prefix_from_target slices it for a self-draft)"
        )
    if prefix_cache is not None:
        from .decode import _check_prefix_layout

        _check_prefix_layout(prefix_cache, quantized_cache)
        _check_prefix_layout(draft_prefix_cache, quantized_cache)
    # worst-case cache position: a row can overshoot num_tokens by up to
    # k when it freezes (count <= num_tokens + k -> frozen length up to
    # prompt + num_tokens + k - 1), and each later round still writes k
    # masked slots past that length — so both caches need
    # prefix + prompt + num_tokens + 2k positions
    from .decode import _check_prefix_budget

    for name, config in (("target", config_target), ("draft", config_draft)):
        _check_prefix_budget(
            prefix_cache, prompt_len, num_tokens, config,
            slack=2 * draft_tokens, slack_label="2x draft window",
            model_name=name,
        )

    sampled = temperature > 0.0
    if sampled and rng is None:
        raise ValueError("temperature sampling requires an rng key")
    if top_k < 0:
        raise ValueError(f"top_k={top_k} must be >= 0 (0 = off)")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} must be in (0, 1] (1.0 = off)")

    k = draft_tokens
    rows = jnp.arange(batch)
    t_prefill, t_step, t_chunk, t_prefix_prefill = _family_ops(
        config_target, quantized_cache)
    d_prefill, d_step, _, d_prefix_prefill = _family_ops(
        config_draft, quantized_cache)
    if prefix_cache is not None:
        t_logits, t_cache = t_prefix_prefill(
            params_target, prefix_cache, prompt, config_target,
            lengths=lengths,
        )
        _, d_cache = d_prefix_prefill(
            params_draft, draft_prefix_cache, prompt, config_draft,
            lengths=lengths,
        )
    else:
        t_logits, t_cache = t_prefill(
            params_target, prompt, config_target, attention_fn,
            lengths=lengths,
        )
        _, d_cache = d_prefill(
            params_draft, prompt, config_draft, attention_fn,
            lengths=lengths,
        )
    if sampled:
        from .decode import _pick

        rng, first_key = jax.random.split(rng)
        pending = _pick(t_logits, first_key, temperature, top_k, top_p)
    else:
        rng = jnp.zeros((), jnp.uint32)  # unused carry placeholder
        pending = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B]

    # over-allocate one full round past num_tokens so the fixed-width
    # round write never clips; sliced off at the end
    out = jnp.zeros((batch, num_tokens + k + 1), jnp.int32)
    out = out.at[:, 0].set(pending)
    count = jnp.ones((batch,), jnp.int32)  # emitted per row (incl. pending)
    rounds = jnp.zeros((batch,), jnp.int32)
    accepted_total = jnp.zeros((batch,), jnp.int32)
    eos_seen = (
        pending == eos_id if eos_id is not None
        else jnp.zeros((batch,), bool)
    )

    def row_done(count, eos_seen):
        return (count >= num_tokens) | eos_seen

    def round_body(carry):
        (out, count, pending, t_cache, d_cache, rounds, accepted_total,
         rng, eos_seen) = carry
        # rows already at num_tokens (or past their eos) freeze: no
        # emission, no cache/count advance — their chunk writes land in
        # masked slots within the validated budget instead of marching
        # past max_seq_len while slower rows finish
        done = row_done(count, eos_seen)
        if sampled:
            rng, accept_key, *draft_keys = jax.random.split(rng, k + 2)

        # --- draft: propose k tokens autoregressively ------------------
        proposals = []
        draft_warped = []
        token = pending
        dc = d_cache
        for i in range(k):  # k is small and static — unrolled
            logits, dc = d_step(params_draft, dc, token, config_draft)
            if sampled:
                warped = _warp(logits, temperature, top_k, top_p)
                draft_warped.append(warped)
                token = jax.random.categorical(
                    draft_keys[i], warped
                ).astype(jnp.int32)
            else:
                token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            proposals.append(token)
        drafts = jnp.stack(proposals, axis=1)  # [B, k]
        # extra consume of d_k so the draft cache holds every accepted
        # input even when all k drafts are accepted (masked otherwise)
        _, dc = d_step(params_draft, dc, drafts[:, -1], config_draft)

        # --- target: verify the whole window in one chunk forward ------
        chunk = jnp.concatenate([pending[:, None], drafts], axis=1)  # [B,k+1]
        t_len = t_cache["length"]
        d_len = d_cache["length"]
        logits, t_cache_adv = t_chunk(
            params_target, t_cache, chunk, config_target
        )

        # --- accept, and pick the replacement/bonus token --------------
        if sampled:
            n, bonus = _accept_and_fixup(
                accept_key, drafts, jnp.stack(draft_warped, axis=1),
                _warp(logits, temperature, top_k, top_p),
            )
        else:
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            matches = (drafts == greedy[:, :k]).astype(jnp.int32)
            accepted = jnp.cumprod(matches, axis=1)  # all-prefix match
            n = jnp.sum(accepted, axis=1)  # [B] in [0, k]
            bonus = jnp.take_along_axis(greedy, n[:, None], axis=1)[:, 0]

        # --- emit d_1..d_n then the bonus ------------------------------
        j = jnp.arange(k + 1)[None, :]
        round_tokens = jnp.where(
            j < n[:, None],
            jnp.pad(drafts, ((0, 0), (0, 1))),
            bonus[:, None],
        )  # position j: draft j while j < n, bonus at j == n, bonus pad after
        idx = jnp.minimum(count[:, None] + j, out.shape[1] - 1)
        keep = (j <= n[:, None]) & ~done[:, None]
        current = jnp.take_along_axis(out, idx, axis=1)
        out = out.at[rows[:, None], idx].set(
            jnp.where(keep, round_tokens, current)
        )

        # --- advance: counts, pending, cache rollback by length --------
        # frozen rows advance by 0 (their draft/chunk writes this round
        # landed in slots their unchanged length keeps masked)
        advance = jnp.where(done, 0, n + 1)
        count = count + advance
        # the target consumed inputs [p, d_1..d_n] validly -> +n+1; the
        # draft consumed the same accepted prefix (its extra step covers
        # the n == k case); later entries are masked by length
        t_cache_adv = dict(t_cache_adv, length=t_len + advance)
        dc = dict(dc, length=d_len + advance)
        pending_next = jnp.where(done, pending, bonus)
        rounds = rounds + jnp.where(done, 0, 1)
        accepted_total = accepted_total + jnp.where(done, 0, n)
        if eos_id is not None:
            emitted_eos = jnp.any(
                (round_tokens == eos_id) & (j <= n[:, None]), axis=1
            )
            eos_seen = eos_seen | (~done & emitted_eos)
        return (out, count, pending_next, t_cache_adv, dc, rounds,
                accepted_total, rng, eos_seen)

    def cond(carry):
        _, count, *rest = carry
        eos_seen = rest[-1]
        return jnp.any(~row_done(count, eos_seen))

    out, count, _, _, _, rounds, accepted_total, _, _ = jax.lax.while_loop(
        cond, round_body,
        (out, count, pending, t_cache, d_cache, rounds, accepted_total,
         rng, eos_seen),
    )
    result = out[:, :num_tokens]
    if eos_id is not None:
        # pin everything from the first eos on to the id (an eos row may
        # have frozen mid-buffer; its unwritten tail holds zeros) —
        # exactly generate's post-eos padding
        hit = jnp.cumsum((result == eos_id).astype(jnp.int32), axis=1) > 0
        result = jnp.where(hit, eos_id, result)
    if return_stats:
        proposed = jnp.maximum(rounds * k, 1)
        return result, {
            "rounds": rounds,
            "acceptance_rate": accepted_total / proposed,
        }
    return result


def make_speculative_serving_fn(
    mesh,
    config_target: ModelConfig,
    params_target: dict,
    config_draft: ModelConfig,
    *,
    draft_tokens: int = 4,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    prefix_cache: dict | None = None,
    quantized_cache: bool = False,
):
    """Compile the draft-and-verify loop over a ``(data, model)`` serving
    mesh: batch rows shard over ``data``, both models' weights and KV
    caches keep their Megatron/head shardings (the same layout contract
    as :func:`.decode.compile_serving_fns` — chunk verify, single-token
    draft steps, and the per-row rollback are all row-local, so nothing
    about the speculative schedule fights the partitioner).

    ``prefix_cache`` pins a shared prompt prefix into the compiled loop
    as a replicated-batch operand (heads over ``"model"`` via
    :func:`.decode.prefix_cache_shardings`); the self-draft's prefix
    cache is derived per :func:`draft_prefix_from_target` — no second
    prefill.  ``quantized_cache`` streams both models' caches as int8
    (the caches are internal to the compiled loop, so only the flag
    changes; a given ``prefix_cache`` must match the layout).

    Returns ``run(params_target, params_draft, prompt, lengths, rng,
    num_tokens) -> [B, num_tokens]`` with ``num_tokens`` static; ``rng``
    is always an operand (ignored under greedy), so greedy and sampled
    batches share the compiled layout.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .decode import (
        _check_prefix_layout,
        prefix_cache_shardings,
        require_serving_mesh,
    )
    from .train import param_shardings

    require_serving_mesh(mesh)
    p_shard_t = param_shardings(mesh, params_target)
    # the early-exit self-draft shares the target's leaves — same
    # sharding rules, fewer layers — so its sharding tree is literally a
    # slice of the target's
    p_shard_d = dict(
        p_shard_t, layers=p_shard_t["layers"][:config_draft.n_layers]
    )
    tokens_2d = NamedSharding(mesh, P("data", None))
    tokens_1d = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    if prefix_cache is None:

        def run(params_t, params_d, prompt, lengths, rng, num_tokens):
            return speculative_generate(
                params_t, config_target, params_d, config_draft, prompt,
                num_tokens, draft_tokens=draft_tokens, lengths=lengths,
                temperature=temperature,
                rng=rng if temperature > 0.0 else None,
                top_k=top_k, top_p=top_p, eos_id=eos_id,
                quantized_cache=quantized_cache,
            )

        return jax.jit(
            run,
            static_argnames=("num_tokens",),
            in_shardings=(p_shard_t, p_shard_d, tokens_2d, tokens_1d,
                          rep),
            out_shardings=tokens_2d,
        )

    _check_prefix_layout(prefix_cache, quantized_cache)
    draft_prefix = draft_prefix_from_target(prefix_cache,
                                            config_draft.n_layers)
    pfx_shard_t = prefix_cache_shardings(mesh, prefix_cache)
    pfx_shard_d = prefix_cache_shardings(mesh, draft_prefix)
    placed_t = jax.device_put(prefix_cache, pfx_shard_t)
    placed_d = jax.device_put(draft_prefix, pfx_shard_d)

    def run_pfx(params_t, params_d, pfx_t, pfx_d, prompt, lengths, rng,
                num_tokens):
        return speculative_generate(
            params_t, config_target, params_d, config_draft, prompt,
            num_tokens, draft_tokens=draft_tokens, lengths=lengths,
            temperature=temperature,
            rng=rng if temperature > 0.0 else None,
            top_k=top_k, top_p=top_p, eos_id=eos_id,
            quantized_cache=quantized_cache,
            prefix_cache=pfx_t, draft_prefix_cache=pfx_d,
        )

    fn = jax.jit(
        run_pfx,
        static_argnames=("num_tokens",),
        in_shardings=(p_shard_t, p_shard_d, pfx_shard_t, pfx_shard_d,
                      tokens_2d, tokens_1d, rep),
        out_shardings=tokens_2d,
    )
    return lambda params_t, params_d, prompt, lengths, rng, num_tokens: (
        fn(params_t, params_d, placed_t, placed_d, prompt, lengths, rng,
           num_tokens)
    )


@partial(
    jax.jit,
    static_argnames=(
        "config_target", "config_draft", "num_tokens", "draft_tokens",
        "attention_fn", "return_stats", "temperature", "top_k", "top_p",
        "eos_id", "quantized_cache",
    ),
)
def speculative_generate_jit(
    params_target: dict,
    config_target: ModelConfig,
    params_draft: dict,
    config_draft: ModelConfig,
    prompt: jax.Array,
    num_tokens: int,
    draft_tokens: int = 4,
    attention_fn=None,
    lengths: jax.Array | None = None,
    return_stats: bool = False,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
    draft_prefix_cache: dict | None = None,
) -> jax.Array:
    """Compiled :func:`speculative_generate` (one program: prefills +
    the whole while_loop of rounds)."""
    return speculative_generate(
        params_target, config_target, params_draft, config_draft, prompt,
        num_tokens, draft_tokens=draft_tokens, attention_fn=attention_fn,
        lengths=lengths, return_stats=return_stats,
        temperature=temperature, rng=rng, top_k=top_k, top_p=top_p,
        eos_id=eos_id, quantized_cache=quantized_cache,
        prefix_cache=prefix_cache, draft_prefix_cache=draft_prefix_cache,
    )
