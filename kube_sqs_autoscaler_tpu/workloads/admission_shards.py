"""The sharded admission plane: N crash-tolerant admission workers.

A million tenants break the single staging plane before they break the
fleet (ROADMAP item 4): every refill cycle the lone
:class:`~.tenancy.FairAdmission` pays O(active tenants) of serial host
work — rate decays, flood scans, DRR registration churn — and that one
instance is also the last unreplicated failure domain in the stack.
This module splits it MQFQ-Sticky-style (PAPERS.md):

- :class:`HashRing` — consistent hashing with virtual nodes (crc32,
  process-stable like :func:`~.tenancy.prefix_pool_key`) maps tenants
  to shards; changing N moves only ~1/N of the population, and the
  sticky home map pins a tenant where it first staged so its prefix
  home and DRR state live on ONE shard across restarts;
- :class:`AdmissionShard` — one slice of the plane: its own
  :class:`~.tenancy.FairAdmission` (DRR + EDF + flood classifier) over
  its tenant slice and, when ``shed_tiers`` asks for one, its own
  :class:`~.tenancy.OverloadLadder` — one shard's overload engages
  tier actions for ITS tenants without degrading anyone else's;
- :class:`AdmissionCoordinator` — global fairness across shards the
  way DRR credits already work: each busy shard earns pick credit
  proportional to its staged tenants' weight, banks per-busy-period
  debt (reset on idle, like DRR's reset-on-empty), and may go
  work-conservingly beyond its share only through a rate-bounded
  borrow bucket — so one shard's flood cannot starve another shard's
  victims by more than a bounded, refunded debt;
- :class:`ShardedAdmission` — the facade the worker talks to.  It
  duck-types ``FairAdmission``'s whole surface (``note_cycle`` /
  ``room`` / ``stage`` / ``pick`` / ``over_share`` / ``.drr`` / the
  durable-state pair), so ``ContinuousWorker`` and the fleet's
  snapshot machinery run unchanged; ``admission_shards=1`` never
  constructs this module at all — the single plane stays byte-
  identical.

Crash tolerance: :meth:`ShardedAdmission.kill_shard` tombstones the
shard's deficit/credit/flood state, hands every staged request back to
the queue via the worker's ``change_message_visibility(0)`` callback
(at-least-once: redelivered, never lost; the pool reply registry still
dedups, so exactly-once holds end to end), and the next
:meth:`~ShardedAdmission.note_cycle` rehydrates the shard from its
tombstone plus peer gossip — NOT cold.  What rehydration does NOT
re-drive: staged message contents (live receipt handles die with the
shard; the queue redelivers them) and already-picked requests (they
are the engine's in-flight work, not staging's).

Flood classifications GOSSIP between shards each ladder cycle
(:meth:`ShardedAdmission.gossip`): a coalition classified on its home
shard stays classified when a kill fails its tenants over to a peer,
and every newly shared classification is journaled as a
``kind="admission"`` line on the PR 13 tick journal so operators (and
the restart path) can replay who knew what, when.  A PARTITIONED shard
(chaos seam, :class:`~..sim.faults.FleetFaultPlan`
``admission_partitions``) keeps admitting its slice but neither sends
nor receives gossip until the window heals.
"""

from __future__ import annotations

import bisect
import time
import zlib
from collections import OrderedDict
from typing import Any

from .tenancy import (
    FairAdmission,
    OverloadLadder,
    TenancyConfig,
    _PoolEvent,
)


class HashRing:
    """Consistent tenant→shard hashing with virtual nodes.

    crc32-based (Python's ``hash`` is salted; the mapping must be
    stable across processes so a restarted plane routes every tenant
    to the same home).  ``vnodes`` virtual points per shard smooth the
    arc lengths, so growing N by one moves ~1/(N+1) of tenants — the
    property the hash-stability test pins."""

    #: virtual points per shard — enough to keep arc-length variance
    #: low at small N without making ring construction noticeable
    VNODES = 64

    def __init__(self, shards: int, vnodes: int = VNODES) -> None:
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        self.shards = shards
        points = []
        for shard in range(shards):
            for v in range(vnodes):
                key = f"admission-shard:{shard}:{v}".encode()
                points.append((zlib.crc32(key), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [s for _, s in points]

    def shard_of(self, tenant: str, alive=None) -> int:
        """The tenant's home shard; ``alive`` (a set of shard indices,
        or None = all) walks the ring past dead owners so a killed
        shard's tenants fail over deterministically to the next alive
        point instead of erroring."""
        h = zlib.crc32(str(tenant).encode())
        start = bisect.bisect_right(self._hashes, h)
        n = len(self._owners)
        for step in range(n):
            owner = self._owners[(start + step) % n]
            if alive is None or owner in alive:
                return owner
        raise ValueError("no alive admission shard to route to")


class AdmissionShard:
    """One slice of the sharded plane: its own staging, classifier,
    and (optionally) overload ladder, plus the liveness flags the
    chaos seams flip."""

    def __init__(
        self, index: int, tenancy: TenancyConfig, *,
        per_tenant_limit: int, total_limit: int,
    ) -> None:
        self.index = index
        self.tenancy = tenancy
        self.per_tenant_limit = per_tenant_limit
        self.total_limit = total_limit
        self.fair = FairAdmission(
            tenancy,
            per_tenant_limit=per_tenant_limit,
            total_limit=total_limit,
        )
        self.ladder = (
            OverloadLadder(tenancy.shed_tiers)
            if tenancy.shed_tiers > 0 else None
        )
        self.alive = True
        self.partitioned = False
        self.kills = 0
        self.rehydrations = 0
        #: records recovered by the LAST rehydration (the chaos gate's
        #: "rehydrated, not cold" evidence)
        self.rehydrated_records = 0
        #: exported state captured at kill time, consumed at restart
        self.tombstone: "dict | None" = None

    def _fresh_fair(self) -> FairAdmission:
        return FairAdmission(
            self.tenancy,
            per_tenant_limit=self.per_tenant_limit,
            total_limit=self.total_limit,
        )


class AdmissionCoordinator:
    """Global fairness across admission shards, DRR-style.

    Each pick cycle every BUSY shard (staged work > 0) earns credit
    proportional to its staged tenants' configured weight; a shard
    spends one credit per picked request.  Credit banks only within a
    busy period — an idle shard's balance resets to zero, the exact
    reset-on-empty rule that bounds DRR deficits — so no shard can
    hoard entitlement while idle and then burst past everyone.

    The work-conserving pass then hands LEFTOVER capacity (credit the
    entitled shards could not use) to shards with remaining demand, in
    rotating-cursor order, but each extra grant costs a token from
    that shard's rate-bounded borrow bucket (refilled
    :data:`BORROW_REFILL` per cycle, capped at :data:`BORROW_CAP`) and
    is charged as negative credit — debt the shard repays out of its
    future earnings.  The invariant the property tests pin: no
    shard's debt ever exceeds ``BORROW_CAP``, so the total share a
    flooded shard can take from its peers over any window is their
    proportional entitlement plus a constant — a flood cannot starve
    another shard's victims, only briefly borrow from them."""

    #: borrow tokens refilled per cycle (the cross-shard borrow RATE)
    BORROW_REFILL = 1.0
    #: max banked borrow tokens — and the per-shard debt bound
    BORROW_CAP = 4.0

    def __init__(self, shards: int) -> None:
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        self.shards = shards
        self._credit = [0.0] * shards
        self._borrow = [self.BORROW_CAP] * shards
        self._cursor = 0
        self.borrows_total = 0

    def debt(self, shard: int) -> float:
        """How far ``shard`` has picked beyond its earned share this
        busy period (>= 0; the invariant bounds it by BORROW_CAP)."""
        return max(0.0, -self._credit[shard])

    def allocate(
        self, k: int, demands, weights,
    ) -> "list[int]":
        """Split ``k`` pick slots across shards given per-shard staged
        ``demands`` and active staged ``weights``; returns per-shard
        grants summing to at most ``min(k, sum(demands))``."""
        n = self.shards
        grants = [0] * n
        busy = [s for s in range(n) if demands[s] > 0]
        for s in range(n):
            self._borrow[s] = min(
                self.BORROW_CAP, self._borrow[s] + self.BORROW_REFILL
            )
            if demands[s] <= 0:
                # busy period over: entitlement does not bank across
                # idle gaps (reset-on-empty), and neither does debt —
                # the backlog that owed it is gone
                self._credit[s] = 0.0
        if not busy or k <= 0:
            return grants
        wtotal = sum(max(0.0, weights[s]) for s in busy) or float(len(busy))
        remaining = k
        for s in busy:
            share = (max(0.0, weights[s]) or 1.0) / wtotal
            self._credit[s] += k * share
            # banked credit from under-granted cycles can exceed this
            # cycle's slice: cap at what is left of k so the plane
            # never picks past the engine's free slots (the surplus
            # stays banked for the next cycle)
            grant = min(demands[s], int(self._credit[s]), remaining)
            if grant > 0:
                grants[s] = grant
                self._credit[s] -= grant
                remaining -= grant
        # work conservation: leftover capacity (fractional credits,
        # idle entitlement) goes to shards that still have demand —
        # rate-bounded, charged as debt
        leftover = k - sum(grants)
        spin = 0
        while leftover > 0 and spin < 2 * len(busy):
            s = busy[self._cursor % len(busy)]
            self._cursor += 1
            spin += 1
            if demands[s] - grants[s] <= 0 or self._borrow[s] < 1.0:
                continue
            self._borrow[s] -= 1.0
            self._credit[s] -= 1.0
            if self._credit[s] < -self.BORROW_CAP:
                # the debt bound is an invariant, not a hope: clamp so
                # arithmetic drift can never widen what a borrow
                # bucket's worth of tokens allows
                self._credit[s] = -self.BORROW_CAP
            grants[s] += 1
            self.borrows_total += 1
            leftover -= 1
            spin = 0
        return grants

    def export_state(self) -> dict:
        return {
            "records": self.shards,
            "credit": list(self._credit),
            "borrow": list(self._borrow),
            "cursor": self._cursor,
            "borrows_total": self.borrows_total,
        }

    def import_state(self, state: dict) -> int:
        recovered = 0
        for name, default in (("credit", 0.0), ("borrow", self.BORROW_CAP)):
            values = state.get(name)
            if not isinstance(values, (list, tuple)):
                continue
            dest = self._credit if name == "credit" else self._borrow
            for s, value in enumerate(values[: self.shards]):
                try:
                    dest[s] = float(value)
                except (TypeError, ValueError):
                    dest[s] = default
                recovered += 1
        self._cursor = int(state.get("cursor", 0) or 0)
        self.borrows_total = int(state.get("borrows_total", 0) or 0)
        return recovered


class _ShardedDrr:
    """The ``.drr`` facade: ContinuousWorker reaches into
    ``_fair.drr`` for push/refund (the no-nack fallback and the
    expired-pick refund) and the shed loops reach it per shard — every
    call here routes by the tenant's home so the charge lands on the
    scheduler that staged the request."""

    def __init__(self, plane: "ShardedAdmission") -> None:
        self._plane = plane

    def _drr_of(self, tenant: str):
        return self._plane.shard_of(tenant).fair.drr

    def push(self, tenant: str, item: Any,
             deadline: "float | None" = None) -> None:
        self._drr_of(tenant).push(tenant, item, deadline=deadline)

    def refund(self, tenant: str, item: Any = None) -> None:
        self._drr_of(tenant).refund(tenant, item)

    def depth(self, tenant: str) -> int:
        return self._drr_of(tenant).depth(tenant)

    def depths(self) -> dict:
        merged: dict[str, int] = {}
        for shard in self._plane.shards:
            for tenant, depth in shard.fair.drr.depths().items():
                merged[tenant] = merged.get(tenant, 0) + depth
        return merged

    def pop_over_deadline(
        self, now: float, eligible=None,
    ) -> "tuple[str, Any] | None":
        for shard in self._plane.shards:
            if not shard.alive:
                continue
            popped = shard.fair.drr.pop_over_deadline(
                now, eligible=eligible
            )
            if popped is not None:
                return popped
        return None

    def pop_tail(self, tenant: str) -> "Any | None":
        return self._drr_of(tenant).pop_tail(tenant)

    @property
    def staged(self) -> int:
        return sum(s.fair.drr.staged for s in self._plane.shards)

    @property
    def urgent_picks(self) -> int:
        return sum(s.fair.drr.urgent_picks for s in self._plane.shards)


class ShardedAdmission:
    """N :class:`AdmissionShard`s behind one ``FairAdmission``-shaped
    facade (see the module docstring for the architecture)."""

    def __init__(
        self, tenancy: TenancyConfig, *,
        per_tenant_limit: int, total_limit: int,
    ) -> None:
        n = tenancy.admission_shards
        if n < 2:
            raise ValueError(
                "ShardedAdmission needs admission_shards >= 2; the "
                "single plane is plain FairAdmission (byte-identical)"
            )
        if per_tenant_limit < 1 or total_limit < 1:
            raise ValueError("staging limits must be >= 1")
        self.tenancy = tenancy
        self.per_tenant_limit = per_tenant_limit
        # the GLOBAL staging bound is unchanged by sharding; each shard
        # owns an equal slice (ceil so N never rounds capacity to 0)
        self.total_limit = total_limit
        per_shard = max(2, -(-total_limit // n))
        self.ring = HashRing(n)
        self.shards = [
            AdmissionShard(
                i, tenancy,
                per_tenant_limit=per_tenant_limit,
                total_limit=per_shard,
            )
            for i in range(n)
        ]
        self.coordinator = AdmissionCoordinator(n)
        self.drr = _ShardedDrr(self)
        # sticky home map: tenant -> shard, pinned at first stage and
        # exported with the durable state so a rehydrated plane keeps
        # every tenant's home (ring changes move only unpinned tenants)
        self._homes: OrderedDict = OrderedDict()
        self.HOME_LIMIT = 8192
        # worker-incremented, like FairAdmission's (the facade keeps
        # the counter global: one backpressure series, not N)
        self.overflow_total = 0
        self._lifecycle = None
        self._journal = None
        # classifications already gossiped (so each union member
        # journals once, not once per cycle)
        self._gossiped: set[str] = set()
        # admission-kill / admission-rehydrate instants for the merged
        # Chrome-trace timeline (same shape as PrefixPool/ladder events)
        from collections import deque

        self.events = deque(maxlen=1024)

    # -- constants the worker's shed loop reads off its `fair` handle --
    PREMIUM_FLOOD_FACTOR = FairAdmission.PREMIUM_FLOOD_FACTOR
    OVER_SHARE_MIN_RATE = FairAdmission.OVER_SHARE_MIN_RATE

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------

    def _alive(self) -> set:
        return {s.index for s in self.shards if s.alive}

    def shard_of(self, tenant: str) -> AdmissionShard:
        """The tenant's current shard: its sticky home when that shard
        is alive, else the ring walked past dead owners (failover is
        deterministic, and the home re-pins once it lands)."""
        alive = self._alive()
        home = self._homes.get(tenant)
        if home is not None and home in alive:
            self._homes.move_to_end(tenant)
            return self.shards[home]
        owner = self.ring.shard_of(tenant, alive=alive or None)
        self._homes[tenant] = owner
        self._homes.move_to_end(tenant)
        while len(self._homes) > self.HOME_LIMIT:
            self._homes.popitem(last=False)
        return self.shards[owner]

    # ------------------------------------------------------------------
    # the FairAdmission facade surface
    # ------------------------------------------------------------------

    @property
    def lifecycle(self):
        return self._lifecycle

    @lifecycle.setter
    def lifecycle(self, registry) -> None:
        self._lifecycle = registry
        for shard in self.shards:
            shard.fair.lifecycle = registry

    @property
    def staged(self) -> int:
        return sum(s.fair.staged for s in self.shards)

    @property
    def room(self) -> int:
        """Receive sizing: alive shards' remaining slices (a full or
        dead shard contributes nothing — its tenants' messages bounce
        through the stage() → hand-back path, backpressure not loss)."""
        return sum(
            max(0, s.total_limit - s.fair.staged)
            for s in self.shards if s.alive
        )

    @property
    def arrival_rate(self) -> dict:
        """Merged per-tenant offered rates (introspection + the shed
        loop's premium bar; each shard still classifies on its own)."""
        merged: dict[str, float] = {}
        for shard in self.shards:
            for tenant, rate in shard.fair.arrival_rate.items():
                merged[tenant] = merged.get(tenant, 0.0) + rate
        return merged

    @property
    def host_ops(self) -> int:
        """Total serial host work across shards (the N=1-equivalent
        cost; the bench charges the MAX over shards instead — see
        :meth:`host_ops_by_shard`)."""
        return sum(s.fair.host_ops for s in self.shards)

    def host_ops_by_shard(self) -> "tuple[int, ...]":
        """Per-shard host-op counters: the admission-scale bench's
        virtual clock charges max-over-shards of the per-cycle deltas
        (shards run concurrently; the slowest one bounds the cycle)."""
        return tuple(s.fair.host_ops for s in self.shards)

    def note_cycle(self) -> None:
        """One refill cycle: restart any killed shard (the plane's
        supervisor restarts an admission worker within a cycle — the
        rehydration path, not a cold start), then decay every alive
        shard's classifier."""
        for shard in self.shards:
            if not shard.alive:
                self.restart_shard(shard.index)
        for shard in self.shards:
            if shard.alive:
                shard.fair.note_cycle()

    def stage(self, tenant: str, item: Any,
              deadline: "float | None" = None,
              message_id: "str | None" = None) -> bool:
        shard = self.shard_of(tenant)
        return shard.fair.stage(
            tenant, item, deadline=deadline, message_id=message_id
        )

    def pick(self, k: int,
             now: "float | None" = None) -> "list[tuple[str, Any]]":
        """This cycle's admission batch: the coordinator splits ``k``
        across shards by earned credit (plus bounded borrowing), each
        shard's own DRR/EDF picks its grant."""
        shards = self.shards
        demands = [
            s.fair.staged if s.alive else 0 for s in shards
        ]
        weights = []
        for s in shards:
            if not s.alive or s.fair.staged == 0:
                weights.append(0.0)
                continue
            weights.append(sum(
                self.tenancy.weight_of(t)
                for t, d in s.fair.drr.depths().items() if d > 0
            ))
        grants = self.coordinator.allocate(k, demands, weights)
        picked: list = []
        for shard, grant in zip(shards, grants):
            if grant > 0:
                picked += shard.fair.pick(grant, now=now)
        return picked

    def over_share(self) -> frozenset:
        """The union flood set across alive shards, after a gossip
        exchange — a coalition classified anywhere is degraded
        everywhere (except across a partition)."""
        self.gossip()
        flood: set = set()
        for shard in self.shards:
            if shard.alive:
                flood |= set(shard.fair.over_share())
        return frozenset(flood)

    def depths(self) -> dict:
        depths = {t: 0 for t in self.tenancy.tenants}
        for tenant, depth in self.drr.depths().items():
            depths[tenant] = depths.get(tenant, 0) + depth
        return depths

    # ------------------------------------------------------------------
    # gossip
    # ------------------------------------------------------------------

    def attach_journal(self, journal) -> None:
        """Record gossip and kill/rehydrate transitions as
        ``kind="admission"`` lines on a :class:`~..obs.TickJournal`
        (None detaches; journaling is observability + replay, never
        load-bearing for the exchange itself)."""
        self._journal = journal

    def _journal_event(self, payload: dict) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append_event("admission", payload)
        except (OSError, ValueError):  # crash-safe: gossip never dies
            pass

    def gossip(self) -> None:
        """Exchange flood classifications between alive, un-partitioned
        shards: every peer adopts the union (sticky, grace-armed), and
        each classification is journaled ONCE when it first spreads."""
        connected = [
            s for s in self.shards if s.alive and not s.partitioned
        ]
        if len(connected) < 2:
            return
        union: set = set()
        for shard in connected:
            union |= shard.fair._flood_sticky
        if not union:
            return
        for shard in connected:
            shard.fair.adopt_flood(union)
        fresh = union - self._gossiped
        if fresh:
            self._gossiped |= fresh
            self._journal_event({
                "event": "gossip",
                "flood": sorted(fresh),
                "shards": [s.index for s in connected],
            })

    # ------------------------------------------------------------------
    # chaos seams (FleetFaultPlan admission_kills / admission_partitions)
    # ------------------------------------------------------------------

    def kill_shard(self, shard: int, handback=None) -> int:
        """Kill one admission shard: tombstone its durable accounting,
        hand every staged request back to the queue through
        ``handback(message)`` (the worker wires
        ``change_message_visibility(0)``), and mark it dead until
        :meth:`restart_shard` / the next cycle's auto-restart.  Returns
        the number of staged requests handed back."""
        target = self.shards[shard]
        if not target.alive:
            return 0
        target.tombstone = target.fair.export_state()
        released = 0
        drr = target.fair.drr
        for tenant in list(drr.depths()):
            while True:
                item = drr.pop_tail(tenant)
                if item is None:
                    break
                released += 1
                if handback is not None:
                    # back through the queue: redelivers immediately,
                    # re-stages on a surviving shard next cycle — the
                    # reply registry dedups any copy racing this
                    handback(item[3])
        target.fair = target._fresh_fair()
        target.fair.lifecycle = self._lifecycle
        target.alive = False
        target.kills += 1
        self.events.append(_PoolEvent(
            "admission-kill", time.perf_counter(),
            {"shard": shard, "handed_back": released},
        ))
        self._journal_event({
            "event": "kill", "shard": shard, "handed_back": released,
        })
        return released

    def restart_shard(self, shard: int) -> int:
        """Restart a killed shard: rehydrate deficit/credit/flood
        accounting from its tombstone, then adopt the peers' current
        flood gossip — the shard comes back knowing what the plane
        knew, not cold.  Returns the number of records recovered."""
        target = self.shards[shard]
        if target.alive:
            return 0
        recovered = 0
        if target.tombstone is not None:
            recovered = target.fair.import_state(target.tombstone)
            target.tombstone = None
        target.alive = True
        target.partitioned = False
        peers_flood: set = set()
        for peer in self.shards:
            if peer.alive and not peer.partitioned and peer is not target:
                peers_flood |= peer.fair._flood_sticky
        if peers_flood:
            target.fair.adopt_flood(peers_flood)
        target.rehydrations += 1
        target.rehydrated_records = recovered
        self.events.append(_PoolEvent(
            "admission-rehydrate", time.perf_counter(),
            {"shard": shard, "records": recovered},
        ))
        self._journal_event({
            "event": "rehydrate", "shard": shard, "records": recovered,
        })
        return recovered

    def partition_shard(self, shard: int, partitioned: bool = True) -> None:
        """Flip one shard's gossip partition: it keeps admitting its
        slice but is excluded from the exchange both ways."""
        self.shards[shard].partitioned = bool(partitioned)
        self._journal_event({
            "event": "partition" if partitioned else "heal",
            "shard": shard,
        })

    def trace_events(self, origin: float) -> list:
        """Kill/rehydrate instants for the merged Chrome-trace
        timeline (same contract as the ladder's)."""
        from ..obs.trace import instant_trace_events

        return instant_trace_events(self.events, origin)

    # ------------------------------------------------------------------
    # durable-state surface: slots into ContinuousWorker's existing
    # export_admission_state "fair" key unchanged
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        shards = []
        for shard in self.shards:
            entry = {
                "fair": shard.fair.export_state(),
                "alive": shard.alive,
                "kills": shard.kills,
                "rehydrations": shard.rehydrations,
            }
            if shard.ladder is not None:
                entry["ladder"] = shard.ladder.export_state()
            shards.append(entry)
        state = {
            "sharded": True,
            "shards": shards,
            "coordinator": self.coordinator.export_state(),
            "homes": [
                [tenant, int(shard)]
                for tenant, shard in self._homes.items()
            ],
            "overflow_total": self.overflow_total,
        }
        state["records"] = (
            sum(e["fair"].get("records", 0) for e in shards)
            + len(self._homes)
        )
        return state

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: "float | None" = None, max_age_s: float = 0.0,
    ) -> int:
        recovered = 0
        entries = state.get("shards") or ()
        for shard, entry in zip(self.shards, entries):
            if not isinstance(entry, dict):
                continue
            fair = entry.get("fair")
            if isinstance(fair, dict):
                recovered += shard.fair.import_state(
                    fair, rebase=rebase, now=now, max_age_s=max_age_s
                )
            ladder = entry.get("ladder")
            if shard.ladder is not None and isinstance(ladder, dict):
                recovered += shard.ladder.import_state(ladder)
            shard.kills = int(entry.get("kills", 0) or 0)
            shard.rehydrations = int(entry.get("rehydrations", 0) or 0)
        coordinator = state.get("coordinator")
        if isinstance(coordinator, dict):
            recovered += self.coordinator.import_state(coordinator)
        for entry in state.get("homes") or ():
            try:
                tenant, shard = entry
                tenant, shard = str(tenant), int(shard)
            except (TypeError, ValueError):
                continue
            if not 0 <= shard < len(self.shards):
                continue
            self._homes[tenant] = shard
            self._homes.move_to_end(tenant)
            recovered += 1
            while len(self._homes) > self.HOME_LIMIT:
                self._homes.popitem(last=False)
        self.overflow_total = int(state.get("overflow_total", 0) or 0)
        return recovered
