"""Sharded training/inference compilation over a device mesh.

Scaling here is pure JAX SPMD: pick a ``Mesh`` with ``("data", "model")``
axes, annotate parameter/batch shardings with ``NamedSharding`` /
``PartitionSpec``, ``jax.jit`` the step, and let XLA insert the collectives
(all-reduce for data-parallel grads, all-gather/reduce-scatter around the
Megatron-style tensor-parallel matmuls) so they ride ICI.

Sharding rules (classic Megatron pairing, applied via
:data:`~.model.PARAM_AXES` logical names):

- ``wqkv``/``w_up`` shard their *output* axis over ``model``;
- ``wo``/``w_down`` shard their *input* axis over ``model`` (the pair's
  all-reduce happens once, after the second matmul);
- the embedding shards its vocab axis over ``model`` (the fp32 logits
  einsum then reduce-scatters naturally);
- layernorm scales/biases replicate;
- activations/batches shard over ``data``.

Optimizer state inherits each parameter's sharding, so Adam moments are
distributed exactly like the weights (ZeRO-1-style for the tensor-parallel
shards, replicated across ``data``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .model import ModelConfig, forward, init_params

# logical axis name (model.PARAM_AXES) -> mesh axis
_LOGICAL_TO_MESH = {
    "vocab": "model",
    "three_heads": "model",
    "heads": "model",
    "ff": "model",
    "model": None,  # d_model axes replicate (Megatron 1D sharding)
    "seq": None,
    "expert": "data",  # expert parallelism rides the data axis (ep=dp)
    "experts_out": None,  # router output axis (n_experts) replicates
    # llama family (workloads.llama): fused kv / gate-up projections shard
    # their output axis tensor-parallel like the query/ff projections
    "kv_heads": "model",
    "ff2": "model",
}


def mesh_shape(
    n_devices: int, model_parallel: int, seq_parallel: int
) -> tuple[int, int, int]:
    """Validated ``(data, seq, model)`` axis sizes for ``n_devices`` — the
    one place the mesh contract's arithmetic lives (shared with
    :mod:`.distributed`)."""
    if n_devices % (model_parallel * seq_parallel):
        raise ValueError(
            f"{n_devices} devices not divisible by "
            f"model_parallel={model_parallel} x seq_parallel={seq_parallel}"
        )
    return (
        n_devices // (model_parallel * seq_parallel),
        seq_parallel,
        model_parallel,
    )


def make_mesh(
    devices: list | None = None,
    model_parallel: int | None = None,
    seq_parallel: int = 1,
) -> Mesh:
    """A ``("data", "seq", "model")`` mesh over the available devices.

    ``model_parallel`` defaults to the largest power of two <= 4 dividing the
    device count — small TP degree, rest data-parallel, the usual
    bandwidth-friendly default for small models.  ``seq_parallel`` > 1 adds
    sequence/context parallelism: batches shard their sequence axis over
    ``"seq"`` and attention runs as ring attention (:mod:`.ring`).
    Devices are used in enumeration order; on real hardware prefer
    :func:`.distributed.make_topology_mesh`, which orders them along the
    physical ICI torus.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if model_parallel is None:
        model_parallel = 1
        for candidate in (4, 2):
            if n % (candidate * seq_parallel) == 0:
                model_parallel = candidate
                break
    import numpy as np

    grid = np.asarray(devices).reshape(
        mesh_shape(n, model_parallel, seq_parallel)
    )
    return Mesh(grid, ("data", "seq", "model"))


def mesh_attention_fn(mesh: Mesh, window: int | None = None):
    """Ring attention when the mesh has a nontrivial ``seq`` axis, else the
    per-shard flash-or-dense dispatcher (:func:`.flash.make_sharded_attention`)
    — on TPU this is what puts the Pallas flash kernel (forward *and*
    backward) on the training hot path.

    ``window`` threads sliding-window attention through the seam: the
    windowed flash block-skip / windowed dense mask per shard on a
    ``(data, model)`` mesh, and the windowed ring schedule (a global
    band mask per hop — :func:`.ring.make_ring_attention`) on a ``seq``
    mesh, so Mistral-style configs train under sequence parallelism too.
    The zig-zag schedule remains windowless (its permuted blocks have no
    banded form; :func:`.zigzag.make_zigzag_loss` rejects windowed
    configs).
    """
    if mesh.shape.get("seq", 1) > 1:
        from .ring import make_ring_attention

        return make_ring_attention(mesh, window=window)
    from .flash import make_sharded_attention

    return make_sharded_attention(mesh, window=window)


def _param_spec(path: tuple, mesh: Mesh) -> P:
    from .model import PARAM_AXES

    name = path[-1]
    # quantized weights (.quantize.QuantizedTensor) flatten into
    # codes [in, out] + scale [out] under the weight's name: codes take
    # the weight's spec, the per-output-channel scale takes the output
    # axis's slice of it (replicated for row-parallel weights, whose
    # output axis replicates)
    if (
        name in ("codes", "scale")
        and len(path) >= 2
        and path[-2] in PARAM_AXES
    ):
        axes = PARAM_AXES[path[-2]]
        if name == "codes":
            return P(*(_LOGICAL_TO_MESH[a] for a in axes))
        return P(_LOGICAL_TO_MESH[axes[-1]])
    axes = PARAM_AXES.get(name)
    if axes is None:
        return P()
    return P(*(_LOGICAL_TO_MESH[a] for a in axes))


def param_shardings(mesh: Mesh, params: Any) -> Any:
    """NamedSharding pytree matching ``params`` (by PARAM_AXES rules)."""

    def spec_for(path, leaf):
        keys = tuple(
            p.key if hasattr(p, "key") else str(p.idx) for p in path
            if hasattr(p, "key") or hasattr(p, "idx")
        )
        return NamedSharding(mesh, _param_spec(keys or ("",), mesh))

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    # tokens [B, S]: batch over data, sequence over seq (trivial when sp=1)
    if "seq" in mesh.shape:
        return NamedSharding(mesh, P("data", "seq"))
    return NamedSharding(mesh, P("data", None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    # remat: recompute block activations in the backward pass instead of
    # keeping them resident in HBM — the standard TPU memory/FLOPs trade
    # (jax.checkpoint around the loss).  Identical results, lower peak HBM.
    remat: bool = False
    # grad_accum > 1 splits each batch into that many microbatches and
    # averages their grads under one optimizer step (lax.scan, so the
    # compiled program is one XLA module regardless of the count) —
    # large effective batches without large resident activations.
    grad_accum: int = 1

    # learning-rate schedule: constant by default (reference-free choice);
    # warmup_steps > 0 adds linear warmup from 0, decay_steps > 0 adds
    # cosine decay to min_lr_ratio * learning_rate over that many steps —
    # together the standard warmup-cosine LM recipe.
    warmup_steps: int = 0
    decay_steps: int = 0
    min_lr_ratio: float = 0.1

    # > 0: clip the global gradient norm to this value before the Adam
    # update (optax.clip_by_global_norm — the global norm is computed over
    # the whole pytree, so under SPMD the all-reduce of sharded-grad norms
    # is inserted by XLA; the clip composes with grad_accum and with
    # pipeline's hand-built value_and_grad alike since it acts on the
    # final gradient).  0 = no clipping (default, matches prior behavior).
    grad_clip_norm: float = 0.0

    def __post_init__(self) -> None:
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum={self.grad_accum} must be >= 1")
        if self.warmup_steps < 0 or self.decay_steps < 0:
            raise ValueError("warmup_steps/decay_steps must be >= 0")
        if self.grad_clip_norm < 0:
            raise ValueError(
                f"grad_clip_norm={self.grad_clip_norm} must be >= 0"
            )

    def schedule(self):
        """The optax learning-rate schedule this config describes."""
        if self.warmup_steps == 0 and self.decay_steps == 0:
            return self.learning_rate
        if self.decay_steps == 0:
            return optax.linear_schedule(
                0.0, self.learning_rate, self.warmup_steps
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=self.learning_rate,
            warmup_steps=self.warmup_steps,
            decay_steps=self.warmup_steps + self.decay_steps,
            end_value=self.min_lr_ratio * self.learning_rate,
        )


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    adamw = optax.adamw(
        config.schedule(), b1=config.b1, b2=config.b2,
        weight_decay=config.weight_decay,
    )
    if config.grad_clip_norm > 0:
        return optax.chain(
            optax.clip_by_global_norm(config.grad_clip_norm), adamw
        )
    return adamw


def next_token_nll(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy from full-sequence logits (fp32).

    The shift happens on the *logits*, so the input length stays divisible
    by the ``seq`` mesh axis under sequence parallelism.  Shared by the
    dense (:func:`loss_fn`) and MoE (:func:`.moe.moe_loss_fn`) objectives.
    """
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, targets[..., None], axis=-1)
    return jnp.mean(nll)


@jax.custom_vjp
def fused_next_token_nll(
    embed: jax.Array, x: jax.Array, tokens: jax.Array
) -> jax.Array:
    """``next_token_nll(unembed(x, embed), tokens)`` without the logits
    residual.

    The plain composition differentiates into the single most expensive
    non-model computation of a train step: autodiff saves the fp32
    ``[B, S, vocab]`` logits for the backward (0.5 GiB at the flagship
    bench shape) and then runs both backward matmuls in fp32 — measured
    59 ms of the 205 ms step (TPU v5e, B=8 S=2048 V=8192), ~4x slower
    than the MXU's bf16 path.

    This ``custom_vjp`` keeps the forward *bit-identical* (same einsum,
    same max/exp/sum reduction as ``jax.nn.log_softmax``) but saves only
    ``(embed, x, tokens, lse)`` — the per-row logsumexp is ``[B, S-1]``,
    ~vocab times smaller than the logits — and recomputes the logits in
    the backward with one extra bf16 einsum, so ``d x`` / ``d embed``
    are bf16 MXU matmuls (19 ms total for the same shapes).  Gradients
    are cast to the storage dtype of ``x``/``embed``: fp32 test configs
    keep exact fp32 backward numerics.

    ``tokens`` is nondifferentiable; loss = mean over the ``[B, S-1]``
    shifted targets, exactly :func:`next_token_nll`'s reduction.
    """
    from .model import unembed

    return next_token_nll(unembed(x, embed), tokens)


def _fused_nll_fwd(embed, x, tokens):
    from .model import unembed

    # slice the hidden states before the einsum (same values as slicing
    # the logits after — identical rows — without the last position's
    # [B, V] logits ever being computed); mirrors _fused_nll_bwd
    logits = unembed(x[:, :-1], embed)
    targets = tokens[:, 1:]
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1))
    tgt_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[
        ..., 0
    ]
    return jnp.mean(lse - tgt_logit), (embed, x, tokens, lse)


def _fused_nll_bwd(residuals, g):
    from .model import unembed

    embed, x, tokens, lse = residuals
    targets = tokens[:, 1:]
    x_shift = x[:, :-1]
    logits = unembed(x_shift, embed)  # recomputed, bf16 MXU
    probs = jnp.exp(logits - lse[..., None])
    # d loss/d logits = (softmax - onehot(target)) / n_targets; the onehot
    # via an iota compare (not scatter) so the SPMD partitioner keeps it
    # elementwise under any vocab/batch sharding
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
        == targets[..., None]
    )
    dlogits = (
        (probs - onehot.astype(jnp.float32)) * (g / targets.size)
    ).astype(x.dtype)
    dx_shift = jnp.einsum("bsv,vd->bsd", dlogits, embed)
    dx = jnp.concatenate([dx_shift, jnp.zeros_like(x[:, -1:])], axis=1)
    dembed = jnp.einsum("bsv,bsd->vd", dlogits, x_shift).astype(embed.dtype)
    return dembed, dx, None


fused_next_token_nll.defvjp(_fused_nll_fwd, _fused_nll_bwd)


def loss_fn(
    params: Any,
    tokens: jax.Array,
    config: ModelConfig,
    attention_fn=None,
    remat: bool = False,
) -> jax.Array:
    """Next-token cross-entropy in fp32 (the standard LM objective).

    Runs the hidden-state forward plus :func:`fused_next_token_nll` —
    same value as ``next_token_nll(forward(...), tokens)`` bit for bit,
    with the memory-lean recomputing backward."""
    from .model import forward_hidden

    return fused_next_token_nll(
        params["embed"],
        forward_hidden(params, tokens, config, attention_fn, remat=remat),
        tokens,
    )


def init_train_state(
    rng: jax.Array,
    model_config: ModelConfig,
    train_config: TrainConfig,
    init_fn=init_params,
) -> dict:
    """Fresh params (via ``init_fn(rng, model_config)``) + optimizer state."""
    params = init_fn(rng, model_config)
    opt_state = make_optimizer(train_config).init(params)
    return {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}


def state_shardings(
    mesh: Mesh, state: dict, param_shardings_fn: Any = None
) -> dict:
    """Shard optimizer moments like their parameters; scalars replicate.

    ``param_shardings_fn(mesh, params)`` overrides the parameter placement
    rules (default: the PARAM_AXES rules in :func:`param_shardings`;
    :mod:`.pipeline` passes its stage-stacked rules) — the Adam-moment
    mirroring is the same for every variant.
    """
    p_shardings = (param_shardings_fn or param_shardings)(mesh, state["params"])

    # optax.adamw state: (ScaleByAdamState(count, mu, nu), EmptyState/...);
    # wrapping transforms (e.g. the grad_clip_norm chain) nest that tuple
    # one level deeper, so the walk recurses through plain tuples
    # (NamedTuple states like ScaleByAdamState/EmptyState are handled as
    # leaves — they carry _fields).
    def shard_opt(opt_state):
        def map_one(entry):
            if hasattr(entry, "mu"):  # ScaleByAdamState
                return entry._replace(
                    count=replicated(mesh),
                    mu=p_shardings,
                    nu=p_shardings,
                )
            if isinstance(entry, tuple) and not hasattr(entry, "_fields"):
                return tuple(map_one(e) for e in entry)
            return jax.tree.map(lambda _: replicated(mesh), entry)

        return tuple(map_one(e) for e in opt_state)

    return {
        "params": p_shardings,
        "opt_state": shard_opt(state["opt_state"]),
        "step": replicated(mesh),
    }


def place_state(
    mesh: Mesh, state: dict, state_shardings_fn: Any = None
) -> dict:
    """Device-put the state pytree onto the mesh with its shardings."""
    shardings = (state_shardings_fn or state_shardings)(mesh, state)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), state, shardings,
        is_leaf=lambda x: x is None,
    )


def accumulate_value_and_grad(vag: Any, accum: int, accum_axis: int = 0):
    """Wrap ``vag(params, tokens) -> (loss, grads)`` in fp32 chunked
    gradient accumulation over ``accum`` microbatches (``accum == 1``
    returns ``vag`` untouched).

    Chunks interleave along ``accum_axis`` — microbatch ``j`` takes rows
    ``≡ j (mod accum)`` — so each data-parallel shard contributes evenly
    to every microbatch and the split stays shard-local.  One
    ``lax.scan``, so the compiled program is a single XLA module
    regardless of the count.  The one accumulation implementation for
    every step variant (dense/llama/moe objectives, the pipeline's
    custom 1F1B backward via ``accum_axis=1``, LoRA's adapter-only
    backward).
    """
    if accum == 1:
        return vag

    def wrapped(params, tokens):
        ax = accum_axis
        n = tokens.shape[ax]
        if n % accum:
            raise ValueError(
                f"batch axis {ax} (size {n}) not divisible by "
                f"grad_accum={accum}"
            )
        shape = tokens.shape
        micro = jnp.moveaxis(
            tokens.reshape(*shape[:ax], n // accum, accum, *shape[ax + 1:]),
            ax + 1, 0,
        )

        def one(carry, microbatch):
            loss_sum, grad_sum = carry
            l, g = vag(params, microbatch)
            # fp32 accumulation regardless of the grad dtype
            grad_sum = jax.tree.map(
                lambda acc, grad: acc + grad.astype(jnp.float32), grad_sum, g
            )
            return (loss_sum + l, grad_sum), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, grad_sum), _ = jax.lax.scan(
            one, (jnp.zeros((), jnp.float32), zeros), micro
        )
        grads = jax.tree.map(
            lambda g, p: (g / accum).astype(p.dtype), grad_sum, params
        )
        return loss_sum / accum, grads

    return wrapped


def make_train_step(
    mesh: Mesh,
    model_config: ModelConfig,
    train_config: TrainConfig,
    state: dict,
    loss: Any = None,
    state_shardings_fn: Any = None,
    batch_sharding_fn: Any = None,
    value_and_grad_fn: Any = None,
    window: int | None = None,
    accum_axis: int = 0,
):
    """Compile one optimizer step over the mesh.

    Returns ``step_fn(state, tokens) -> (state, loss)`` with input/output
    shardings pinned so repeated calls stay stable (no resharding churn).
    Three seams keep this the single optimizer-step implementation for all
    model variants: ``loss(params, tokens, attention_fn) -> scalar``
    overrides the objective (default :func:`loss_fn`; :mod:`.moe` passes
    its aux-augmented loss, :mod:`.pipeline` its microbatched one), and
    ``state_shardings_fn(mesh, state)`` / ``batch_sharding_fn(mesh)``
    override the placement rules (default: the PARAM_AXES rules here;
    :mod:`.pipeline` passes its stage-stacked rules).
    ``value_and_grad_fn(params, tokens) -> (loss, grads)`` replaces
    autodiff of ``loss`` entirely — for schedules that compute their own
    backward (the 1F1B pipeline); ``grad_accum`` composes with it by
    chunking the batch and scanning the custom backward per chunk.
    ``accum_axis`` is the tokens axis gradient accumulation splits
    (default 0, the batch axis; the pipeline's microbatch-major
    ``[M, B_m, S]`` batches pass 1 — axis 0 is the schedule's own).
    """
    optimizer = make_optimizer(train_config)
    shardings = (state_shardings_fn or state_shardings)(mesh, state)
    batch_shard = (batch_sharding_fn or batch_sharding)(mesh)
    # ``window`` reaches every objective through the shared seam (see
    # mesh_attention_fn) — the llama/moe factories pass their config's
    # sliding_window so no consumer re-plumbs it by hand
    attention_fn = mesh_attention_fn(mesh, window=window)
    if loss is None:
        loss = partial(
            loss_fn, config=model_config, remat=train_config.remat
        )
    # custom losses opt into remat themselves (forward's remat flag)

    def vag(params, tokens):
        if value_and_grad_fn is not None:
            return value_and_grad_fn(params, tokens)
        return jax.value_and_grad(loss)(
            params, tokens, attention_fn=attention_fn
        )

    compute_grads = accumulate_value_and_grad(
        vag, train_config.grad_accum, accum_axis
    )

    def train_step(state, tokens):
        loss_value, grads = compute_grads(state["params"], tokens)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state, "step": state["step"] + 1},
            loss_value,
        )

    return jax.jit(
        train_step,
        in_shardings=(shardings, batch_shard),
        out_shardings=(shardings, replicated(mesh)),
        donate_argnums=0,
    )


def make_forward_step(
    mesh: Mesh, model_config: Any, params: Any, forward_fn: Any = None
):
    """Compile sharded batch inference (the serving path workers run).

    ``forward_fn(params, tokens, config, attention_fn)`` defaults to the
    gpt-family :func:`.model.forward`; the llama family passes
    ``llama.llama_forward`` (the mesh attention seam is GQA-native, so
    the same wiring serves both).  A ``sliding_window`` on the config is
    read off it and threaded through the seam.
    """
    p_shardings = param_shardings(mesh, params)
    attention_fn = mesh_attention_fn(
        mesh, window=getattr(model_config, "sliding_window", None)
    )
    forward_fn = forward_fn or forward

    def forward_step(params, tokens):
        return forward_fn(params, tokens, model_config, attention_fn)

    return jax.jit(
        forward_step,
        in_shardings=(p_shardings, batch_sharding(mesh)),
        out_shardings=batch_sharding(mesh),
    )
