"""Input pipeline: token streams with host-to-device prefetch.

The reference has no data path at all (its "data" is one integer queue
depth); the trainer here needs one.  Two pieces:

- :func:`synthetic_token_stream` — an endless deterministic stream of
  ``[batch, seq]`` int32 batches (NumPy, host-side).  The demo/test data
  source and the template for a real one (anything yielding ndarrays
  works).
- :func:`prefetch_to_mesh` — wraps any batch iterator and keeps ``depth``
  batches ahead already transferred to the mesh with the given sharding,
  so the host->HBM copy of batch ``n+1`` overlaps the device compute of
  batch ``n`` (``jax.device_put`` is async; the deque holds the in-flight
  transfers).  The standard double-buffering recipe — without it the MXU
  idles for a full PCIe/DMA copy between every step.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding


def synthetic_token_stream(
    vocab_size: int, batch: int, seq: int, seed: int = 0
) -> Iterator[np.ndarray]:
    """Endless ``[batch, seq]`` int32 batches, deterministic per seed."""
    rng = np.random.default_rng(seed)
    while True:
        yield rng.integers(0, vocab_size, (batch, seq), dtype=np.int32)


def corpus_token_stream(
    data_dir: str,
    batch: int,
    seq: int,
    seed: int = 0,
    start_step: int = 0,
) -> Iterator[np.ndarray]:
    """Endless random-crop batches from an on-disk token corpus via the
    native mmap reader (``native/tokenreader.py``: C++ double-buffered
    shard reader; ``write_token_shards`` produces the format).

    Every batch is a pure function of ``(seed, step)``, so a trainer
    resumed at step ``N`` passes ``start_step=N`` and reads exactly the
    stream the uninterrupted run would have — no data-cursor state in
    the checkpoint.
    """
    from ..native.tokenreader import TokenReader

    reader = TokenReader(data_dir, min_window=seq)
    step = start_step
    while True:
        yield reader.batch(batch, seq, seed, step)
        step += 1


def prefetch_to_mesh(
    batches: Iterable[np.ndarray],
    sharding: NamedSharding,
    depth: int = 2,
) -> Iterator[jax.Array]:
    """Yield device-resident sharded batches, ``depth`` transfers ahead.

    ``depth=0`` degenerates to plain per-step ``device_put`` (no overlap);
    ``depth=2`` is the usual sweet spot — one batch computing, one in
    flight, one being produced by the host iterator.
    """
    if depth < 0:
        raise ValueError(f"depth={depth} must be >= 0")
    queue: collections.deque[jax.Array] = collections.deque()
    it = iter(batches)
    if depth == 0:
        for batch in it:
            yield jax.device_put(batch, sharding)
        return
    try:
        while True:
            while len(queue) <= depth:
                queue.append(jax.device_put(next(it), sharding))
            yield queue.popleft()
    except StopIteration:
        while queue:
            yield queue.popleft()
