"""The scaled workload as a service: queue-draining inference workers.

This is the missing half of the reference's architecture: the reference
README deploys the autoscaler *next to* an unspecified Deployment of
queue-consumer pods (``README.md:7-17``).  Here that consumer exists — a
worker that receives token batches from an SQS-compatible queue, runs the
compiled model, and deletes processed messages — plus an elastic pool that
sizes its worker count from a Deployment's replica count, closing the whole
loop (queue → autoscaler → Deployment replicas → workers → queue) in one
process for tests and demos.

Message format: each message body is a JSON array of token ids.  Bodies are
padded/truncated to the model's configured sequence length so every batch
hits the same compiled XLA program (static shapes, no recompiles).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass
from typing import Any, Protocol

import jax.numpy as jnp
import numpy as np

from .model import ModelConfig, forward_jit

log = logging.getLogger(__name__)


class MessageQueue(Protocol):
    """What a worker needs from a queue (satisfied by
    :class:`~..metrics.fake.FakeMessageQueue` and
    :class:`~..metrics.sqs_aws.AwsSqsService`)."""

    def receive_messages(self, queue_url: str, max_messages: int = 1) -> list[dict]:
        ...

    def delete_message(self, queue_url: str, receipt_handle: str) -> None:
        ...


@dataclass
class ServiceConfig:
    queue_url: str
    batch_size: int = 8  # messages pulled (and padded) per model call
    seq_len: int = 64  # fixed length every body is padded/truncated to
    pad_token: int = 0
    idle_sleep_s: float = 0.05  # backoff when the queue is empty


class QueueWorker:
    """One worker: receive → batch → forward → delete, until stopped."""

    def __init__(
        self,
        queue: MessageQueue,
        params: Any,
        model_config: ModelConfig,
        service_config: ServiceConfig,
        forward_fn=None,
    ) -> None:
        self.queue = queue
        self.params = params
        self.model_config = model_config
        self.config = service_config
        self._forward = forward_fn or (
            lambda params, tokens: forward_jit(params, tokens, model_config)
        )
        self._stop = threading.Event()
        self.processed = 0

    def stop(self) -> None:
        self._stop.set()

    def _batch_tokens(self, bodies: list[str]) -> jnp.ndarray:
        rows = np.full(
            (self.config.batch_size, self.config.seq_len),
            self.config.pad_token,
            np.int32,
        )
        for i, body in enumerate(bodies):
            try:
                ids = json.loads(body)
            except ValueError:
                log.error("Dropping malformed message body (not JSON): %.64r", body)
                continue
            ids = np.asarray(ids, np.int32)[: self.config.seq_len]
            rows[i, : ids.size] = ids
        return jnp.asarray(rows)

    def run_once(self) -> int:
        """One receive/process/delete cycle. Returns messages processed."""
        messages = self.queue.receive_messages(
            self.config.queue_url, max_messages=self.config.batch_size
        )
        if not messages:
            return 0
        tokens = self._batch_tokens([m["Body"] for m in messages])
        logits = self._forward(self.params, tokens)
        # greedy next token per sequence; block so deletion happens strictly
        # after compute succeeds (at-least-once processing: a crash here
        # leaves messages in-flight to reappear after visibility timeout)
        jnp.argmax(logits[:, -1, :], axis=-1).block_until_ready()
        for message in messages:
            self.queue.delete_message(
                self.config.queue_url, message["ReceiptHandle"]
            )
        self.processed += len(messages)
        return len(messages)

    def run_forever(self) -> None:
        import time

        while not self._stop.is_set():
            if self.run_once() == 0:
                time.sleep(self.config.idle_sleep_s)


class ElasticWorkerPool:
    """Keeps the worker-thread count equal to a Deployment's replica count.

    In production each replica is a pod running one :class:`QueueWorker`;
    in-process this pool plays kubelet: poll the (fake or real) Deployment
    API and start/stop worker threads to match ``spec.replicas`` — which is
    exactly the surface the autoscaler actuates, closing the loop.
    """

    def __init__(self, deployment_api, deployment: str, worker_factory) -> None:
        self.api = deployment_api
        self.deployment = deployment
        self.worker_factory = worker_factory
        self.workers: list[QueueWorker] = []
        self._threads: list[threading.Thread] = []

    def reconcile(self) -> int:
        """Match worker count to the Deployment's replicas; returns count."""
        want = self.api.get(self.deployment).replicas
        while len(self.workers) < want:
            worker = self.worker_factory()
            thread = threading.Thread(target=worker.run_forever, daemon=True)
            thread.start()
            self.workers.append(worker)
            self._threads.append(thread)
        while len(self.workers) > want:
            worker = self.workers.pop()
            worker.stop()
        return len(self.workers)

    @property
    def processed(self) -> int:
        return sum(w.processed for w in self.workers)

    def stop_all(self) -> None:
        for worker in self.workers:
            worker.stop()
        for thread in self._threads:
            thread.join(timeout=30)
        self.workers.clear()
        self._threads.clear()
