"""The scaled workload as a service: queue-draining inference workers.

This is the missing half of the reference's architecture: the reference
README deploys the autoscaler *next to* an unspecified Deployment of
queue-consumer pods (``README.md:7-17``).  Here that consumer exists — a
worker that receives token batches from an SQS-compatible queue, runs the
compiled model, and deletes processed messages — plus an elastic pool that
sizes its worker count from a Deployment's replica count, closing the whole
loop (queue → autoscaler → Deployment replicas → workers → queue) in one
process for tests and demos.

Message format: each message body is a JSON array of token ids.  Bodies are
right-padded to a power-of-two **length bucket** (the smallest that holds
the batch's longest body, capped at ``seq_len``) — short batches run small
compiled programs instead of always paying the full ``seq_len``, and the
bucket set is finite so there are at most ``log2(seq_len)`` compiles per
shape family.  Per-row ``lengths`` travel with every batch: the classify
readout takes each row's *last valid* position (never a pad slot), and
generate mode decodes each row from its own prompt length with pad slots
masked out of the cache — a padded batch produces exactly what each body
would produce unpadded.

Two compute modes per worker:

- **classify** (default): one forward pass, greedy next token — the
  cheapest "drain the queue" workload;
- **generate** (``ServiceConfig.generate_tokens > 0``): treat each body as
  a prompt and decode that many continuation tokens through the KV-cache
  path (:mod:`.decode`) — the serving-shaped workload. Fixed prompt length
  and token budget keep it a single compiled program.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass
from typing import Any, Protocol

import jax.numpy as jnp
import numpy as np

from ..utils.profiling import SpanTimer, maybe_trace
from .decode import generate_jit
from .flash import attention_fn_for
from .model import ModelConfig, forward_jit_with

log = logging.getLogger(__name__)


def sampling_keys(seed: int):
    """Endless per-batch PRNG keys ``key(seed), key(seed+1), ...`` — THE
    seed-per-batch policy every generate-mode serving path shares
    (reproducible runs, non-identical batches)."""
    import itertools

    import jax

    for i in itertools.count():
        yield jax.random.key(seed + i)


def parse_request_body(body: str, tokenizer=None) -> np.ndarray | None:
    """One message body -> int32 ids, or ``None`` for a malformed
    (dropped) body.  Id-array JSON always works; with a tokenizer, plain
    text, a JSON string, or ``{"text": ...}`` JSON encodes (the two JSON
    text forms encode the same characters).  The one request-parsing
    policy — shared by the batch worker and the continuous worker.
    """
    try:
        payload = json.loads(body)
    except Exception:
        payload = None
    if payload is not None:
        if tokenizer is not None:
            text = None
            if isinstance(payload, dict) and isinstance(
                    payload.get("text"), str):
                text = payload["text"]
            elif isinstance(payload, str):
                text = payload
            if text is not None:
                return np.asarray(
                    tokenizer.encode(text), np.int32
                ).reshape(-1)
        try:
            return np.asarray(payload, np.int32).reshape(-1)
        except Exception:
            pass
    if tokenizer is not None:
        try:
            return np.asarray(tokenizer.encode(body), np.int32).reshape(-1)
        except Exception:
            pass
    # a body that is valid JSON but not an integer array ('"abc"' without
    # a tokenizer, nested lists of strings) is dropped like non-JSON, not
    # allowed to crash the worker — the message still gets deleted, so
    # poison messages are consumed rather than redelivered forever; its
    # reply (when replies are on) is an error payload, never a
    # fabricated result
    log.error("Dropping malformed message body: %.64r", body)
    return None


def parse_tenant_request(
    body: str, tokenizer=None, default_tenant: str = "default"
) -> tuple[str, np.ndarray | None, np.ndarray | None]:
    """One multi-tenant message body -> ``(tenant, prefix_ids, ids)``.

    The tenancy envelope is a JSON object: ``{"tenant": "a", "prefix":
    [...], "ids": [...]}`` (or ``"text"`` with a tokenizer) — ``tenant``
    and ``prefix`` both optional.  Everything that is NOT that envelope
    falls through to :func:`parse_request_body` verbatim and lands on
    ``default_tenant`` with no prefix, so a tenancy-enabled worker
    serves today's plain traffic unchanged (single default tenant = the
    reference path).  ``ids is None`` marks a malformed body — the same
    drop-with-error-reply contract as the plain parser.  The one
    tenant-request parsing policy, shared by the worker's fair-admission
    refill and the fleet router's re-dispatch path.
    """
    try:
        payload = json.loads(body)
    except Exception:
        payload = None
    if not isinstance(payload, dict):
        return default_tenant, None, parse_request_body(body, tokenizer)
    tenant = payload.get("tenant")
    tenant = tenant if isinstance(tenant, str) and tenant \
        else default_tenant
    prefix = None
    if isinstance(payload.get("prefix"), list):
        try:
            prefix = np.asarray(payload["prefix"], np.int32).reshape(-1)
        except Exception:
            prefix = None
    if "ids" in payload:
        try:
            return tenant, prefix, np.asarray(
                payload["ids"], np.int32
            ).reshape(-1)
        except Exception:
            log.error("Dropping malformed tenant body: %.64r", body)
            return tenant, prefix, None
    ids = parse_request_body(body, tokenizer)
    return tenant, prefix, ids


# Tenant labels come from untrusted message bodies: per-tenant
# attribution tables (tokens, TTFT samples, completion counts — and the
# Prometheus series exported from them) must not grow one entry per
# distinct label an adversary invents.  Past this many distinct labels,
# new ones fold into one catch-all series.  Lives here (not in
# continuous.py) because the jax-free fleet pool applies the same bound
# when folding retired replicas' per-tenant counts.
MAX_TENANT_SERIES = 512
OTHER_TENANTS = "~other"


def bounded_tenant_key(tenant: str, table: dict) -> str:
    """The attribution key for ``tenant`` in ``table``: itself while the
    table has room (or it already has a row), else the catch-all."""
    if tenant in table or len(table) < MAX_TENANT_SERIES:
        return tenant
    return OTHER_TENANTS


def tenant_completions(replies: dict[str, dict]) -> dict[str, int]:
    """Per-tenant completion counts from :func:`collect_replies` output.

    ``collect_replies`` already de-duplicated by request id, so counting
    its REPLIES (not raw queue messages) is what keeps per-tenant
    completions exactly-once under redelivery: a request answered twice
    on the at-least-once substrate contributes one reply here, labeled
    with the tenant its worker stamped.  Counting received messages —
    the latent FIFO assumption the pre-tenancy benches leaned on —
    double-books every redelivered copy.  Error replies (TTL sheds,
    malformed bodies) are answered but are NOT completions — skipping
    them keeps this count equal to the worker-side
    ``completed_by_tenant``, which the bench gates on.  Unlabeled
    replies count under ``""``."""
    counts: dict[str, int] = {}
    for payload in replies.values():
        if "error" in payload:
            continue
        tenant = payload.get("tenant", "")
        tenant = tenant if isinstance(tenant, str) else ""
        counts[tenant] = counts.get(tenant, 0) + 1
    return counts


def build_token_reply(tokens, eos_id: int | None, tokenizer=None) -> dict:
    """One generate-mode reply payload: ``{"tokens": [...]}`` trimmed at
    ``eos_id`` (the reply carries the finished sequence, not the eos
    padding after it), plus ``{"text": ...}`` when a tokenizer decodes.
    The one reply-construction policy — shared by the batch worker and
    the continuous worker."""
    ids = list(int(t) for t in tokens)
    if eos_id is not None and eos_id in ids:
        ids = ids[: ids.index(eos_id)]
    payload = {"tokens": ids}
    if tokenizer is not None:
        payload["text"] = tokenizer.decode(ids)
    return payload


def request_id(message: dict) -> str:
    """The correlation id a reply carries: the request's MessageId (falls
    back to the receipt handle for queues that don't assign ids)."""
    return message.get("MessageId", message["ReceiptHandle"])


def sent_epoch(message: dict) -> "float | None":
    """The message's queue-stamped arrival in epoch seconds
    (``SentTimestamp`` is epoch milliseconds, like SQS stamps it); None
    when the queue does not stamp.  THE one parse of the attribute —
    request-TTL aging, tenant TTFT deadlines, and lifecycle arrival
    stamps all share it, so they can never disagree on when a request
    arrived."""
    sent = message.get("Attributes", {}).get("SentTimestamp")
    if sent is None:
        return None
    try:
        return float(sent) / 1000.0
    except (TypeError, ValueError):
        return None


def collect_replies(
    queue, queue_url: str, *, max_messages: int = 16
) -> tuple[dict[str, dict], int]:
    """Drain every currently-visible reply from ``queue_url``, deleting
    each as it is read and de-duplicating by ``request_id``.

    Returns ``(replies, duplicates)``: one parsed payload per request id
    (first reply wins) plus the count of duplicate replies dropped.  THE
    one reply-collection policy — the serving system is at-least-once
    end to end (workers reply *before* deleting their input), so any
    consumer that counts replies without this discipline double-counts:

    - a reply left undeleted reappears after the queue's visibility
      timeout and is collected again on a later pass (delete-as-read
      closes this);
    - a request redelivered to — or re-dispatched onto — a second worker
      can legitimately produce a second reply (the request-id dedup
      closes this).

    Used by the serve and fleet benches and by the fleet demo; a reply
    body that is not valid JSON is dropped (counted as a duplicate of
    nothing — it has no request id to correlate)."""
    replies: dict[str, dict] = {}
    duplicates = 0
    while True:
        batch = queue.receive_messages(queue_url, max_messages=max_messages)
        if not batch:
            return replies, duplicates
        for message in batch:
            queue.delete_message(queue_url, message["ReceiptHandle"])
            try:
                payload = json.loads(message["Body"])
                rid = payload["request_id"]
            except Exception:
                log.error("Dropping malformed reply body: %.64r",
                          message["Body"])
                continue
            if rid in replies:
                duplicates += 1
                continue
            replies[rid] = payload


class MessageQueue(Protocol):
    """What a worker needs from a queue (satisfied by
    :class:`~..metrics.fake.FakeMessageQueue` and
    :class:`~..metrics.sqs_aws.AwsSqsService`)."""

    def receive_messages(
        self, queue_url: str, max_messages: int = 1, wait_time_s: int = 0
    ) -> list[dict]:
        ...

    def delete_message(self, queue_url: str, receipt_handle: str) -> None:
        ...


@dataclass
class ServiceConfig:
    queue_url: str
    batch_size: int = 8  # messages pulled (and padded) per model call
    seq_len: int = 64  # fixed length every body is padded/truncated to
    pad_token: int = 0
    idle_sleep_s: float = 0.05  # backoff when the queue is empty
    # SQS long polling: the receive call itself blocks up to this long when
    # the queue is empty, so idle workers cost ~0.05 req/s instead of one
    # (billed) empty ReceiveMessage per idle_sleep_s. Fakes ignore it.
    receive_wait_s: int = 20
    error_backoff_s: float = 1.0  # pause after a failed cycle
    # > 0: decode this many continuation tokens per message (KV-cache
    # generate mode) instead of a single classify forward
    generate_tokens: int = 0
    # generate-mode sampling: 0 = greedy (default); > 0 = temperature
    # sampling, seeded per batch from sample_seed + a batch counter so
    # runs are reproducible but batches are not identical.  top_k > 0 /
    # top_p < 1 truncate the sampled distribution (decode._pick — ignored
    # under greedy).
    temperature: float = 0.0
    sample_seed: int = 0
    top_k: int = 0
    top_p: float = 1.0
    # generation stops at this id (rows pad with it afterwards); None =
    # always generate the full generate_tokens.  The serve binary
    # auto-fills it from --tokenizer's eos_token_id when present.
    eos_id: int | None = None
    # generate mode decodes through the int8 KV cache (half the cache
    # bytes per token — decode.quantized_decode_step); weights-int8 is a
    # separate, composable choice (the quantize module)
    quantized_kv: bool = False
    # continuous serving only: tokens the engine advances per device
    # call (decode.block_decode).  1 = the single-step engine; > 1
    # amortizes the per-token dispatch + host sync over a block and
    # double-buffers blocks against host bookkeeping — greedy results
    # are identical (eos-masked on device, post-eos tokens discarded),
    # only scheduling granularity changes; sampled runs stay
    # distribution-exact but consume RNG keys in a different order.
    decode_block: int = 1
    # continuous serving only: > 0 sheds requests that are already older
    # than this many seconds on ARRIVAL (per the queue's SentTimestamp
    # attribute) with an explicit {"error": "expired"} reply instead of
    # occupying a decode slot — a deadline no consumer is still waiting
    # past should not cost GPU/TPU time.  Shed requests stay
    # exactly-once (the reply registry records them); they are never
    # silently dropped.  0 = off.
    request_ttl_s: float = 0.0
    # continuous serving only: > 1 stacks this many engine shards of
    # batch_size slots each behind ONE admission plane, gang-stepped in
    # a single jitted decode call per cycle (workloads/shard_plane.py);
    # scale-up/down flips device-side shard-active masks instead of
    # spawning workers.  Greedy outputs are byte-identical to `shards`
    # independent single engines; plain decode path only.
    shards: int = 1
    # request/reply: when set, the worker publishes one JSON result per
    # input message to this queue (after compute, before deleting the
    # input — at-least-once semantics, so consumers must tolerate
    # duplicates).  Classify mode sends {"next_token": int}; generate
    # mode {"tokens": [...]} (+ {"text": ...} when a tokenizer decodes).
    result_queue_url: str = ""
    # set to a directory to capture a JAX device trace of the first
    # profile_cycles serve cycles (utils/profiling.maybe_trace), flushed
    # as soon as the window closes — never the whole (unbounded) loop.
    # Empty = no tracing, no overhead.
    profile_dir: str = ""
    profile_cycles: int = 20

    def __post_init__(self) -> None:
        # fail at construction, not at first-batch trace time: run_forever's
        # never-dies loop would otherwise catch the tracing ValueError and
        # retry a doomed batch forever (same policy as decode._pick)
        if self.top_k < 0:
            raise ValueError(f"top_k={self.top_k} must be >= 0 (0 = off)")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p={self.top_p} must be in (0, 1] (1.0 = off)"
            )
        if self.decode_block < 1:
            raise ValueError(
                f"decode_block={self.decode_block} must be >= 1"
            )
        if self.shards < 1:
            raise ValueError(f"shards={self.shards} must be >= 1")
        if self.request_ttl_s < 0:
            raise ValueError(
                f"request_ttl_s={self.request_ttl_s} must be >= 0 "
                "(0 = off)"
            )


class QueueWorker:
    """One worker: receive → batch → forward → (reply) → delete, until
    stopped.

    ``tokenizer`` (optional, anything with HF-shaped ``encode(text) ->
    ids`` and ``decode(ids) -> text``) turns the worker into text-in /
    text-out: message bodies that are not integer-array JSON are treated
    as text (or ``{"text": ...}`` JSON) and encoded; generate-mode
    results carry the decoded continuation.  ``result_queue`` (defaults
    to the input queue object, addressed by
    ``ServiceConfig.result_queue_url``) receives one JSON reply per
    message when the url is set.
    """

    def __init__(
        self,
        queue: MessageQueue,
        params: Any,
        model_config: ModelConfig,
        service_config: ServiceConfig,
        forward_fn=None,
        generate_fn=None,
        tokenizer=None,
        result_queue: MessageQueue | None = None,
    ) -> None:
        self.queue = queue
        self.params = params
        self.model_config = model_config
        self.config = service_config
        self.tokenizer = tokenizer
        if service_config.result_queue_url and result_queue is None:
            # explicit on purpose: in-memory clients (FakeMessageQueue,
            # the native LocalQueue) ignore queue urls, so silently
            # defaulting replies onto the input queue object would
            # self-feed — pass result_queue=queue for url-addressed
            # clients (AWS SQS), or a second queue object otherwise
            raise ValueError(
                "result_queue_url is set but no result_queue client was "
                "given"
            )
        self.result_queue = result_queue
        # default forward picks the attention kernel by the BATCH's bucket
        # length (the Pallas flash kernel when it tiles onto the MXU blocks
        # and is past the measured crossover, dense otherwise) — one
        # compiled program per bucket
        self._forward = forward_fn or (
            lambda params, tokens: forward_jit_with(
                params, tokens, model_config,
                attention_fn_for(tokens.shape[1]),
            )
        )
        if service_config.generate_tokens > 0:
            budget = service_config.seq_len + service_config.generate_tokens
            if budget > model_config.max_seq_len:
                raise ValueError(
                    f"seq_len + generate_tokens = {budget} exceeds the "
                    f"model's max_seq_len={model_config.max_seq_len}"
                )
        # generate seam: (params, tokens, num_tokens, lengths) — the
        # per-row lengths let ragged right-padded prompts decode from
        # their own last real token (see decode.generate).  The default
        # honors ServiceConfig.temperature: greedy at 0 (one compiled
        # program), else temperature sampling with :func:`sampling_keys`
        # (the shared seed-per-batch policy).
        # observability counter only (batches through the generate path);
        # sampling reproducibility is driven by _sample_keys, not this
        self._generate_batches = 0
        self._sample_keys = sampling_keys(service_config.sample_seed)

        def _default_generate(params, tokens, n, lengths):
            rng = None
            if service_config.temperature > 0.0:
                rng = next(self._sample_keys)
            self._generate_batches += 1
            return generate_jit(
                params, tokens, n, model_config,
                temperature=service_config.temperature, rng=rng,
                attention_fn=attention_fn_for(tokens.shape[1]),
                lengths=lengths, top_k=service_config.top_k,
                top_p=service_config.top_p,
                eos_id=service_config.eos_id,
                quantized_cache=service_config.quantized_kv,
            )

        self._generate = generate_fn or _default_generate
        self._stop = threading.Event()
        self.processed = 0
        # wall-clock cycle spans (summary() gives count/mean/p50/p99/max)
        self.timer = SpanTimer()

    def stop(self) -> None:
        self._stop.set()

    MIN_BUCKET = 16  # smallest padded length (keeps the compile-cache tiny)

    def _bucket_len(self, longest: int) -> int:
        """Smallest power-of-two >= ``longest``, in
        ``[MIN_BUCKET, seq_len]`` — the batch's padded length."""
        bucket = self.MIN_BUCKET
        while bucket < min(longest, self.config.seq_len):
            bucket *= 2
        return min(bucket, self.config.seq_len)

    def _parse_body(self, body: str) -> np.ndarray | None:
        return parse_request_body(body, self.tokenizer)

    def _batch_tokens(
        self, bodies: list[str]
    ) -> tuple[jnp.ndarray, jnp.ndarray, list[bool]]:
        """(tokens ``[batch, bucket]``, lengths ``[batch]``, per-body
        validity) for one batch; dropped bodies occupy a one-pad-token
        row so the batch shape holds, flagged invalid."""
        raw = [self._parse_body(body) for body in bodies]
        valid = [ids is not None for ids in raw]
        parsed: list[np.ndarray] = [
            (ids if ids is not None else np.zeros((0,), np.int32))
            [: self.config.seq_len]
            for ids in raw
        ]
        bucket = self._bucket_len(max((p.size for p in parsed), default=1))
        rows = np.full(
            (self.config.batch_size, bucket), self.config.pad_token, np.int32
        )
        # empty/dropped bodies read out position 0 (one pad token) rather
        # than indexing at -1
        lengths = np.ones((self.config.batch_size,), np.int32)
        for i, ids in enumerate(parsed):
            rows[i, : ids.size] = ids
            lengths[i] = max(1, ids.size)
        return jnp.asarray(rows), jnp.asarray(lengths), valid

    def run_once(self) -> int:
        """One receive/process/delete cycle. Returns messages processed."""
        messages = self.queue.receive_messages(
            self.config.queue_url,
            max_messages=self.config.batch_size,
            wait_time_s=self.config.receive_wait_s,
        )
        if not messages:
            return 0
        tokens, lengths, valid = self._batch_tokens(
            [m["Body"] for m in messages]
        )
        # block so deletion happens strictly after compute succeeds
        # (at-least-once processing: a crash here leaves messages in-flight
        # to reappear after the visibility timeout)
        if self.config.generate_tokens > 0:
            produced = self._generate(
                self.params, tokens, self.config.generate_tokens, lengths
            )
            produced.block_until_ready()
            results = None
            if self.config.result_queue_url:
                results = [
                    build_token_reply(row, self.config.eos_id,
                                      self.tokenizer)
                    for row in np.asarray(produced)[: len(messages)]
                ]
        else:
            # greedy next token per sequence, read at each row's last
            # VALID position — never the pad slot at -1
            logits = self._forward(self.params, tokens)
            picks = jnp.argmax(
                logits[jnp.arange(logits.shape[0]), lengths - 1], axis=-1
            )
            picks.block_until_ready()
            results = None
            if self.config.result_queue_url:
                results = [
                    {"next_token": int(t)}
                    for t in np.asarray(picks)[: len(messages)]
                ]
        if results is not None:
            # reply BEFORE deleting the input: a crash between the two
            # redelivers the input, so consumers may see duplicate
            # results (at-least-once) but never lose one.  Each reply
            # carries its request's MessageId so consumers sharing the
            # result queue can correlate (and dedup redeliveries);
            # dropped bodies get an error payload, never a fabricated
            # result computed from their pad-token placeholder row.
            for i, (message, payload) in enumerate(zip(messages, results)):
                if not valid[i]:
                    payload = {"error": "malformed body"}
                payload["request_id"] = request_id(message)
                self.result_queue.send_message(
                    self.config.result_queue_url, json.dumps(payload)
                )
        for message in messages:
            self.queue.delete_message(
                self.config.queue_url, message["ReceiptHandle"]
            )
        self.processed += len(messages)
        return len(messages)

    def run_forever(self) -> None:
        # same never-dies guarantee as the control loop (main.go:43-47):
        # a transient queue/compute error logs, backs off, and retries —
        # unprocessed messages stay in-flight and reappear after the
        # visibility timeout. Pauses use the stop event so stop() wakes a
        # backing-off worker immediately.
        if self.config.profile_dir:
            # bounded window: trace only the first profile_cycles cycles
            # so the trace flushes promptly and never grows with uptime.
            # Profiler failures (unwritable dir, one-session-per-process
            # when several pool workers all request tracing) must not
            # break the never-dies guarantee — log and serve unprofiled.
            try:
                with maybe_trace(self.config.profile_dir):
                    self._serve(max_cycles=self.config.profile_cycles)
            except Exception as err:
                log.error("Profiling failed (continuing unprofiled): %s", err)
        self._serve()

    def _serve(self, max_cycles: int | None = None) -> None:
        """The serve loop body; ``max_cycles`` bounds it (None = forever)."""
        cycles = 0
        while not self._stop.is_set():
            if max_cycles is not None and cycles >= max_cycles:
                return
            cycles += 1
            try:
                with self.timer.span("cycle"):
                    idle = self.run_once() == 0
            except Exception as err:
                log.error("Worker cycle failed: %s", err)
                self._stop.wait(self.config.error_backoff_s)
                continue
            if idle:
                self._stop.wait(self.config.idle_sleep_s)


class ElasticWorkerPool:
    """Keeps the worker-thread count equal to a Deployment's replica count.

    In production each replica is a pod running one :class:`QueueWorker`;
    in-process this pool plays kubelet: poll the (fake or real) Deployment
    API and start/stop worker threads to match ``spec.replicas`` — which is
    exactly the surface the autoscaler actuates, closing the loop.
    """

    def __init__(self, deployment_api, deployment: str, worker_factory) -> None:
        self.api = deployment_api
        self.deployment = deployment
        self.worker_factory = worker_factory
        # live (worker, thread) pairs; scaled-down pairs move to _retiring
        # until their thread exits, so their processed counts are never lost
        self._members: list[tuple[QueueWorker, threading.Thread]] = []
        self._retiring: list[tuple[QueueWorker, threading.Thread]] = []
        self._retired_processed = 0

    @property
    def workers(self) -> list[QueueWorker]:
        """The live workers (kubelet view: running pods of the Deployment)."""
        return [worker for worker, _ in self._members]

    def _prune(self) -> None:
        # fold finished retirees' final counts into the retired total
        still_retiring = []
        for worker, thread in self._retiring:
            if thread.is_alive():
                still_retiring.append((worker, thread))
            else:
                self._retired_processed += worker.processed
        self._retiring = still_retiring
        # a dead thread is not a live worker: drop it (keeping its count) so
        # reconcile replaces it instead of counting a corpse toward replicas
        live = []
        for worker, thread in self._members:
            if thread.is_alive():
                live.append((worker, thread))
            else:
                log.error("Worker thread died; replacing on this reconcile")
                self._retired_processed += worker.processed
        self._members = live

    def reconcile(self) -> int:
        """Match live worker count to the Deployment's replicas; returns count."""
        self._prune()
        want = self.api.get(self.deployment).replicas
        while len(self._members) < want:
            worker = self.worker_factory()
            thread = threading.Thread(target=worker.run_forever, daemon=True)
            thread.start()
            self._members.append((worker, thread))
        while len(self._members) > want:
            worker, thread = self._members.pop()
            worker.stop()
            self._retiring.append((worker, thread))
        return len(self._members)

    @property
    def processed(self) -> int:
        """Total messages processed over the pool's lifetime (scaled-down and
        crashed workers included)."""
        return (
            self._retired_processed
            + sum(w.processed for w, _ in self._members)
            + sum(w.processed for w, _ in self._retiring)
        )

    def stop_all(self) -> None:
        for worker, _ in self._members + self._retiring:
            worker.stop()
        self._retiring += self._members
        self._members = []
        for _, thread in self._retiring:
            thread.join(timeout=30)
        # folds counts of exited threads only; a straggler that outlives the
        # join timeout stays in _retiring (and in `processed`) rather than
        # having a stale count frozen while it is still deleting messages
        self._prune()
        if self._retiring:
            log.error(
                "%d worker thread(s) still alive after stop_all join timeout",
                len(self._retiring),
            )
