"""Multi-host scaling: process initialization and topology-aware meshes.

The reference is a single Go process making RPCs (SURVEY.md §5: its only
"distributed" aspect is client HTTPS to two services); the workload this
framework scales is a JAX SPMD program, and scaling *that* past one host
is a first-class concern:

- :func:`initialize_from_env` — one call at worker/trainer startup.  On a
  multi-host TPU pod slice (or any fleet launched with coordinator env
  vars) it runs ``jax.distributed.initialize`` so every process sees the
  global device set; on a single host it is a no-op.  Controllers never
  call this — they import no JAX.
- :func:`make_topology_mesh` — a drop-in for :func:`.train.make_mesh`
  that asks ``mesh_utils.create_device_mesh`` to order devices by the
  physical ICI topology (so neighboring mesh coordinates are neighboring
  chips and ``ppermute`` rings ride single hops) instead of naive
  enumeration order.
- :func:`make_hybrid_mesh` — multi-slice/multi-host layout: the ``data``
  axis spans the slow DCN boundary (its collectives are the small
  gradient all-reduces), while ``seq``/``model`` stay inside a slice on
  ICI (their collectives are the big activation exchanges) — the
  standard bandwidth-matched assignment from the scaling playbook.

All three return/feed the same ``("data", "seq", "model")`` mesh
contract every step-builder in :mod:`.train`/:mod:`.moe`/:mod:`.zigzag`
already speaks, so going multi-host changes mesh construction only.
"""

from __future__ import annotations

import logging
import os

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh

log = logging.getLogger(__name__)

# env vars that make initialize_from_env run distributed init.  The TPU
# runtime does NOT export a coordinator address itself — on a pod slice
# jax.distributed.initialize() auto-detects the cluster from TPU metadata
# once *called*, so the launcher must opt in by setting one of these (or
# the code must pass require=True).  Detection is deliberately explicit:
# probing cluster metadata from a no-egress or single-host environment
# can hang, and silently staying single-process on a pod would train
# disjoint replicas.
_TRIGGER_VARS = (
    "KSAT_DISTRIBUTED",  # this framework's explicit opt-in (any value)
    "COORDINATOR_ADDRESS",
    "JAX_COORDINATOR_ADDRESS",
)


def initialize_from_env(require: bool = False) -> bool:
    """``jax.distributed.initialize`` when launched as a multi-process job.

    Returns True when distributed init ran.  Triggers: ``require=True``
    (the launcher *knows* this is a pod job — preferred), or any of
    ``KSAT_DISTRIBUTED`` / ``COORDINATOR_ADDRESS`` /
    ``JAX_COORDINATOR_ADDRESS`` set, or ``JAX_NUM_PROCESSES`` > 1.  The
    actual coordinator/process-id discovery is ``initialize()``'s own
    cluster detection (TPU metadata, Slurm, MPI, or the JAX env vars).
    Idempotent: a second call is a no-op.
    """
    already = getattr(initialize_from_env, "_done", False)
    if already:
        return True
    num = int(os.environ.get("JAX_NUM_PROCESSES", "1") or "1")
    triggered = (
        require
        or num > 1
        or any(os.environ.get(v) for v in _TRIGGER_VARS)
    )
    if not triggered:
        return False
    # jax.distributed.initialize()'s no-arg form only covers environments
    # its cluster detectors know (TPU pod metadata, Slurm, MPI).  For a
    # plainly-launched fleet, pass the standard env vars through
    # explicitly — this is what makes a 2-process CPU job (and the
    # two-process test) bootstrap the same way a pod slice does.
    kwargs = {}
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if addr:
        kwargs["coordinator_address"] = addr
    if os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = num
    if os.environ.get("JAX_PROCESS_ID") is not None:
        kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(**kwargs)
    initialize_from_env._done = True
    log.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), len(jax.devices()),
    )
    return True


def make_topology_mesh(
    model_parallel: int = 1, seq_parallel: int = 1, devices: list | None = None
) -> Mesh:
    """A ``("data", "seq", "model")`` mesh ordered by physical topology.

    Same contract as :func:`.train.make_mesh`, but device placement comes
    from ``mesh_utils.create_device_mesh``, which on TPU assigns mesh
    coordinates along the ICI torus — neighbor exchanges (ring attention,
    pipeline hops) become single physical hops instead of whatever the
    enumeration order happens to give.
    """
    from .train import mesh_shape

    devices = devices if devices is not None else jax.devices()
    shape = mesh_shape(len(devices), model_parallel, seq_parallel)
    grid = mesh_utils.create_device_mesh(shape, devices)
    return Mesh(grid, ("data", "seq", "model"))


def make_topology_pipeline_mesh(
    pipe_parallel: int,
    model_parallel: int = 1,
    seq_parallel: int = 1,
    devices: list | None = None,
) -> Mesh:
    """A ``("pipe", "data"[, "model"|"seq"])`` mesh ordered by physical
    topology — the pipeline counterpart of :func:`make_topology_mesh`.
    The pipe axis is the one that most wants torus placement: every
    schedule slot ends in a single-neighbor ``ppermute`` hop, so stage
    ``i`` and stage ``i+1`` should be physically adjacent chips (and
    under pp x sp, so should the ring neighbors).  Same contract as
    :func:`.pipeline.make_pipeline_mesh`.
    """
    from .pipeline import make_pipeline_mesh

    devices = devices if devices is not None else jax.devices()
    # one source of truth for the pipeline mesh contract (divisibility,
    # shape, axis names): build the enumeration-order mesh, then re-grid
    # the same shape with topology-ordered placement
    plain = make_pipeline_mesh(devices, pipe_parallel=pipe_parallel,
                               model_parallel=model_parallel,
                               seq_parallel=seq_parallel)
    grid = mesh_utils.create_device_mesh(plain.devices.shape, devices)
    return Mesh(grid, plain.axis_names)


def make_hybrid_mesh(
    dcn_data_parallel: int,
    model_parallel: int = 1,
    seq_parallel: int = 1,
) -> Mesh:
    """Multi-slice mesh: ``data`` crosses DCN, ``seq``/``model`` stay on ICI.

    ``dcn_data_parallel`` is the slice count and is deliberately
    **required** — the launcher knows it, and guessing wrong would lay
    ICI-assumed axes across the DCN boundary with no error.  Requires a
    multi-process runtime (call :func:`initialize_from_env` first);
    ``dcn_data_parallel=1`` (one slice) degenerates to
    :func:`make_topology_mesh`.
    """
    dcn = dcn_data_parallel
    devices = jax.devices()
    n = len(devices)
    if n % (dcn * model_parallel * seq_parallel):
        raise ValueError(
            f"{n} devices not divisible by dcn={dcn} x "
            f"model={model_parallel} x seq={seq_parallel}"
        )
    if dcn == 1:
        return make_topology_mesh(model_parallel, seq_parallel, devices)
    per_slice_data = n // (dcn * model_parallel * seq_parallel)
    grid = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(per_slice_data, seq_parallel, model_parallel),
        dcn_mesh_shape=(dcn, 1, 1),
        devices=devices,
    )
    # hybrid grid axis 0 is dcn*per_slice_data: exactly the "data" axis —
    # DCN carries only the data-parallel gradient all-reduce
    return Mesh(grid, ("data", "seq", "model"))
