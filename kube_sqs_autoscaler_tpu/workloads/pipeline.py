"""Pipeline parallelism: GPipe and 1F1B microbatched stages over a mesh axis.

The reference (``/root/reference``) has no parallelism of any kind
(SURVEY.md §2 — a single-goroutine Go control loop); this module completes
the package's parallelism set (dp/tp/sp/ep in :mod:`.train`/:mod:`.ring`/
:mod:`.moe`) with **pp**, TPU-native:

- The transformer's layer stack is *stacked* into one pytree with a leading
  ``[n_layers, ...]`` axis and sharded over a ``"pipe"`` mesh axis, so each
  device holds ``n_layers / pipe`` contiguous layers (one stage).
- Inside ``shard_map``, microbatches flow through the stages on a GPipe
  schedule: ``n_micro + pipe - 1`` lockstep steps, each ending with a
  single-hop ``jax.lax.ppermute`` that hands every stage's activation to
  its successor — neighbor traffic that rides the ICI torus, never DCN.
- Per-stage compute is a ``lax.scan`` over the stage's stacked layers
  (trace one layer, compile once, no Python unrolling), running the same
  :func:`.model._block` as every other execution path.
- The remaining mesh axes are ``"data"`` (microbatches shard their batch
  dim) and, on a pp x dp x tp mesh, ``"model"``: stage weights carry
  Megatron column/row-parallel shards and the body places the two
  ``psum("model")`` all-reduces itself (via :func:`.model._block`'s
  ``reduce`` seam).  The ``shard_map`` is **fully manual over every mesh
  axis** — partial-manual mode (``axis_names`` a strict subset) miscompiles
  bf16 programs in this jax/XLA version (XLA CPU check-failure ``Invalid
  binary instruction opcode copy``; reproduced minimally), so nothing here
  relies on it.

The bubble fraction is the usual ``(pipe-1) / (n_micro + pipe - 1)`` —
raise ``n_microbatches`` to amortize it.  The ``"1f1b"`` schedule keeps
only ``min(M, P)`` stage inputs live instead of GPipe's all-M.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .model import (
    PARAM_AXES,
    ModelConfig,
    _block,
    _layer_norm,
    init_params,
)


@dataclass(frozen=True)
class PipelineConfig:
    """Schedule knobs: how many microbatches, and which schedule —
    ``"gpipe"`` (all-forward-then-all-backward, bubble
    ``(P-1)/(M+P-1)``, activations for all M microbatches live) or
    ``"1f1b"`` (interleaved one-forward-one-backward, same bubble but
    only ``min(M, P)`` stage inputs live)."""

    n_microbatches: int = 4
    schedule: str = "gpipe"

    def __post_init__(self) -> None:
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {self.schedule!r}"
            )


def make_pipeline_mesh(
    devices: list | None = None,
    pipe_parallel: int | None = None,
    model_parallel: int = 1,
    seq_parallel: int = 1,
) -> Mesh:
    """A ``("pipe", "data")`` mesh — or ``("pipe", "data", "model")``
    (pp x dp x tp) / ``("pipe", "data", "seq")`` (pp x dp x sp, ring
    attention inside the stages) when the respective degree is > 1, or
    the full 4-axis ``("pipe", "data", "seq", "model")`` (pp x dp x sp
    x tp — the flagship large-model pod layout: stages over ``pipe``,
    Megatron head/ff shards over ``model`` innermost so its two
    per-block all-reduces ride the shortest ICI hops, ring attention
    over ``seq`` above it) when both are; ``pipe_parallel`` defaults to
    all devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    pipe = pipe_parallel if pipe_parallel is not None else n
    if n % (pipe * model_parallel * seq_parallel):
        raise ValueError(
            f"{n} devices not divisible by pipe_parallel={pipe} x "
            f"model_parallel={model_parallel} x seq_parallel={seq_parallel}"
        )
    data = n // (pipe * model_parallel * seq_parallel)
    if model_parallel > 1 and seq_parallel > 1:
        grid = np.asarray(devices).reshape(
            pipe, data, seq_parallel, model_parallel
        )
        return Mesh(grid, ("pipe", "data", "seq", "model"))
    if model_parallel > 1:
        grid = np.asarray(devices).reshape(pipe, data, model_parallel)
        return Mesh(grid, ("pipe", "data", "model"))
    if seq_parallel > 1:
        grid = np.asarray(devices).reshape(pipe, data, seq_parallel)
        return Mesh(grid, ("pipe", "data", "seq"))
    grid = np.asarray(devices).reshape(pipe, data)
    return Mesh(grid, ("pipe", "data"))


def stack_layers(params: dict) -> dict:
    """``layers`` list-of-dicts -> one stacked pytree with leading ``[L]``.

    The stacked form is what shards over ``"pipe"`` and what ``lax.scan``
    consumes; stacking order == layer order, and contiguous leading-axis
    sharding assigns layers ``[i*L/P, (i+1)*L/P)`` to stage ``i`` — the
    natural pipeline placement.

    The fused ``wqkv`` is split into ``wq``/``wk``/``wv``: under the
    fully-manual pp x tp ``shard_map``, each projection's output axis
    shards into contiguous head groups (Megatron column-parallel), which a
    fused ``[D, 3D]`` axis cannot do — a contiguous ``3D/tp`` chunk crosses
    the q/k/v boundary.  :func:`.model._project_qkv` accepts both layouts.
    """
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *params["layers"])
    wq, wk, wv = jnp.split(stacked.pop("wqkv"), 3, axis=-1)
    stacked["wq"], stacked["wk"], stacked["wv"] = wq, wk, wv
    return stacked


def unstack_layers(params: dict) -> dict:
    """Inverse of the pipeline layout: stage stack -> flat ``layers`` list
    with the fused ``wqkv`` — the layout :func:`.model.forward`, the
    serving worker, and the decode paths consume.  Used by
    :meth:`.checkpoint.TrainCheckpointer.restore_params` so pipeline-trained
    checkpoints serve like any other."""
    stages = dict(params["stages"])
    wq, wk, wv = stages.pop("wq"), stages.pop("wk"), stages.pop("wv")
    stages["wqkv"] = jnp.concatenate([wq, wk, wv], axis=-1)
    n_layers = next(iter(stages.values())).shape[0]
    flat = {k: v for k, v in params.items() if k != "stages"}
    flat["layers"] = [
        {k: v[i] for k, v in stages.items()} for i in range(n_layers)
    ]
    return flat


def init_pipeline_params(
    rng: jax.Array, config: ModelConfig, n_stages: int
) -> dict:
    """:func:`.model.init_params` with the layer stack pre-stacked."""
    if config.n_layers % n_stages:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by n_stages={n_stages}"
        )
    return as_pipeline_params(init_params(rng, config))


def stack_llama_layers(params: dict) -> dict:
    """The llama-family counterpart of :func:`stack_layers`: one stacked
    pytree with leading ``[L]``, fused projections split so every weight's
    output axis shards into contiguous blocks under the fully-manual
    pp x tp ``shard_map`` — ``wkv`` into ``wk``/``wv`` (contiguous kv
    heads; a fused ``2*kv_dim`` chunk crosses the k/v boundary) and
    ``w_gate_up`` into ``w_gate``/``w_up`` (contiguous ff columns).
    :func:`.llama._project_qkv` / :func:`.llama._swiglu` accept both
    layouts.  MoE layers (no dense ``w_gate_up``; router + expert
    stacks instead) pass through with just the kv split."""
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *params["layers"])
    wk, wv = jnp.split(stacked.pop("wkv"), 2, axis=-1)
    stacked["wk"], stacked["wv"] = wk, wv
    if "w_gate_up" in stacked:
        w_gate, w_up = jnp.split(stacked.pop("w_gate_up"), 2, axis=-1)
        stacked["w_gate"], stacked["w_up"] = w_gate, w_up
    if "w_gate_up_experts" in stacked:
        # fused SwiGLU expert projection splits for the same reason: each
        # expert's ff columns shard contiguously under pp x tp, and a
        # fused [2F] chunk crosses the gate/up boundary
        w_gate_e, w_up_e = jnp.split(
            stacked.pop("w_gate_up_experts"), 2, axis=-1
        )
        stacked["w_gate_experts"], stacked["w_up_experts"] = w_gate_e, w_up_e
    return stacked


def unstack_llama_layers(params: dict) -> dict:
    """Inverse of the llama pipeline layout: stage stack -> flat
    ``layers`` list with the fused ``wkv``/``w_gate_up`` — the layout
    :func:`.llama.llama_forward` and the decode paths consume (the
    llama counterpart of :func:`unstack_layers`, used by the
    checkpoint train→serve handoff)."""
    stages = dict(params["stages"])
    wk, wv = stages.pop("wk"), stages.pop("wv")
    stages["wkv"] = jnp.concatenate([wk, wv], axis=-1)
    if "w_gate" in stages:
        w_gate, w_up = stages.pop("w_gate"), stages.pop("w_up")
        stages["w_gate_up"] = jnp.concatenate([w_gate, w_up], axis=-1)
    if "w_gate_experts" in stages:
        w_gate_e = stages.pop("w_gate_experts")
        w_up_e = stages.pop("w_up_experts")
        stages["w_gate_up_experts"] = jnp.concatenate(
            [w_gate_e, w_up_e], axis=-1
        )
    n_layers = next(iter(stages.values())).shape[0]
    flat = {k: v for k, v in params.items() if k != "stages"}
    flat["layers"] = [
        {k: v[i] for k, v in stages.items()} for i in range(n_layers)
    ]
    return flat


def as_pipeline_params(params: dict) -> dict:
    """Flat gpt-family params -> the stage-stacked pipeline layout (the
    non-layer leaves pass through; the gpt counterpart of
    :func:`as_llama_pipeline_params`)."""
    out = {k: v for k, v in params.items() if k != "layers"}
    out["stages"] = stack_layers(params)
    return out


def as_llama_pipeline_params(params: dict) -> dict:
    """Flat llama params -> the stage-stacked pipeline layout (the
    non-layer leaves — embed, final_norm, an untied lm_head — pass
    through).  Inverse: :func:`unstack_llama_layers`."""
    out = {k: v for k, v in params.items() if k != "layers"}
    out["stages"] = stack_llama_layers(params)
    return out


def init_llama_pipeline_params(rng: jax.Array, config, n_stages: int) -> dict:
    """:func:`.llama.init_llama_params` with the stack pre-stacked."""
    from .llama import init_llama_params

    if config.n_layers % n_stages:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by n_stages={n_stages}"
        )
    return as_llama_pipeline_params(init_llama_params(rng, config))


def _act_spec(mesh: Mesh) -> P:
    """PartitionSpec of the microbatched activations/tokens entering the
    pipelined body: ``[M, B_m, ...]`` with batch over ``data`` and (on a
    pp x dp x sp mesh) the sequence axis over ``seq``."""
    if "seq" in mesh.shape:
        return P(None, "data", "seq")
    return P(None, "data")


def _stage_ring_attention(mesh: Mesh, window: int | None = None):
    """The per-stage attention for a pp x dp x sp mesh: the ring-attention
    per-device body running INSIDE the pipeline's fully-manual region —
    k/v rotate over ``seq`` within each stage's compute while activations
    flow over ``pipe`` between stages.  Same body dispatch as
    :func:`.ring.make_ring_attention`: the Pallas flash-lse kernel per
    hop on TPU when the local length tiles (and no window — the kernel
    has no banded-block form), the einsum reference body elsewhere.
    GQA-native (compact k/v rotate as-is); ``window`` adds the Mistral
    band."""
    from .ring import _ring_attention_kernel_local, _ring_attention_local

    sp = mesh.shape["seq"]

    def attend(q, k, v):
        from .flash import tiles_cleanly

        # q.shape[2] is already the LOCAL length here (manual region)
        if (window is None and jax.default_backend() == "tpu"
                and tiles_cleanly(q.shape[2])):
            return _ring_attention_kernel_local(
                q, k, v, axis_name="seq", axis_size=sp
            )
        return _ring_attention_local(
            q, k, v, axis_name="seq", axis_size=sp, window=window
        )

    attend.gqa_native = True
    return attend


def _stage_zigzag_attention(mesh: Mesh):
    """The per-stage attention for a pp x dp x sp mesh whose sequence
    axis carries the ZIG-ZAG layout (:func:`.zigzag.zigzag_permutation`):
    the load-balanced ring body running inside the pipeline's
    fully-manual region — every device owns one early and one late
    chunk, so each hop computes the same half-block work (the imbalance
    plain ring attention pays under a causal mask).  Same body dispatch
    as :func:`.zigzag.make_zigzag_ring_attention`: the Pallas flash-lse
    hop kernel on TPU when both hop shapes tile, the einsum reference
    body elsewhere.  GQA-native."""
    from .zigzag import (
        _zigzag_attention_kernel_local,
        _zigzag_attention_local,
    )

    sp = mesh.shape["seq"]

    def attend(q, k, v):
        from .flash import tiles_cleanly

        s_local = q.shape[2]  # already the LOCAL length (manual region)
        if (
            jax.default_backend() == "tpu"
            and s_local % 2 == 0
            and tiles_cleanly(s_local)
            and tiles_cleanly(s_local // 2)
        ):
            return _zigzag_attention_kernel_local(
                q, k, v, axis_name="seq", axis_size=sp
            )
        return _zigzag_attention_local(
            q, k, v, axis_name="seq", axis_size=sp
        )

    attend.gqa_native = True
    attend._zigzag = True
    return attend


def _stage_spec(name: str, with_model: bool) -> P:
    """PartitionSpec of one stage-stack leaf: leading layer axis over
    ``"pipe"``; on a pp x tp mesh, the PARAM_AXES Megatron axes over
    ``"model"`` (column-parallel wq/wk/wv/w_up, row-parallel wo/w_down).

    MoE leaves under tp: the router replicates (routing decisions must
    be identical on every model shard) and each expert's FF axis carves
    over ``"model"`` — column-parallel ``w_up/w_gate`` columns,
    row-parallel ``w_down`` rows — so the routed expert compute is
    genuinely tensor-parallel and the block's ``reduce`` seam closes the
    partial sums exactly like the dense MLP's.  The EXPERT axis stays
    unsharded (the flat path's expert-over-``data`` placement does not
    apply inside the fully-manual stage body: routing there addresses
    the full expert set per data shard)."""
    from .train import _LOGICAL_TO_MESH

    if name == "router":
        return P("pipe")
    if "experts" in name:
        if not with_model:
            return P("pipe")
        axes = PARAM_AXES[name]
        return P("pipe", *(
            None if a == "expert" else _LOGICAL_TO_MESH[a] for a in axes
        ))
    axes = PARAM_AXES.get(name) if with_model else None
    if axes is None:
        return P("pipe")
    return P("pipe", *(_LOGICAL_TO_MESH[a] for a in axes))


def stage_partition_specs(stages: dict, mesh: Mesh) -> dict:
    """Per-leaf ``PartitionSpec`` pytree for the stage stack — the
    ``shard_map`` in/out specs of the pipelined bodies."""
    with_model = mesh.shape.get("model", 1) > 1
    return {k: _stage_spec(k, with_model) for k in stages}


def _moe_layer_scan(block_call, x, stage_layers, expert_mlp, moe):
    """The MoE variant of the per-stage layer scan: the aux loss rides
    the scan carry (a Python-list collection like the flat objectives
    use would leak tracers out of ``lax.scan``).  ``block_call(h, layer,
    mlp)`` runs one block with the given mlp seam; returns
    ``(out, aux_sum)`` — the SUM of this stage's per-layer aux terms.
    """
    def one_layer(carry, layer):
        h, aux_sum = carry
        box = []

        def sparse_mlp(v, lyr):
            out, aux = expert_mlp(v, lyr, moe)
            box.append(aux)
            return out

        h = block_call(h, layer, sparse_mlp)
        return (h, aux_sum + box[0]), None

    (out, aux_sum), _ = jax.lax.scan(
        one_layer, (x, jnp.zeros((), jnp.float32)), stage_layers
    )
    return out, aux_sum


def _stage_apply(
    stage_layers: dict, x: jax.Array, config: ModelConfig,
    remat: bool = False, tp_size: int = 1, attention_fn=None,
    moe=None, expert_mlp=None,
) -> jax.Array:
    """Run one stage's stacked layers over an activation microbatch.

    ``remat=True`` checkpoints each layer like :func:`.model.forward`
    does: the backward pass recomputes block activations instead of
    keeping every microbatch's every layer resident — on a pipeline
    stage that is the difference between O(M·L/P) and O(M + L/P) live
    activations.

    ``tp_size > 1``: the layer weights are local Megatron shards
    (contiguous ``n_heads/tp`` heads per projection, ``d_ff/tp`` MLP
    columns); the block runs on the local head group with Megatron's
    *f*/*g* conjugate operators hand-placed (:func:`_tp_promote` /
    :func:`_tp_reduce`) — explicit because the body is fully manual and
    ``check_vma=False`` AD would otherwise drop the backward all-reduce
    of ``replicated @ sharded`` matmuls.  Activations stay full
    ``d_model`` (replicated over ``"model"``) — the classic Megatron
    dataflow.
    """
    if tp_size > 1:
        cfg = dataclasses.replace(
            config,
            d_model=config.d_model // tp_size,
            n_heads=config.n_heads // tp_size,
        )
        reduce, promote = _tp_reduce, _tp_promote
    else:
        cfg, reduce, promote = config, None, None
    block = (
        jax.checkpoint(_block, static_argnums=(2, 3, 4, 5, 6))
        if remat else _block
    )
    # attention by the measured dispatcher unless the caller injects one
    # (the seam CPU tests use to run the Pallas kernel in interpret mode
    # inside the pipelined bodies): the flash kernel on TPU past its
    # crossover (the pallas_call runs fine inside the fully-manual body —
    # same situation as the ring kernel hops), the dense XLA path
    # elsewhere
    if attention_fn is None:
        from .flash import attention_fn_for

        attention_fn = attention_fn_for(x.shape[1])
    attend = attention_fn

    if moe is not None:
        # routed expert MLP in the block's mlp seam; aux rides the carry.
        # Under tp the expert ff shards over "model" (stage_partition_
        # specs), so the router's dispatch/combine cotangents need the
        # Megatron f-operator sync (see moe._routed_ffn's grad_sync).
        emlp = expert_mlp
        if tp_size > 1:
            emlp = partial(expert_mlp, grad_sync=_tp_promote)
        return _moe_layer_scan(
            lambda h, layer, mlp: block(h, layer, cfg, attend, mlp,
                                        reduce, promote),
            x, stage_layers, emlp, moe,
        )

    def one_layer(h, layer):
        return block(h, layer, cfg, attend, None, reduce, promote), None

    out, _ = jax.lax.scan(one_layer, x, stage_layers)
    return out


def _llama_stage_apply(
    stage_layers: dict, x: jax.Array, config,
    remat: bool = False, tp_size: int = 1, attention_fn=None,
    moe=None, expert_mlp=None, seq_axis: str | None = None,
    positions_table: jax.Array | None = None,
) -> jax.Array:
    """The llama-family counterpart of :func:`_stage_apply`: one stage's
    stacked llama layers (RoPE/GQA/RMSNorm/SwiGLU via
    :func:`.llama._llama_block`) over an activation microbatch.

    RoPE positions are a static function of the microbatch shape plus
    (under ``seq_axis``, the pp x sp layout) the shard's global offset
    via ``axis_index`` — identical on every PIPE stage either way, so no
    position state crosses the ``ppermute`` hops.  ``tp_size > 1`` runs
    the local Megatron shard
    (contiguous ``n_heads/tp`` query heads, ``n_kv_heads/tp`` kv heads,
    ``d_ff/tp`` ff columns) with the *f*/*g* conjugates hand-placed
    through the block's ``reduce``/``promote`` seams; requires
    ``n_kv_heads % tp == 0``.  ``config.sliding_window`` rides into the
    default kernel pick (windowed flash block-skip / windowed dense).
    """
    from .llama import _llama_block

    if tp_size > 1 and (config.n_heads % tp_size
                        or config.n_kv_heads % tp_size):
        # catch it here, not as a reshape-to-zero-heads error deep inside
        # the shard_map trace (kv_dim can divide evenly while the head
        # count does not)
        raise ValueError(
            f"n_heads={config.n_heads} / n_kv_heads={config.n_kv_heads} "
            f"must both be divisible by model_parallel={tp_size}"
        )
    if tp_size > 1:
        cfg = dataclasses.replace(
            config,
            d_model=config.d_model // tp_size,
            n_heads=config.n_heads // tp_size,
            n_kv_heads=config.n_kv_heads // tp_size,
        )
        reduce, promote = _tp_reduce, _tp_promote
    else:
        cfg, reduce, promote = config, None, None
    block = (
        jax.checkpoint(_llama_block, static_argnums=(2, 4, 5, 6, 7))
        if remat else _llama_block
    )
    # same kernel policy as _stage_apply (measured dispatcher unless the
    # caller injects one), adapted to the family's GQA-shaped k/v and
    # sliding window
    if attention_fn is None:
        from .flash import attention_fn_for, windowed

        attention_fn = windowed(
            attention_fn_for(x.shape[1]), config.sliding_window
        )
    from .flash import gqa_adapt

    attend = gqa_adapt(attention_fn)
    if positions_table is not None:
        # zig-zag layout: RoPE rotates by the PERMUTED positions — row i
        # of the (static-content) table is seq-shard i's position vector
        positions = positions_table[jax.lax.axis_index(seq_axis)]
    else:
        positions = jnp.arange(x.shape[1])
        if seq_axis is not None:
            # sequence-sharded stage: RoPE rotates by GLOBAL positions
            # (the local shard holds rows [i*S_loc, (i+1)*S_loc))
            positions = positions + jax.lax.axis_index(seq_axis) * x.shape[1]

    if moe is not None:
        # same router grad sync as the gpt stage apply (moe._routed_ffn)
        emlp = expert_mlp
        if tp_size > 1:
            emlp = partial(expert_mlp, grad_sync=_tp_promote)
        return _moe_layer_scan(
            lambda h, layer, mlp: block(h, layer, cfg, positions, attend,
                                        mlp, reduce, promote),
            x, stage_layers, emlp, moe,
        )

    def one_layer(h, layer):
        return block(h, layer, cfg, positions, attend, None, reduce,
                     promote), None

    out, _ = jax.lax.scan(one_layer, x, stage_layers)
    return out


# Megatron's conjugate communication operators, as custom_vjps so the
# backward collectives are explicit rather than relying on AD's transpose
# rules for psum under check_vma=False:
#   g (_tp_reduce):  all-reduce forward, identity backward — closes the
#                    row-parallel partial sums.
#   f (_tp_promote): identity forward, all-reduce backward — merges the
#                    per-shard input cotangents of column-parallel matmuls.
@jax.custom_vjp
def _tp_reduce(y: jax.Array) -> jax.Array:
    return jax.lax.psum(y, "model")


def _tp_reduce_fwd(y):
    return jax.lax.psum(y, "model"), None


def _tp_reduce_bwd(_, g):
    return (g,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


@jax.custom_vjp
def _tp_promote(y: jax.Array) -> jax.Array:
    return y


def _tp_promote_fwd(y):
    return y, None


def _tp_promote_bwd(_, g):
    return (jax.lax.psum(g, "model"),)


_tp_promote.defvjp(_tp_promote_fwd, _tp_promote_bwd)


def _gpipe_tp_boundary(tp_size: int):
    """Boundary conjugates for differentiating the GPipe body under tp.

    With ``check_vma=False``, ``shard_map``'s AD handles axes a spec does
    not mention as: *outputs* split their cotangent evenly across the
    unmentioned axis (each model shard receives ``dy/tp``), and *inputs*
    ``psum`` their per-shard cotangents over it.  Both conventions are
    measured behavior (pinned by
    ``tests/test_pipeline.py::test_gpipe_tp_grads_match_no_tp_truth``)
    and both are wrong for our replicated-over-``"model"`` activations,
    so the body wraps its input/output with explicit inverses:

    - ``share`` (input): identity forward; backward divides by tp so the
      in-spec's psum over ``"model"`` restores the true cotangent.
    - ``unsplit`` (output): identity forward; backward psums the split
      ``dy/tp`` shards back into the full ``dy`` on every shard — which
      is exactly Megatron's *f* operator, so :func:`_tp_promote` is
      reused rather than redefined.
    """

    @jax.custom_vjp
    def share(x):
        return x

    share.defvjp(lambda x: (x, None), lambda _, g: (g / tp_size,))

    return share, _tp_promote


def _pipeline_body(
    stage_layers: dict,
    x_micro: jax.Array,
    *,
    config: ModelConfig,
    n_micro: int,
    axis_name: str,
    axis_size: int,
    remat: bool = False,
    tp_size: int = 1,
    attention_fn=None,
    stage_apply=None,
    moe_aux: bool = False,
    data_size: int = 1,
) -> jax.Array:
    """Per-device GPipe schedule (inside a fully-manual ``shard_map``).

    ``stage_layers``: this stage's ``[L/P, ...]`` slice of the stack
    (tp-sharded leaves when ``tp_size > 1``).
    ``x_micro``: embedded microbatches ``[M, B_loc, S, D]`` (replicated
    over ``"pipe"``/``"model"``, batch-sharded over ``"data"``; stage 0 is
    the only reader, but keeping the buffer everywhere makes the schedule
    a pure lockstep loop).  Returns the fully-processed microbatches with
    the same layout.  ``stage_apply`` is the family seam (default: the
    gpt :func:`_stage_apply`; llama passes :func:`_llama_stage_apply`).

    ``moe_aux=True``: ``stage_apply`` returns ``(y, aux_sum)`` per
    microbatch; warmup/drain slots (whose clipped reads recompute a
    microbatch whose output is masked) are masked out of the aux
    accumulation too, and the body returns ``(outputs, aux_total)`` with
    ``aux_total`` the psum over pipe AND data shards (divided by
    ``data_size`` — each data shard routed its own rows, so the global
    term is the mean over shards of the per-shard layer/microbatch
    sums).
    """
    stage_apply = stage_apply or _stage_apply
    stage = jax.lax.axis_index(axis_name)
    last = axis_size - 1

    if tp_size > 1:
        share, unsplit = _gpipe_tp_boundary(tp_size)
        x_micro = share(x_micro)
        # replicated stage leaves (layernorm scales/biases, in-spec
        # P("pipe")) also see the in-spec psum over "model" on identical
        # per-shard cotangents — share() divides it back out.  Leaves with
        # a "model" dimension in their spec transpose shard-locally and
        # stay untouched.
        stage_layers = {
            k: (v if "model" in _stage_spec(k, True) else share(v))
            for k, v in stage_layers.items()
        }

    act0 = x_micro[0] * 0.0
    out0 = x_micro * 0.0
    aux0 = jnp.zeros((), jnp.float32)

    def step(carry, t):
        act_in, outputs, aux_acc = carry
        fresh = x_micro[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(stage == 0, fresh, act_in)
        applied = stage_apply(
            stage_layers, inp, config, remat=remat, tp_size=tp_size,
            attention_fn=attention_fn,
        )
        if moe_aux:
            act_out, aux = applied
            # stage s runs microbatch m at slot t = m + s; anything else
            # is warmup/drain garbage whose aux must not count
            valid = (t >= stage) & (t - stage < n_micro)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        else:
            act_out = applied

        out_idx = jnp.clip(t - last, 0, n_micro - 1)
        outputs = jnp.where(
            (stage == last) & (t >= last),
            jax.lax.dynamic_update_index_in_dim(outputs, act_out, out_idx, 0),
            outputs,
        )
        # hand every stage's activation to its successor (single ICI hop)
        ring = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        act_next = jax.lax.ppermute(act_out, axis_name, ring)
        return (act_next, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = jax.lax.scan(
        step, (act0, out0, aux0), jnp.arange(n_micro + axis_size - 1)
    )
    # only the last stage wrote real outputs; psum broadcasts them to all
    # stages so the result is replicated over "pipe" (out_specs P(None,...))
    result = jax.lax.psum(
        jnp.where(stage == last, outputs, jnp.zeros_like(outputs)), axis_name
    )
    if tp_size > 1:
        result = unsplit(result)
    if moe_aux:
        aux_total = jax.lax.psum(aux_acc, (axis_name, "data")) / data_size
        if tp_size > 1:
            # same boundary correction as the activations: the P() out
            # spec splits the aux cotangent across the unmentioned
            # "model" axis; unsplit's backward psum restores the full
            # cotangent on every shard before it reaches the router
            aux_total = unsplit(aux_total)
        return result, aux_total
    return result


def one_f_one_b_schedule(
    n_stages: int, n_micro: int
) -> tuple[np.ndarray, np.ndarray]:
    """Static 1F1B slot tables: ``(fwd[T, P], bwd[T, P])`` with the
    microbatch index each stage runs at each slot (-1 = idle).

    Built by greedy simulation of the classic non-interleaved 1F1B
    discipline: stage ``s`` runs ``min(M, P - s)`` warmup forwards, then
    prefers backward whenever one is ready.  Dependencies: ``fwd(s, m)``
    needs ``fwd(s-1, m)`` from an earlier slot; ``bwd(s, m)`` needs
    ``fwd(s, m)`` and (below the last stage) ``bwd(s+1, m)`` earlier.

    The builder *asserts* the two buffer disciplines the SPMD body relies
    on (single-slot activation/cotangent mailboxes are never overwritten
    before consumption), so an invalid schedule fails at trace time, not
    as silent corruption.
    """
    P_, M = n_stages, n_micro
    warmup = [min(M, P_ - s) for s in range(P_)]
    fwd_done = [[-1] * M for _ in range(P_)]  # slot of fwd(s, m)
    bwd_done = [[-1] * M for _ in range(P_)]
    fwd_next = [0] * P_  # next microbatch each stage forwards
    bwd_next = [0] * P_
    fwd_rows, bwd_rows = [], []
    t = 0
    while any(b < M for b in bwd_next):
        fwd_row, bwd_row = [-1] * P_, [-1] * P_
        for s in range(P_):
            m_f, m_b = fwd_next[s], bwd_next[s]
            fwd_ready = m_f < M and (
                s == 0 or (fwd_done[s - 1][m_f] not in (-1, t)
                           and fwd_done[s - 1][m_f] < t)
            )
            bwd_ready = m_b < M and fwd_done[s][m_b] not in (-1,) and (
                fwd_done[s][m_b] < t
            ) and (
                s == P_ - 1
                or (bwd_done[s + 1][m_b] != -1 and bwd_done[s + 1][m_b] < t)
            )
            # the 1F1B discipline: backward whenever one is ready; forward
            # only while fewer than warmup_s microbatches are in flight
            # (this cap is what bounds activation memory to O(P) and what
            # keeps the mailbox assertions below true)
            can_fwd = fwd_ready and (fwd_next[s] - bwd_next[s]) < warmup[s]
            if bwd_ready:
                bwd_row[s] = m_b
                bwd_done[s][m_b] = t
                bwd_next[s] += 1
            elif can_fwd:
                fwd_row[s] = m_f
                fwd_done[s][m_f] = t
                fwd_next[s] += 1
        fwd_rows.append(fwd_row)
        bwd_rows.append(bwd_row)
        t += 1
        if t > 4 * (M + P_):  # pragma: no cover - builder bug guard
            raise RuntimeError("1F1B schedule did not converge")
    # mailbox discipline: stage s consumes act(m) at fwd(s,m); its
    # predecessor writes act(m+1) at the END of fwd(s-1, m+1) — require
    # consumption no later than that write for every (s, m)
    for s in range(1, P_):
        for m in range(M - 1):
            assert fwd_done[s][m] <= fwd_done[s - 1][m + 1], (s, m)
    for s in range(P_ - 1):
        for m in range(M - 1):
            assert bwd_done[s][m] <= bwd_done[s + 1][m + 1], (s, m)
    return np.asarray(fwd_rows), np.asarray(bwd_rows)


def pipeline_forward(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    pcfg: PipelineConfig,
    mesh: Mesh,
    remat: bool = False,
    stage_attention=None,
    positions: jax.Array | None = None,
) -> jax.Array:
    """Logits via the pipelined layer stack.

    ``tokens``: int32 ``[M, B_m, S]`` — microbatch-major so the schedule is
    explicit in the type (shard ``B_m`` over ``"data"`` with
    :func:`pipeline_batch_sharding`).  Returns fp32 logits
    ``[M, B_m, S, vocab]``.  ``positions`` (static-content int32 ``[S]``)
    overrides the natural positional indices — the zig-zag objective
    passes the permutation so slot ``i`` embeds position ``perm[i]``.
    """
    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    if seq > config.max_seq_len:
        raise ValueError(
            f"sequence length {seq} exceeds max_seq_len={config.max_seq_len}"
        )
    pos = (
        params["pos_embed"][:seq] if positions is None
        else params["pos_embed"][positions]
    )
    x = params["embed"][tokens] + pos

    pipe = mesh.shape["pipe"]
    tp_size = mesh.shape.get("model", 1)
    if stage_attention is None and mesh.shape.get("seq", 1) > 1:
        # pp x sp: ring attention inside the stages (the per-shard
        # default kernel would attend local keys only)
        stage_attention = _stage_ring_attention(mesh)
    body = partial(
        _pipeline_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=pipe,
        remat=remat,
        tp_size=tp_size,
        attention_fn=stage_attention,
    )
    # FULLY manual over every mesh axis: the schedule's ppermutes/psums
    # (and, under tp, the Megatron model-axis psums; under sp, the ring
    # rotation) are all explicit.  Partial-manual mode miscompiles bf16
    # on this jax/XLA version (see module docstring), so no axis stays
    # auto.  check_vma=False: the carried activations diverge per stage
    # and the varying-type algebra adds nothing once every collective is
    # hand-placed.
    y = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_partition_specs(params["stages"], mesh),
                  _act_spec(mesh)),
        out_specs=_act_spec(mesh),
        check_vma=False,
    )(params["stages"], x)

    y = _layer_norm(y, params["final_ln_scale"], params["final_ln_bias"])
    return jnp.einsum(
        "mbsd,vd->mbsv", y, params["embed"], preferred_element_type=jnp.float32
    )


def pipeline_loss_fn(
    params: Any,
    tokens: jax.Array,
    config: ModelConfig,
    pcfg: PipelineConfig,
    mesh: Mesh,
    attention_fn=None,  # accepted for train.make_train_step's loss seam
    remat: bool = False,
    stage_attention=None,
) -> jax.Array:
    """Mean next-token NLL over all microbatches.

    ``attention_fn`` (the train seam's mesh dispatcher) is deliberately
    ignored — it wraps its own ``shard_map`` and cannot run inside the
    fully-manual body; ``stage_attention`` is the pipeline's own
    injection seam (per-shard kernel, e.g. flash in interpret mode for
    CPU tests; default: the measured dispatcher)."""
    from .train import next_token_nll

    logits = pipeline_forward(params, tokens, config, pcfg, mesh,
                              remat=remat, stage_attention=stage_attention)
    m, b, s, v = logits.shape
    return next_token_nll(
        logits.reshape(m * b, s, v), tokens.reshape(m * b, s)
    )


def llama_pipeline_forward(
    params: dict,
    tokens: jax.Array,
    config,
    pcfg: PipelineConfig,
    mesh: Mesh,
    remat: bool = False,
    stage_attention=None,
    positions_table: jax.Array | None = None,
) -> jax.Array:
    """Logits via the pipelined llama stack — :func:`pipeline_forward`
    with the family's pieces swapped in: RoPE positions instead of a
    learned ``pos_embed`` (so embedding is just the table lookup),
    :func:`_llama_stage_apply` inside the same GPipe body, and a final
    RMSNorm + (possibly untied) readout.  ``tokens``: int32
    ``[M, B_m, S]`` -> fp32 logits ``[M, B_m, S, vocab]``."""
    from .llama import _rms_norm, readout_weights

    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    if seq > config.max_seq_len:
        raise ValueError(
            f"sequence length {seq} exceeds max_seq_len={config.max_seq_len}"
        )
    x = params["embed"][tokens]

    stage_apply = _llama_stage_apply
    if mesh.shape.get("seq", 1) > 1:
        if stage_attention is None:
            # pp x sp: GQA ring attention inside the stages, window and
            # all (compact k/v rotate over "seq")
            stage_attention = _stage_ring_attention(
                mesh, window=config.sliding_window
            )
        stage_apply = partial(_llama_stage_apply, seq_axis="seq",
                              positions_table=positions_table)
    body = partial(
        _pipeline_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=mesh.shape["pipe"],
        remat=remat,
        tp_size=mesh.shape.get("model", 1),
        attention_fn=stage_attention,
        stage_apply=stage_apply,
    )
    y = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_partition_specs(params["stages"], mesh),
                  _act_spec(mesh)),
        out_specs=_act_spec(mesh),
        check_vma=False,
    )(params["stages"], x)

    y = _rms_norm(y, params["final_norm"], config.rms_eps)
    return jnp.einsum(
        "mbsd,vd->mbsv", y, readout_weights(params),
        preferred_element_type=jnp.float32,
    )


def llama_pipeline_loss_fn(
    params: Any,
    tokens: jax.Array,
    config,
    pcfg: PipelineConfig,
    mesh: Mesh,
    attention_fn=None,  # accepted for train.make_train_step's loss seam
    remat: bool = False,
    stage_attention=None,
) -> jax.Array:
    """Mean next-token NLL over all microbatches (llama family; same
    seam contract as :func:`pipeline_loss_fn`)."""
    from .train import next_token_nll

    logits = llama_pipeline_forward(params, tokens, config, pcfg, mesh,
                                    remat=remat,
                                    stage_attention=stage_attention)
    m, b, s, v = logits.shape
    return next_token_nll(
        logits.reshape(m * b, s, v), tokens.reshape(m * b, s)
    )


def zigzag_pipeline_loss_fn(
    params: Any,
    tokens: jax.Array,
    config,
    pcfg: PipelineConfig,
    mesh: Mesh,
    llama: bool = False,
    attention_fn=None,  # accepted for train.make_train_step's loss seam
    remat: bool = False,
) -> jax.Array:
    """The zig-zag (load-balanced causal sp) objective through the
    GPipe pipeline: natural-order ``[M, B_m, S]`` tokens are permuted
    into the zig-zag layout with static index gathers, the stages run
    :func:`_stage_zigzag_attention` (every seq shard owns one early and
    one late chunk, so each ring hop computes identical half-block
    work), positions ride permuted (gpt: ``pos_embed[perm]``; llama:
    a per-shard RoPE position table), and the loss is the
    permuted-order next-token NLL — same value as
    :func:`pipeline_loss_fn` / :func:`llama_pipeline_loss_fn` on the
    same batch (pinned by test; the permutation reorders terms of the
    same mean).  GPipe only (autodiff backward); sliding windows are
    rejected like the flat zig-zag objective (the permuted blocks have
    no banded form)."""
    from .zigzag import zigzag_permutation

    if pcfg.schedule != "gpipe":
        raise ValueError(
            "the zig-zag pipeline objective runs the gpipe schedule only"
        )
    if getattr(config, "sliding_window", None) is not None:
        raise ValueError(
            "sliding_window does not compose with the zig-zag schedule; "
            "use plain pp x sp (windowed ring attention inside stages)"
        )
    n_micro, b, seq = tokens.shape
    sp = mesh.shape.get("seq", 1)
    if sp < 2:
        raise ValueError(
            "the zig-zag pipeline objective needs a (pipe, data, seq) "
            "mesh with seq >= 2"
        )
    perm = zigzag_permutation(seq, sp)
    perm_j = jnp.asarray(perm)
    tokens_zz = tokens[:, :, perm_j]
    next_tokens = jnp.concatenate(
        [tokens[:, :, 1:], jnp.zeros_like(tokens[:, :, :1])], axis=2
    )
    targets_zz = next_tokens[:, :, perm_j]
    valid = jnp.asarray(perm < seq - 1)[None, None, :]

    attend = _stage_zigzag_attention(mesh)
    if llama:
        logits = llama_pipeline_forward(
            params, tokens_zz, config, pcfg, mesh, remat=remat,
            stage_attention=attend,
            positions_table=perm_j.reshape(sp, seq // sp),
        )
    else:
        logits = pipeline_forward(
            params, tokens_zz, config, pcfg, mesh, remat=remat,
            stage_attention=attend, positions=perm_j,
        )
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        log_probs, targets_zz[..., None], axis=-1
    )[..., 0]
    return jnp.sum(nll * valid) / (n_micro * b * (seq - 1))


def _zigzag_masked_nll(valid_tbl: jax.Array, seq_size: int):
    """The zig-zag variant of :func:`_sp_masked_nll`: targets arrive
    pre-shifted-and-permuted (computed outside the body), and validity
    is the static permuted table row for this ``"seq"`` shard (the slot
    holding natural position ``S-1`` has no target) — same global
    ``B * (S_global - 1)`` normalization, so the 1F1B epilogue's psums
    reassemble exactly the GPipe zig-zag objective's mean.
    Collective-free (``axis_index`` is a constant per shard)."""

    def nll(logits, next_t):
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        token_nll = -jnp.take_along_axis(
            log_probs, next_t[..., None], axis=-1
        )[..., 0]
        valid = valid_tbl[jax.lax.axis_index("seq")]
        s_loc = next_t.shape[-1]
        total = next_t.shape[0] * (seq_size * s_loc - 1)
        return jnp.sum(token_nll * valid[None, :]) / total

    return nll


def zigzag_one_f_one_b_value_and_grad(
    params: dict,
    tokens: jax.Array,
    config,
    pcfg: "PipelineConfig",
    mesh: Mesh,
    llama: bool = False,
    remat: bool = False,
):
    """``(loss, grads)`` for the zig-zag pipeline objective via the 1F1B
    schedule — gradient-equal to autodiff of
    :func:`zigzag_pipeline_loss_fn` (same permuted layout and mask,
    explicitly-scheduled backward).  The permutation work all happens
    OUTSIDE the manual body: tokens and next-token targets permute with
    static gathers, positions ride permuted (gpt: ``pos_embed[perm]``;
    llama: the per-shard RoPE table), and the body's sp seams get the
    identity targets fn plus the permuted-validity masked NLL — the
    slot machinery is untouched."""
    from .zigzag import zigzag_permutation

    if getattr(config, "sliding_window", None) is not None:
        raise ValueError(
            "sliding_window does not compose with the zig-zag schedule; "
            "use plain pp x sp (windowed ring attention inside stages)"
        )
    n_micro, b, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    sp = mesh.shape.get("seq", 1)
    if sp < 2:
        raise ValueError(
            "the zig-zag pipeline objective needs a (pipe, data, seq) "
            "mesh with seq >= 2"
        )
    perm = zigzag_permutation(seq, sp)
    perm_j = jnp.asarray(perm)
    tokens_zz = tokens[:, :, perm_j]
    next_tokens = jnp.concatenate(
        [tokens[:, :, 1:], jnp.zeros_like(tokens[:, :, :1])], axis=2
    )
    targets_zz = next_tokens[:, :, perm_j]
    valid_tbl = jnp.asarray(perm < seq - 1, jnp.float32).reshape(
        sp, seq // sp
    )

    attend = _stage_zigzag_attention(mesh)
    if llama:
        x_micro, head, assemble_grads = _llama_embed_head(params, tokens_zz)
        stage_apply = partial(
            _llama_stage_apply, seq_axis="seq",
            positions_table=perm_j.reshape(sp, seq // sp),
        )
        head_loss = _llama_head_loss(config.rms_eps)
        head_logits = _llama_head_logits(config.rms_eps)
    else:
        x_micro, head, assemble_grads = _gpt_embed_head(
            params, tokens_zz, positions=perm_j
        )
        stage_apply = None
        head_loss = _gpt_head_loss
        head_logits = _gpt_head_logits

    stage_specs = stage_partition_specs(params["stages"], mesh)
    body = partial(
        _one_f_one_b_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=mesh.shape["pipe"],
        data_size=mesh.shape["data"],
        remat=remat,
        tp_size=mesh.shape.get("model", 1),
        seq_size=sp,
        attention_fn=attend,
        stage_apply=stage_apply,
        head_loss=head_loss,
        head_logits=head_logits,
        sp_targets_fn=lambda t: t,  # targets precomputed above
        sp_nll_fn=_zigzag_masked_nll(valid_tbl, sp),
    )
    loss, dstages, dhead, dx_micro = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_specs, P(), _act_spec(mesh), _act_spec(mesh)),
        out_specs=(P(), stage_specs, P(), _act_spec(mesh)),
        check_vma=False,
    )(params["stages"], head, x_micro, targets_zz)

    inv_m = 1.0 / pcfg.n_microbatches
    return loss * inv_m, assemble_grads(dstages, dhead, dx_micro, inv_m)


def make_zigzag_pipeline_train_step(
    mesh: Mesh,
    config,
    pcfg: PipelineConfig,
    train_config,
    state: dict,
    llama: bool = False,
):
    """Compile one pp x dp x sp optimizer step on the zig-zag objective,
    either schedule — GPipe differentiates the lockstep forward
    (:func:`zigzag_pipeline_loss_fn`); 1F1B uses the explicitly
    scheduled backward (:func:`zigzag_one_f_one_b_value_and_grad`) —
    through the same :func:`.train.make_train_step` seams every
    pipeline step uses."""
    from .train import make_train_step

    remat = getattr(train_config, "remat", False)
    if pcfg.schedule == "1f1b":
        return make_train_step(
            mesh, config, train_config, state,
            value_and_grad_fn=partial(
                zigzag_one_f_one_b_value_and_grad,
                config=config, pcfg=pcfg, mesh=mesh, llama=llama,
                remat=remat,
            ),
            state_shardings_fn=pipeline_state_shardings,
            batch_sharding_fn=pipeline_batch_sharding,
            accum_axis=1,
        )
    return make_train_step(
        mesh, config, train_config, state,
        loss=partial(
            zigzag_pipeline_loss_fn, config=config, pcfg=pcfg, mesh=mesh,
            llama=llama, remat=remat,
        ),
        state_shardings_fn=pipeline_state_shardings,
        batch_sharding_fn=pipeline_batch_sharding,
        accum_axis=1,
    )


def moe_pipeline_loss_fn(
    params: Any,
    tokens: jax.Array,
    config,
    moe,
    pcfg: PipelineConfig,
    mesh: Mesh,
    llama: bool = False,
    attention_fn=None,  # accepted for train.make_train_step's loss seam
    stage_attention=None,
    aux_weight: float | None = None,
) -> jax.Array:
    """MoE × pipeline objective (GPipe): mean next-token NLL over all
    microbatches + the Switch aux term, with the routed expert MLP
    running inside each stage's layer scan (aux rides the scan carry and
    the schedule masks warmup/drain recomputation out of it).

    Experts replicate per stage on the pp mesh — expert parallelism
    rides ``data`` only in the non-pipelined path; a dedicated ep axis
    inside the fully-manual body would buy nothing until experts
    outnumber what replication can hold.  Routing is per data shard
    (each shard's rows form its own flattened-stream groups), which with
    GShard's bounded groups is the same policy the flat path applies —
    pinned equal to the flat MoE loss under ample capacity by test.

    ``aux_weight=None`` uses ``moe.aux_loss_weight``; held-out eval
    passes ``0.0`` (pure LM NLL through the same routed forward).
    """
    from .moe import llama_moe_mlp, moe_mlp
    from .train import next_token_nll

    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    if seq > config.max_seq_len:
        raise ValueError(
            f"sequence length {seq} exceeds max_seq_len={config.max_seq_len}"
        )
    if llama:
        from .llama import _rms_norm, readout_weights

        x = params["embed"][tokens]
        stage_apply = partial(_llama_stage_apply, moe=moe,
                              expert_mlp=llama_moe_mlp)
    else:
        x = params["embed"][tokens] + params["pos_embed"][:seq]
        stage_apply = partial(_stage_apply, moe=moe, expert_mlp=moe_mlp)

    body = partial(
        _pipeline_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=mesh.shape["pipe"],
        remat=False,  # MoE rejects remat (aux closure vs re-tracing)
        tp_size=mesh.shape.get("model", 1),
        attention_fn=stage_attention,
        stage_apply=stage_apply,
        moe_aux=True,
        data_size=mesh.shape["data"],
    )
    y, aux_total = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_partition_specs(params["stages"], mesh),
                  P(None, "data")),
        out_specs=(P(None, "data"), P()),
        check_vma=False,
    )(params["stages"], x)

    if llama:
        y = _rms_norm(y, params["final_norm"], config.rms_eps)
        readout = readout_weights(params)
    else:
        y = _layer_norm(y, params["final_ln_scale"], params["final_ln_bias"])
        readout = params["embed"]
    logits = jnp.einsum(
        "mbsd,vd->mbsv", y, readout, preferred_element_type=jnp.float32
    )
    m, b, s, v = logits.shape
    nll = next_token_nll(
        logits.reshape(m * b, s, v), tokens.reshape(m * b, s)
    )
    mean_aux = aux_total / (config.n_layers * pcfg.n_microbatches)
    weight = moe.aux_loss_weight if aux_weight is None else aux_weight
    return nll + weight * mean_aux


def init_moe_pipeline_train_state(
    rng: jax.Array, config, moe, train_config, n_stages: int,
    llama: bool = False,
) -> dict:
    """MoE params with the layer stack pre-stacked (router + expert
    weights keep their leading expert axis under the layer axis)."""
    from .moe import init_llama_moe_params, init_moe_params
    from .train import init_train_state

    if config.n_layers % n_stages:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by "
            f"n_stages={n_stages}"
        )
    if llama:
        def init_fn(rng, cfg):
            return as_llama_pipeline_params(
                init_llama_moe_params(rng, cfg, moe)
            )
    else:
        def init_fn(rng, cfg):
            return as_pipeline_params(init_moe_params(rng, cfg, moe))
    return init_train_state(rng, config, train_config, init_fn=init_fn)


def make_moe_pipeline_train_step(
    mesh: Mesh,
    config,
    moe,
    pcfg: PipelineConfig,
    train_config,
    state: dict,
    llama: bool = False,
):
    """Compile one MoE × pipeline optimizer step, either schedule —
    GPipe differentiates the lockstep forward; 1F1B uses the explicitly
    scheduled backward with the Switch aux term riding each stage vjp
    as a constant cotangent (:func:`moe_one_f_one_b_value_and_grad`).
    On a (pipe, data, model) mesh the attention weights carry Megatron
    shards AND each expert's ff axis carves over ``model``
    (column-parallel up/gate, row-parallel down — see
    :func:`_stage_spec`), so expert FLOPs and memory shrink by tp like
    the dense MLP's; only the router replicates (routing must be
    identical per shard), with its dispatch/combine cotangents synced
    through ``moe._routed_ffn``'s ``grad_sync`` seam.  The EXPERT axis
    stays unsharded inside the pipeline (no ep).  No sp, no remat (the
    flat MoE constraints).  Gradient accumulation composes
    (``accum_axis=1``).
    """
    from .moe import _require_no_remat
    from .train import make_train_step

    _require_no_remat(train_config)
    _require_no_seq_axis(mesh)
    if getattr(config, "sliding_window", None) is not None:
        raise ValueError(
            "sliding_window does not compose with the pipelined MoE "
            "stack's full-causal stage kernels"
        )
    if pcfg.schedule == "1f1b":
        return make_train_step(
            mesh, config, train_config, state,
            value_and_grad_fn=partial(
                moe_one_f_one_b_value_and_grad,
                config=config, moe=moe, pcfg=pcfg, mesh=mesh, llama=llama,
            ),
            state_shardings_fn=pipeline_state_shardings,
            batch_sharding_fn=pipeline_batch_sharding,
            accum_axis=1,
        )
    return make_train_step(
        mesh, config, train_config, state,
        loss=partial(moe_pipeline_loss_fn, config=config, moe=moe,
                     pcfg=pcfg, mesh=mesh, llama=llama),
        state_shardings_fn=pipeline_state_shardings,
        batch_sharding_fn=pipeline_batch_sharding,
        accum_axis=1,
    )


def _gpt_head_logits(head, y):
    """Last-stage readout of the gpt family: final LayerNorm +
    tied-embedding logits."""
    y = _layer_norm(y, head["final_ln_scale"], head["final_ln_bias"])
    return jnp.einsum(
        "bsd,vd->bsv", y, head["embed"], preferred_element_type=jnp.float32
    )


def _gpt_head_loss(head, y, targets):
    """Mean next-token NLL through :func:`_gpt_head_logits` (the 1F1B
    body's default ``head_loss`` seam)."""
    from .train import next_token_nll

    return next_token_nll(_gpt_head_logits(head, y), targets)


def _llama_head_logits(rms_eps: float):
    """The llama-family readout: final RMSNorm + (tied embed or untied
    ``lm_head``, already selected into ``head["readout"]``) logits."""

    def head_logits(head, y):
        from .llama import _rms_norm

        y = _rms_norm(y, head["final_norm"], rms_eps)
        return jnp.einsum(
            "bsd,vd->bsv", y, head["readout"],
            preferred_element_type=jnp.float32,
        )

    return head_logits


def _llama_head_loss(rms_eps: float):
    """The llama-family ``head_loss`` seam: :func:`_llama_head_logits`
    + mean next-token NLL."""
    head_logits = _llama_head_logits(rms_eps)

    def head_loss(head, y, targets):
        from .train import next_token_nll

        return next_token_nll(head_logits(head, y), targets)

    return head_loss


def _sp_shift_targets(targets: jax.Array, seq_size: int) -> jax.Array:
    """Next-token targets for sequence-sharded loss heads, inside the
    fully-manual region.

    Each ``"seq"`` shard holds local ``targets [..., S_loc]``; global
    position ``i*S_loc + t`` predicts the token at ``i*S_loc + t + 1``,
    so every local position's target is the NEXT local token — except
    the shard's last position, whose target is the RIGHT neighbor's
    first token (one ``ppermute``; the last shard receives zeros, masked
    by :func:`_sp_masked_nll`).  This is the ONLY collective of the sp
    loss head; it depends on the targets alone, so the 1F1B body hoists
    it outside the slot scan — the per-slot head computation stays
    collective-free and can be gated to the last stage.
    """
    neighbor_first = jax.lax.ppermute(
        targets[..., :1], "seq",
        [(i, i - 1) for i in range(1, seq_size)],
    )
    return jnp.concatenate([targets[..., 1:], neighbor_first], axis=-1)


def _sp_masked_nll(logits: jax.Array, next_t: jax.Array,
                   seq_size: int) -> jax.Array:
    """Summed NLL of pre-shifted targets (:func:`_sp_shift_targets`)
    over one shard's local positions, divided by the GLOBAL count
    ``B * (S_global - 1)`` — psum over ``"seq"`` (the 1F1B epilogue's)
    reassembles exactly the unsharded next-token mean.  The global last
    position has no target and is masked out.  Collective-free (the
    ``axis_index`` is a constant per shard)."""
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    token_nll = -jnp.take_along_axis(
        log_probs, next_t[..., None], axis=-1
    )[..., 0]
    s_loc = next_t.shape[-1]
    idx = jax.lax.axis_index("seq")
    valid = jnp.ones((1, s_loc), token_nll.dtype)
    valid = valid.at[:, -1].set(jnp.where(idx == seq_size - 1, 0.0, 1.0))
    total = next_t.shape[0] * (seq_size * s_loc - 1)
    return jnp.sum(token_nll * valid) / total


def _one_f_one_b_body(
    stage_layers: dict,
    head: dict,
    x_micro: jax.Array,
    tokens_micro: jax.Array,
    *,
    config: ModelConfig,
    n_micro: int,
    axis_name: str,
    axis_size: int,
    data_size: int,
    remat: bool,
    tp_size: int,
    seq_size: int = 1,
    attention_fn=None,
    stage_apply=None,
    head_loss=None,
    head_logits=None,
    moe_aux: bool = False,
    aux_cot: float = 0.0,
    sp_targets_fn=None,
    sp_nll_fn=None,
):
    """Per-stage 1F1B schedule (inside a fully-manual ``shard_map`` over
    every mesh axis — see the module docstring for why partial-manual is
    off the table).  Batch rows are manual over ``"data"`` too, so the
    loss/grads computed here are per-data-shard means; the epilogue
    ``psum`` s them over ``"data"`` and divides by ``data_size``, making
    every output already globally averaged (matching
    :func:`pipeline_loss_fn`'s all-rows mean exactly).

    The backward slot *recomputes* the stage forward from the saved stage
    input and vjp's it immediately (``jax.vjp`` closures cannot be
    carried across ``lax.scan`` steps) — stage-granular rematerialization,
    which is exactly what bounds live activations to the 1F1B in-flight
    cap (min(M, P) stage inputs) instead of GPipe's all-M.

    Returns ``(loss, dstages, dhead, dx_micro)``; the caller divides by M
    and feeds ``dx_micro`` to the embedding vjp.

    ``stage_apply``/``head_loss`` are the family seams: the per-stage
    stacked-layer forward (default gpt :func:`_stage_apply`) and the
    last stage's ``head_loss(head, y, targets) -> scalar`` readout
    objective (default :func:`_gpt_head_loss`; llama passes its
    RMSNorm + readout version).
    """
    stage_apply = stage_apply or _stage_apply
    head_loss = head_loss or _gpt_head_loss
    fwd_tbl, bwd_tbl = one_f_one_b_schedule(axis_size, n_micro)
    window = int(min(n_micro, axis_size))
    stage = jax.lax.axis_index(axis_name)
    last = axis_size - 1
    pred = (stage - 1) % axis_size
    succ = (stage + 1) % axis_size
    fwd_ring = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    bwd_ring = [(i, (i - 1) % axis_size) for i in range(axis_size)]

    act_shape = x_micro.shape[1:]  # [B_loc, S, D]

    def stage_fwd(layers, x):
        return stage_apply(layers, x, config, tp_size=tp_size,
                           attention_fn=attention_fn)

    def stage_fwd_remat(layers, x):
        return stage_apply(layers, x, config, remat=remat, tp_size=tp_size,
                           attention_fn=attention_fn)

    def communicate(act_out, grad_out, act_in, grad_in, fwd_row, bwd_row):
        """Every slot's pipe hops with validity-gated mailboxes — the one
        implementation all three slot variants end on."""
        act_arrived = jax.lax.ppermute(act_out, axis_name, fwd_ring)
        grad_arrived = jax.lax.ppermute(
            grad_out.astype(x_micro.dtype), axis_name, bwd_ring
        )
        act_in = jnp.where(fwd_row[pred] >= 0, act_arrived, act_in)
        grad_in = jnp.where(bwd_row[succ] >= 0, grad_arrived, grad_in)
        return act_in, grad_in

    if seq_size > 1:
        # the sp loss head's ONLY collective: next-token targets shifted
        # across "seq" shards — depends on the tokens alone, so it runs
        # ONCE here instead of inside every slot (keeping the per-slot
        # head computation collective-free and gateable to the last
        # stage).  ``sp_targets_fn``/``sp_nll_fn`` are the zig-zag
        # seams: the permuted layout precomputes its (permuted) targets
        # outside the body (identity here) and masks by the static
        # permuted-validity table instead of the last-global-position
        # rule.
        _targets = sp_targets_fn or (
            lambda t: _sp_shift_targets(t, seq_size)
        )
        _sp_nll = sp_nll_fn or (
            lambda logits, next_t: _sp_masked_nll(logits, next_t,
                                                  seq_size)
        )
        next_targets_micro = _targets(tokens_micro)

    def uniform_slot(carry, tables):
        """The sp variant of ``slot``: ring attention puts collectives
        over ``"seq"`` INSIDE the stage compute, and this backend's
        collective rendezvous spans every device of the computation — a
        device skipping a ppermute (via ``lax.cond`` on a stage-varying
        predicate) deadlocks the rest.  So under sp every stage executes
        the SAME stage forward/vjp every slot, and validity gates the
        *accumulation*, not the execution (the same compute-always
        masking the GPipe body uses for its warmup/drain slots).  The
        loss head IS still gated to the last stage — its collective (the
        targets shift) was hoisted out of the scan, so the per-slot head
        vjp is collective-free and safe inside ``lax.cond``."""
        (act_in, grad_in, saved, dstage_acc, dhead_acc, dx_buf,
         loss_acc) = carry
        fwd_row, bwd_row = tables  # [P] each
        fwd_m = fwd_row[stage]
        bwd_m = bwd_row[stage]

        # ---- forward slot (compute-always) --------------------------
        m_f = jnp.clip(fwd_m, 0, n_micro - 1)
        inp = jnp.where(
            stage == 0,
            jax.lax.dynamic_index_in_dim(x_micro, m_f, 0, keepdims=False),
            act_in,
        )
        saved_new = jax.lax.dynamic_update_index_in_dim(
            saved, inp, m_f % window, 0
        )
        saved = jnp.where(fwd_m >= 0, saved_new, saved)
        act_out = stage_fwd(stage_layers, inp)

        # ---- backward slot (stage vjp compute-always) ---------------
        m_b = jnp.clip(bwd_m, 0, n_micro - 1)
        x_saved = jax.lax.dynamic_index_in_dim(
            saved, m_b % window, 0, keepdims=False
        )
        next_t = jax.lax.dynamic_index_in_dim(
            next_targets_micro, m_b, 0, keepdims=False
        )
        # one stage vjp serves both the last stage (cotangent from the
        # loss head) and mid stages (cotangent from the pipe mailbox):
        # select WHICH cotangent flows, not which code runs
        y, stage_vjp = jax.vjp(stage_fwd_remat, stage_layers, x_saved)

        def do_head(y):
            def head_obj(h, yy):
                return _sp_nll(head_logits(h, yy), next_t)

            loss_m, (dhead, dy) = jax.value_and_grad(
                head_obj, argnums=(0, 1)
            )(head, y)
            return loss_m, dhead, dy

        def skip_head(y):
            return (
                jnp.zeros((), jnp.float32),
                jax.tree.map(jnp.zeros_like, head),
                jnp.zeros_like(y),
            )

        loss_m, dhead, dy_head = jax.lax.cond(
            stage == last, do_head, skip_head, y
        )
        g_y = jnp.where(stage == last, dy_head.astype(grad_in.dtype),
                        grad_in)
        dstage, dx = stage_vjp(g_y)

        bwd_valid = bwd_m >= 0
        is_last = stage == last
        dstage_acc = jax.tree.map(
            lambda a, g: a + jnp.where(bwd_valid, g, 0).astype(jnp.float32),
            dstage_acc, dstage,
        )
        dhead_acc = jax.tree.map(
            lambda a, g: a + jnp.where(
                bwd_valid & is_last, g, 0
            ).astype(jnp.float32),
            dhead_acc, dhead,
        )
        loss_acc = loss_acc + jnp.where(
            bwd_valid & is_last, loss_m, 0.0
        )
        dx_masked = jnp.where(stage == 0, dx, jnp.zeros_like(dx))
        dx_buf_new = jax.lax.dynamic_update_index_in_dim(
            dx_buf, dx_masked, m_b, 0
        )
        dx_buf = jnp.where(bwd_valid, dx_buf_new, dx_buf)
        grad_out = jnp.where(bwd_valid, dx, jnp.zeros_like(dx))

        act_in, grad_in = communicate(act_out, grad_out, act_in, grad_in,
                                      fwd_row, bwd_row)

        return (act_in, grad_in, saved, dstage_acc, dhead_acc, dx_buf,
                loss_acc), None

    def slot(carry, tables):
        (act_in, grad_in, saved, dstage_acc, dhead_acc, dx_buf,
         loss_acc) = carry
        fwd_row, bwd_row = tables  # [P] each
        fwd_m = fwd_row[stage]
        bwd_m = bwd_row[stage]

        # ---- forward slot -------------------------------------------
        def do_fwd(args):
            act_in, saved = args
            m = jnp.clip(fwd_m, 0, n_micro - 1)
            inp = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(x_micro, m, 0, keepdims=False),
                act_in,
            )
            saved = jax.lax.dynamic_update_index_in_dim(
                saved, inp, m % window, 0
            )
            # the last stage's output goes nowhere (its bwd slot
            # recomputes through the loss head), so skip its matmuls
            y = jax.lax.cond(
                stage == last,
                lambda layers, x: jnp.zeros(act_shape, x.dtype),
                stage_fwd,
                stage_layers, inp,
            )
            return y, saved

        act_out, saved = jax.lax.cond(
            fwd_m >= 0,
            do_fwd,
            lambda args: (jnp.zeros(act_shape, x_micro.dtype), args[1]),
            (act_in, saved),
        )

        # ---- backward slot ------------------------------------------
        def do_bwd(args):
            grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc = args
            m = jnp.clip(bwd_m, 0, n_micro - 1)
            x_saved = jax.lax.dynamic_index_in_dim(
                saved, m % window, 0, keepdims=False
            )

            def last_branch(grad_in):
                targets = jax.lax.dynamic_index_in_dim(
                    tokens_micro, m, 0, keepdims=False
                )

                def loss_of(layers, head, x):
                    return head_loss(head, stage_fwd_remat(layers, x),
                                     targets)

                loss_m, (dstage, dhead, dx) = jax.value_and_grad(
                    loss_of, argnums=(0, 1, 2)
                )(stage_layers, head, x_saved)
                return loss_m, dstage, dhead, dx

            def mid_branch(grad_in):
                _, vjp = jax.vjp(stage_fwd_remat, stage_layers, x_saved)
                dstage, dx = vjp(grad_in)
                zero_head = jax.tree.map(jnp.zeros_like, head)
                return jnp.zeros((), jnp.float32), dstage, zero_head, dx

            loss_m, dstage, dhead, dx = jax.lax.cond(
                stage == last, last_branch, mid_branch, grad_in
            )
            dstage_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), dstage_acc, dstage
            )
            dhead_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), dhead_acc, dhead
            )
            # only stage 0's dx feeds the embedding backward; other
            # stages write zeros into their (ignored, psum'ed-away) rows
            dx_masked = jnp.where(stage == 0, dx, jnp.zeros_like(dx))
            dx_buf = jax.lax.dynamic_update_index_in_dim(
                dx_buf, dx_masked, m, 0
            )
            return grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc + loss_m, dx

        def skip_bwd(args):
            grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc = args
            return (grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc,
                    jnp.zeros(act_shape, x_micro.dtype))

        (grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc,
         grad_out) = jax.lax.cond(
            bwd_m >= 0,
            do_bwd,
            skip_bwd,
            (grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc),
        )

        act_in, grad_in = communicate(act_out, grad_out, act_in, grad_in,
                                      fwd_row, bwd_row)

        return (act_in, grad_in, saved, dstage_acc, dhead_acc, dx_buf,
                loss_acc), None

    def moe_slot(carry, tables):
        """The MoE variant of ``slot``: ``stage_apply`` returns
        ``(y, aux_sum)`` per microbatch.  The aux term joins gradients as
        a CONSTANT cotangent on the stage vjp's aux output (``aux_cot``,
        pre-scaled by the caller so the epilogue/caller 1/M·1/dp scaling
        lands it at ``weight/(n_layers·M)`` — exactly autodiff of the
        GPipe objective), and joins the LOSS via a separate accumulator
        so every stage's aux counts, not just the last's.  Routing is
        shard-local (experts replicate per stage), so the stage compute
        has no collectives and the validity ``lax.cond`` s stay safe."""
        (act_in, grad_in, saved, dstage_acc, dhead_acc, dx_buf,
         loss_acc, aux_acc) = carry
        fwd_row, bwd_row = tables
        fwd_m = fwd_row[stage]
        bwd_m = bwd_row[stage]

        # ---- forward slot -------------------------------------------
        def do_fwd(args):
            act_in, saved = args
            m = jnp.clip(fwd_m, 0, n_micro - 1)
            inp = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(x_micro, m, 0, keepdims=False),
                act_in,
            )
            saved = jax.lax.dynamic_update_index_in_dim(
                saved, inp, m % window, 0
            )
            y = jax.lax.cond(
                stage == last,
                lambda layers, x: jnp.zeros(act_shape, x.dtype),
                lambda layers, x: stage_fwd(layers, x)[0],
                stage_layers, inp,
            )
            return y, saved

        act_out, saved = jax.lax.cond(
            fwd_m >= 0,
            do_fwd,
            lambda args: (jnp.zeros(act_shape, x_micro.dtype), args[1]),
            (act_in, saved),
        )

        # ---- backward slot ------------------------------------------
        def do_bwd(args):
            grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc, aux_acc = args
            m = jnp.clip(bwd_m, 0, n_micro - 1)
            x_saved = jax.lax.dynamic_index_in_dim(
                saved, m % window, 0, keepdims=False
            )
            targets = jax.lax.dynamic_index_in_dim(
                tokens_micro, m, 0, keepdims=False
            )
            (y, aux), stage_vjp = jax.vjp(
                stage_fwd_remat, stage_layers, x_saved
            )

            def last_head(y):
                loss_m, (dhead, dy) = jax.value_and_grad(
                    head_loss, argnums=(0, 1)
                )(head, y, targets)
                return loss_m, dhead, dy

            def mid_head(y):
                return (
                    jnp.zeros((), jnp.float32),
                    jax.tree.map(jnp.zeros_like, head),
                    jnp.zeros_like(y),
                )

            loss_m, dhead, dy_head = jax.lax.cond(
                stage == last, last_head, mid_head, y
            )
            g_y = jnp.where(stage == last, dy_head.astype(grad_in.dtype),
                            grad_in)
            dstage, dx = stage_vjp(
                (g_y, jnp.asarray(aux_cot, aux.dtype))
            )
            dstage_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), dstage_acc, dstage
            )
            dhead_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), dhead_acc, dhead
            )
            dx_masked = jnp.where(stage == 0, dx, jnp.zeros_like(dx))
            dx_buf = jax.lax.dynamic_update_index_in_dim(
                dx_buf, dx_masked, m, 0
            )
            return (grad_in, dstage_acc, dhead_acc, dx_buf,
                    loss_acc + loss_m, aux_acc + aux, dx)

        def skip_bwd(args):
            grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc, aux_acc = args
            return (grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc,
                    aux_acc, jnp.zeros(act_shape, x_micro.dtype))

        (grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc, aux_acc,
         grad_out) = jax.lax.cond(
            bwd_m >= 0,
            do_bwd,
            skip_bwd,
            (grad_in, dstage_acc, dhead_acc, dx_buf, loss_acc, aux_acc),
        )

        act_in, grad_in = communicate(act_out, grad_out, act_in, grad_in,
                                      fwd_row, bwd_row)

        return (act_in, grad_in, saved, dstage_acc, dhead_acc, dx_buf,
                loss_acc, aux_acc), None

    carry0 = (
        jnp.zeros(act_shape, x_micro.dtype),  # act mailbox
        jnp.zeros(act_shape, x_micro.dtype),  # grad mailbox
        jnp.zeros((window, *act_shape), x_micro.dtype),  # saved inputs
        jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), stage_layers
        ),
        jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), head),
        jnp.zeros((n_micro, *act_shape), x_micro.dtype),
        jnp.zeros((), jnp.float32),
    )
    tables = (jnp.asarray(fwd_tbl), jnp.asarray(bwd_tbl))
    if moe_aux:
        carry0 = carry0 + (jnp.zeros((), jnp.float32),)
        (_, _, _, dstage_acc, dhead_acc, dx_buf, loss_acc,
         aux_acc), _ = jax.lax.scan(moe_slot, carry0, tables)
    else:
        (_, _, _, dstage_acc, dhead_acc, dx_buf, loss_acc), _ = jax.lax.scan(
            uniform_slot if seq_size > 1 else slot, carry0, tables
        )

    # epilogue: replicate the pieces only one stage holds, and average the
    # per-data-shard means into the global all-rows mean (1/dp).  No psum
    # over "model": activations/head stay replicated there, so each model
    # shard already computed identical loss/dhead/dx values.  Under sp the
    # per-"seq"-shard loss/head/stage contributions are partial SUMS
    # (each already carries the global position-count normalization, see
    # _sp_masked_nll), so "seq" joins the psums with no extra divide;
    # dx stays per-seq-shard (its out spec is sequence-sharded).
    seq_axes = ("seq",) if seq_size > 1 else ()
    inv_dp = 1.0 / data_size
    loss = jax.lax.psum(
        jnp.where(stage == last, loss_acc, 0.0),
        (axis_name, "data", *seq_axes),
    ) * inv_dp
    dstages = jax.tree.map(
        lambda g: jax.lax.psum(g, ("data", *seq_axes)) * inv_dp, dstage_acc
    )
    dhead = jax.tree.map(
        lambda g: jax.lax.psum(
            jnp.where(stage == last, g, jnp.zeros_like(g)),
            (axis_name, "data", *seq_axes),
        ) * inv_dp,
        dhead_acc,
    )
    dx_micro = jax.lax.psum(
        jnp.where(stage == 0, dx_buf, jnp.zeros_like(dx_buf)), axis_name
    ) * inv_dp
    if moe_aux:
        # every stage contributes aux (unlike loss/dhead, which only the
        # last stage holds): psum over pipe SUMS the per-stage terms,
        # matching _pipeline_body's gpipe aux_total
        aux_total = jax.lax.psum(aux_acc, (axis_name, "data")) * inv_dp
        return loss, dstages, dhead, dx_micro, aux_total
    return loss, dstages, dhead, dx_micro


def _gpt_embed_head(params: dict, tokens: jax.Array,
                    positions: jax.Array | None = None):
    """The gpt family's outside-the-pipeline pieces for a 1F1B backward:
    embedded microbatches (with the embed vjp), the loss-head leaves,
    and the grads assembler that folds the body's raw sums into the
    final gradient pytree (embedding lookup cotangents from stage 0,
    tied-embedding unembed contribution from the last stage — summed).
    One implementation for the dense AND MoE 1F1B callers.
    ``positions`` (static-content int32 ``[S]``) overrides the natural
    positional indices — the zig-zag objective passes its permutation
    so slot ``i`` embeds position ``perm[i]``."""
    seq = tokens.shape[-1]

    def embed_fn(embed_params):
        pos = (
            embed_params["pos_embed"][:seq] if positions is None
            else embed_params["pos_embed"][positions]
        )
        return embed_params["embed"][tokens] + pos

    embed_params = {
        "embed": params["embed"], "pos_embed": params["pos_embed"]
    }
    x_micro, embed_vjp = jax.vjp(embed_fn, embed_params)
    head = {
        "embed": params["embed"],
        "final_ln_scale": params["final_ln_scale"],
        "final_ln_bias": params["final_ln_bias"],
    }

    def assemble_grads(dstages, dhead, dx_micro, inv_m):
        (d_embed_side,) = embed_vjp(dx_micro * inv_m)
        dtype_of = lambda name: params[name].dtype  # noqa: E731
        return {
            "stages": jax.tree.map(
                lambda g, p: (g * inv_m).astype(p.dtype),
                dstages, params["stages"],
            ),
            "embed": (
                dhead["embed"] * inv_m
                + d_embed_side["embed"].astype(jnp.float32)
            ).astype(dtype_of("embed")),
            "pos_embed": d_embed_side["pos_embed"].astype(
                dtype_of("pos_embed")
            ),
            "final_ln_scale": (dhead["final_ln_scale"] * inv_m).astype(
                dtype_of("final_ln_scale")
            ),
            "final_ln_bias": (dhead["final_ln_bias"] * inv_m).astype(
                dtype_of("final_ln_bias")
            ),
        }

    return x_micro, head, assemble_grads


def _llama_embed_head(params: dict, tokens: jax.Array):
    """The llama counterpart of :func:`_gpt_embed_head`: lookup-only
    embedding (RoPE lives inside the stages), RMSNorm + readout head
    leaves, and the grads assembler — with a tied readout the embed
    cotangent sums with the last stage's, an untied ``lm_head`` (HF
    imports) gets its own gradient entry."""
    tied = "lm_head" not in params

    def embed_fn(embed_table):
        return embed_table[tokens]

    x_micro, embed_vjp = jax.vjp(embed_fn, params["embed"])
    head = {
        "readout": params["embed"] if tied else params["lm_head"],
        "final_norm": params["final_norm"],
    }

    def assemble_grads(dstages, dhead, dx_micro, inv_m):
        (d_embed_side,) = embed_vjp(dx_micro * inv_m)
        grads = {
            "stages": jax.tree.map(
                lambda g, p: (g * inv_m).astype(p.dtype),
                dstages, params["stages"],
            ),
            "final_norm": (dhead["final_norm"] * inv_m).astype(
                params["final_norm"].dtype
            ),
        }
        if tied:
            grads["embed"] = (
                dhead["readout"] * inv_m
                + d_embed_side.astype(jnp.float32)
            ).astype(params["embed"].dtype)
        else:
            grads["embed"] = d_embed_side.astype(params["embed"].dtype)
            grads["lm_head"] = (dhead["readout"] * inv_m).astype(
                params["lm_head"].dtype
            )
        return grads

    return x_micro, head, assemble_grads


def one_f_one_b_value_and_grad(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    pcfg: "PipelineConfig",
    mesh: Mesh,
    remat: bool = False,
    stage_attention=None,
):
    """``(loss, grads)`` for the pipelined LM via the 1F1B schedule.

    Gradient-equal to ``jax.value_and_grad(pipeline_loss_fn)`` (same math,
    different schedule/memory profile — asserted by
    ``tests/test_pipeline.py::test_1f1b_grads_match_gpipe_autodiff``); the
    embedding/head handling is :func:`_gpt_embed_head`.
    """
    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    x_micro, head, assemble_grads = _gpt_embed_head(params, tokens)

    pipe = mesh.shape["pipe"]
    sp = mesh.shape.get("seq", 1)
    if sp > 1 and stage_attention is None:
        # pp x sp x 1F1B: ring attention inside the stage fwd/bwd (its
        # ppermutes differentiate through jax.vjp — the transpose of a
        # rotation is the inverse rotation); the loss head goes
        # sequence-sharded via head_logits + the sp masked NLL
        stage_attention = _stage_ring_attention(mesh)
    stage_specs = stage_partition_specs(params["stages"], mesh)
    body = partial(
        _one_f_one_b_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=pipe,
        data_size=mesh.shape["data"],
        remat=remat,
        tp_size=mesh.shape.get("model", 1),
        seq_size=sp,
        attention_fn=stage_attention,
        head_logits=_gpt_head_logits,
    )
    loss, dstages, dhead, dx_micro = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_specs, P(), _act_spec(mesh), _act_spec(mesh)),
        out_specs=(P(), stage_specs, P(), _act_spec(mesh)),
        check_vma=False,
    )(params["stages"], head, x_micro, tokens)

    inv_m = 1.0 / pcfg.n_microbatches
    return loss * inv_m, assemble_grads(dstages, dhead, dx_micro, inv_m)


def llama_one_f_one_b_value_and_grad(
    params: dict,
    tokens: jax.Array,
    config,
    pcfg: "PipelineConfig",
    mesh: Mesh,
    remat: bool = False,
    stage_attention=None,
):
    """``(loss, grads)`` for the pipelined llama LM via the 1F1B schedule
    — :func:`one_f_one_b_value_and_grad` with the family seams swapped in
    (:func:`_llama_stage_apply`, :func:`_llama_head_loss`).  Gradient-
    equal to autodiff of :func:`llama_pipeline_loss_fn` (asserted by
    ``tests/test_pipeline_llama.py``).  The embedding/head handling is
    :func:`_llama_embed_head`."""
    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    x_micro, head, assemble_grads = _llama_embed_head(params, tokens)

    sp = mesh.shape.get("seq", 1)
    stage_apply = _llama_stage_apply
    if sp > 1:
        # pp x sp x 1F1B, llama: GQA ring attention (window included)
        # inside the stage fwd/bwd, global RoPE offsets per seq shard,
        # sequence-sharded loss head via head_logits + the sp masked NLL
        if stage_attention is None:
            stage_attention = _stage_ring_attention(
                mesh, window=config.sliding_window
            )
        stage_apply = partial(_llama_stage_apply, seq_axis="seq")
    stage_specs = stage_partition_specs(params["stages"], mesh)
    body = partial(
        _one_f_one_b_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=mesh.shape["pipe"],
        data_size=mesh.shape["data"],
        remat=remat,
        tp_size=mesh.shape.get("model", 1),
        seq_size=sp,
        attention_fn=stage_attention,
        stage_apply=stage_apply,
        head_loss=_llama_head_loss(config.rms_eps),
        head_logits=_llama_head_logits(config.rms_eps),
    )
    loss, dstages, dhead, dx_micro = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_specs, P(), _act_spec(mesh), _act_spec(mesh)),
        out_specs=(P(), stage_specs, P(), _act_spec(mesh)),
        check_vma=False,
    )(params["stages"], head, x_micro, tokens)

    inv_m = 1.0 / pcfg.n_microbatches
    return loss * inv_m, assemble_grads(dstages, dhead, dx_micro, inv_m)


def moe_one_f_one_b_value_and_grad(
    params: dict,
    tokens: jax.Array,
    config,
    moe,
    pcfg: "PipelineConfig",
    mesh: Mesh,
    llama: bool = False,
    remat: bool = False,  # accepted for seam parity; MoE rejects remat
    stage_attention=None,
):
    """``(loss, grads)`` for the MoE pipelined LM via the 1F1B schedule
    — gradient-equal to ``jax.value_and_grad(moe_pipeline_loss_fn)``
    (asserted by ``tests/test_moe.py``).  The Switch aux term threads
    through the hand-built backward as a constant cotangent on each
    stage vjp's aux output (``weight / n_layers``, so the shared 1/M
    scaling lands it at the GPipe objective's
    ``weight · aux_total / (n_layers · M)``), and every stage's aux
    value joins the reported loss via the body's separate accumulator.
    Same mesh contract as the GPipe MoE objective: (pipe, data[, model])
    — attention AND expert ff Megatron-sharded under tp, router
    replicated with grad-synced dispatch/combine — no sp, no remat."""
    from .moe import llama_moe_mlp, moe_mlp

    _require_no_seq_axis(mesh)
    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )

    if llama:
        x_micro, head, assemble_grads = _llama_embed_head(params, tokens)
        stage_apply = partial(_llama_stage_apply, moe=moe,
                              expert_mlp=llama_moe_mlp)
        head_loss = _llama_head_loss(config.rms_eps)
    else:
        x_micro, head, assemble_grads = _gpt_embed_head(params, tokens)
        stage_apply = partial(_stage_apply, moe=moe, expert_mlp=moe_mlp)
        head_loss = _gpt_head_loss

    aux_cot = moe.aux_loss_weight / config.n_layers
    stage_specs = stage_partition_specs(params["stages"], mesh)
    body = partial(
        _one_f_one_b_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=mesh.shape["pipe"],
        data_size=mesh.shape["data"],
        remat=False,  # MoE rejects remat (aux closure vs re-tracing)
        tp_size=mesh.shape.get("model", 1),
        attention_fn=stage_attention,
        stage_apply=stage_apply,
        head_loss=head_loss,
        moe_aux=True,
        aux_cot=aux_cot,
    )
    loss, dstages, dhead, dx_micro, aux_total = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_specs, P(), P(None, "data"), P(None, "data")),
        out_specs=(P(), stage_specs, P(), P(None, "data"), P()),
        check_vma=False,
    )(params["stages"], head, x_micro, tokens)

    inv_m = 1.0 / pcfg.n_microbatches
    total_loss = (loss + aux_cot * aux_total) * inv_m
    return total_loss, assemble_grads(dstages, dhead, dx_micro, inv_m)


def pipeline_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Tokens ``[M, B_m, S]``: microbatch axis replicated, batch over
    data, sequence over ``seq`` on a pp x dp x sp mesh (the same rule
    the body's activation specs use — :func:`_act_spec`)."""
    return NamedSharding(mesh, _act_spec(mesh))


def pipeline_param_shardings(mesh: Mesh, params: dict) -> dict:
    """Stage stacks shard their leading layer axis over ``"pipe"`` — and,
    on a pp x tp mesh, their Megatron axes over ``"model"`` via the same
    PARAM_AXES rules the non-pipelined trainer uses (these NamedShardings
    agree leaf-for-leaf with :func:`stage_partition_specs`, so device_put
    placement and the manual body see the same layout).
    Embedding/unembedding/final-LN replicate (they live outside the
    pipelined region)."""
    with_model = mesh.shape.get("model", 1) > 1

    def param_spec(path, leaf):
        keys = [p.key for p in path if hasattr(p, "key")]
        if "stages" not in keys:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _stage_spec(keys[-1], with_model))

    return jax.tree_util.tree_map_with_path(param_spec, params)


def pipeline_state_shardings(mesh: Mesh, state: dict) -> dict:
    """:func:`.train.state_shardings` with the stage-stacked param rules
    (Adam moments mirror their parameters either way)."""
    from .train import state_shardings

    return state_shardings(
        mesh, state, param_shardings_fn=pipeline_param_shardings
    )


def init_pipeline_train_state(
    rng: jax.Array, config: ModelConfig, train_config, n_stages: int
) -> dict:
    from .train import init_train_state

    return init_train_state(
        rng, config, train_config,
        init_fn=partial(init_pipeline_params, n_stages=n_stages),
    )


def place_pipeline_state(mesh: Mesh, state: dict) -> dict:
    from .train import place_state

    return place_state(mesh, state, state_shardings_fn=pipeline_state_shardings)


def make_pipeline_train_step(
    mesh: Mesh,
    config: ModelConfig,
    pcfg: PipelineConfig,
    train_config,
    state: dict,
):
    """Compile one pp x dp (x tp) optimizer step.

    ``pcfg.schedule`` picks the pipeline schedule: ``"gpipe"``
    differentiates the lockstep forward (reverse-pipeline collectives
    inserted by AD); ``"1f1b"`` uses the explicitly-scheduled backward
    (:func:`one_f_one_b_value_and_grad`) — same gradients, ``min(M, P)``
    live stage inputs instead of all M.

    Delegates to :func:`.train.make_train_step` through its loss/sharding
    seams so there is exactly one optimizer-step implementation.
    """
    from .train import make_train_step

    remat = getattr(train_config, "remat", False)
    if pcfg.schedule == "1f1b":
        return make_train_step(
            mesh, config, train_config, state,
            value_and_grad_fn=partial(
                one_f_one_b_value_and_grad,
                config=config, pcfg=pcfg, mesh=mesh, remat=remat,
            ),
            state_shardings_fn=pipeline_state_shardings,
            batch_sharding_fn=pipeline_batch_sharding,
            accum_axis=1,
        )
    return make_train_step(
        mesh, config, train_config, state,
        loss=partial(pipeline_loss_fn, config=config, pcfg=pcfg, mesh=mesh,
                     remat=remat),
        state_shardings_fn=pipeline_state_shardings,
        batch_sharding_fn=pipeline_batch_sharding,
        accum_axis=1,
    )


def _require_no_seq_axis(mesh: Mesh) -> None:
    """The MoE pipeline objective keeps its activations/loss head (and
    the aux term riding the stage scan) unsharded over sequence — it runs
    on (pipe, data[, model]) meshes only.  The plain 1F1B schedule DOES
    compose with sp (ring attention in the stage fwd/bwd, sequence-
    sharded loss head via ``_sp_shift_targets`` + ``_sp_masked_nll``)."""
    if mesh.shape.get("seq", 1) > 1:
        raise ValueError(
            "this pipeline objective supports (pipe, data[, model]) "
            "meshes only — moe x pp does not combine with seq_parallel"
        )


def init_llama_pipeline_train_state(
    rng: jax.Array, config, train_config, n_stages: int
) -> dict:
    from .train import init_train_state

    return init_train_state(
        rng, config, train_config,
        init_fn=partial(init_llama_pipeline_params, n_stages=n_stages),
    )


def make_llama_pipeline_train_step(
    mesh: Mesh,
    config,
    pcfg: PipelineConfig,
    train_config,
    state: dict,
):
    """Compile one llama-family pp x dp (x tp) optimizer step — the
    counterpart of :func:`make_pipeline_train_step` with the family's
    loss/backward swapped through the same :func:`.train.make_train_step`
    seams (one optimizer-step implementation for every variant).

    ``config.sliding_window`` rides into the per-stage kernel pick via
    :func:`_llama_stage_apply`'s default dispatcher; gradient
    accumulation microbatches over the batch axis (``accum_axis=1`` —
    the leading axis is the pipeline's own microbatch schedule).
    """
    from .train import make_train_step

    remat = getattr(train_config, "remat", False)
    if pcfg.schedule == "1f1b":
        return make_train_step(
            mesh, config, train_config, state,
            value_and_grad_fn=partial(
                llama_one_f_one_b_value_and_grad,
                config=config, pcfg=pcfg, mesh=mesh, remat=remat,
            ),
            state_shardings_fn=pipeline_state_shardings,
            batch_sharding_fn=pipeline_batch_sharding,
            accum_axis=1,
        )
    return make_train_step(
        mesh, config, train_config, state,
        loss=partial(llama_pipeline_loss_fn, config=config, pcfg=pcfg,
                     mesh=mesh, remat=remat),
        state_shardings_fn=pipeline_state_shardings,
        batch_sharding_fn=pipeline_batch_sharding,
        accum_axis=1,
    )
