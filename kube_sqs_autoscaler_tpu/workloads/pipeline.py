"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

The reference (``/root/reference``) has no parallelism of any kind
(SURVEY.md §2 — a single-goroutine Go control loop); this module completes
the package's parallelism set (dp/tp/sp/ep in :mod:`.train`/:mod:`.ring`/
:mod:`.moe`) with **pp**, TPU-native:

- The transformer's layer stack is *stacked* into one pytree with a leading
  ``[n_layers, ...]`` axis and sharded over a ``"pipe"`` mesh axis, so each
  device holds ``n_layers / pipe`` contiguous layers (one stage).
- Inside ``shard_map``, microbatches flow through the stages on a GPipe
  schedule: ``n_micro + pipe - 1`` lockstep steps, each ending with a
  single-hop ``jax.lax.ppermute`` that hands every stage's activation to
  its successor — neighbor traffic that rides the ICI torus, never DCN.
- Per-stage compute is a ``lax.scan`` over the stage's stacked layers
  (trace one layer, compile once, no Python unrolling), running the same
  :func:`.model._block` as every other execution path.
- The remaining mesh axis is ``"data"``: microbatches shard their batch
  dim over it, so pp x dp composes in one ``jit``.  (Combining pp with
  tp/sp is a matter of meshes with more axes; embedding/unembedding stay
  outside the pipelined region and replicate over ``"pipe"``.)

The bubble fraction is the usual ``(pipe-1) / (n_micro + pipe - 1)`` —
raise ``n_microbatches`` to amortize it.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .model import ModelConfig, _block, _dense_attention, _layer_norm, init_params


@dataclass(frozen=True)
class PipelineConfig:
    """Schedule knobs: how many microbatches flow through the stages."""

    n_microbatches: int = 4


def make_pipeline_mesh(
    devices: list | None = None, pipe_parallel: int | None = None
) -> Mesh:
    """A ``("pipe", "data")`` mesh; ``pipe_parallel`` defaults to all devices."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    pipe = pipe_parallel if pipe_parallel is not None else n
    if n % pipe:
        raise ValueError(f"{n} devices not divisible by pipe_parallel={pipe}")
    grid = np.asarray(devices).reshape(pipe, n // pipe)
    return Mesh(grid, ("pipe", "data"))


def stack_layers(params: dict) -> dict:
    """``layers`` list-of-dicts -> one stacked pytree with leading ``[L]``.

    The stacked form is what shards over ``"pipe"`` and what ``lax.scan``
    consumes; stacking order == layer order, and GSPMD's contiguous
    leading-axis sharding assigns layers ``[i*L/P, (i+1)*L/P)`` to stage
    ``i`` — the natural pipeline placement.
    """
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *params["layers"])


def init_pipeline_params(
    rng: jax.Array, config: ModelConfig, n_stages: int
) -> dict:
    """:func:`.model.init_params` with the layer stack pre-stacked."""
    if config.n_layers % n_stages:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by n_stages={n_stages}"
        )
    params = init_params(rng, config)
    stages = stack_layers(params)
    del params["layers"]
    params["stages"] = stages
    return params


def _stage_apply(
    stage_layers: dict, x: jax.Array, config: ModelConfig,
    remat: bool = False,
) -> jax.Array:
    """Run one stage's stacked layers over an activation microbatch.

    ``remat=True`` checkpoints each layer like :func:`.model.forward`
    does: the backward pass recomputes block activations instead of
    keeping every microbatch's every layer resident — on a pipeline
    stage that is the difference between O(M·L/P) and O(M + L/P) live
    activations.
    """
    block = jax.checkpoint(_block, static_argnums=(2, 3)) if remat else _block

    def one_layer(h, layer):
        return block(h, layer, config, _dense_attention), None

    out, _ = jax.lax.scan(one_layer, x, stage_layers)
    return out


def _pipeline_body(
    stage_layers: dict,
    x_micro: jax.Array,
    *,
    config: ModelConfig,
    n_micro: int,
    axis_name: str,
    axis_size: int,
    remat: bool = False,
) -> jax.Array:
    """Per-device GPipe schedule (inside ``shard_map``).

    ``stage_layers``: this stage's ``[L/P, ...]`` slice of the stack.
    ``x_micro``: embedded microbatches ``[M, B_m, S, D]`` (replicated over
    ``"pipe"``; stage 0 is the only reader, but keeping the buffer
    everywhere makes the schedule a pure lockstep loop).  Returns the
    fully-processed microbatches, replicated back over ``"pipe"``.
    """
    stage = jax.lax.axis_index(axis_name)
    last = axis_size - 1

    # x_micro replicates over "pipe" (in_spec P(None, "data")), but the
    # carried activations diverge per stage, so mark the accumulators as
    # pipe-varying for shard_map's scan-carry type check
    act0 = jax.lax.pcast(x_micro[0] * 0.0, (axis_name,), to="varying")
    out0 = jax.lax.pcast(x_micro * 0.0, (axis_name,), to="varying")

    def step(carry, t):
        act_in, outputs = carry
        fresh = x_micro[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(stage == 0, fresh, act_in)
        act_out = _stage_apply(stage_layers, inp, config, remat=remat)

        out_idx = jnp.clip(t - last, 0, n_micro - 1)
        outputs = jnp.where(
            (stage == last) & (t >= last),
            jax.lax.dynamic_update_index_in_dim(outputs, act_out, out_idx, 0),
            outputs,
        )
        # hand every stage's activation to its successor (single ICI hop)
        ring = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        act_next = jax.lax.ppermute(act_out, axis_name, ring)
        return (act_next, outputs), None

    (_, outputs), _ = jax.lax.scan(
        step, (act0, out0), jnp.arange(n_micro + axis_size - 1)
    )
    # only the last stage wrote real outputs; psum broadcasts them to all
    # stages so the result is replicated over "pipe" (out_specs P(None,...))
    return jax.lax.psum(
        jnp.where(stage == last, outputs, jnp.zeros_like(outputs)), axis_name
    )


def pipeline_forward(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    pcfg: PipelineConfig,
    mesh: Mesh,
    remat: bool = False,
) -> jax.Array:
    """Logits via the pipelined layer stack.

    ``tokens``: int32 ``[M, B_m, S]`` — microbatch-major so the schedule is
    explicit in the type (shard ``B_m`` over ``"data"`` with
    :func:`pipeline_batch_sharding`).  Returns fp32 logits
    ``[M, B_m, S, vocab]``.
    """
    n_micro, _, seq = tokens.shape
    if n_micro != pcfg.n_microbatches:
        raise ValueError(
            f"tokens have {n_micro} microbatches, config says "
            f"{pcfg.n_microbatches}"
        )
    if seq > config.max_seq_len:
        raise ValueError(
            f"sequence length {seq} exceeds max_seq_len={config.max_seq_len}"
        )
    x = params["embed"][tokens] + params["pos_embed"][:seq]

    pipe = mesh.shape["pipe"]
    body = partial(
        _pipeline_body,
        config=config,
        n_micro=pcfg.n_microbatches,
        axis_name="pipe",
        axis_size=pipe,
        remat=remat,
    )
    y = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P("pipe"), P(None, "data")),
        out_specs=P(None, "data"),
    )(params["stages"], x)

    y = _layer_norm(y, params["final_ln_scale"], params["final_ln_bias"])
    return jnp.einsum(
        "mbsd,vd->mbsv", y, params["embed"], preferred_element_type=jnp.float32
    )


def pipeline_loss_fn(
    params: Any,
    tokens: jax.Array,
    config: ModelConfig,
    pcfg: PipelineConfig,
    mesh: Mesh,
    attention_fn=None,  # accepted for train.make_train_step's loss seam
    remat: bool = False,
) -> jax.Array:
    """Mean next-token NLL over all microbatches."""
    from .train import next_token_nll

    logits = pipeline_forward(params, tokens, config, pcfg, mesh,
                              remat=remat)
    m, b, s, v = logits.shape
    return next_token_nll(
        logits.reshape(m * b, s, v), tokens.reshape(m * b, s)
    )


def pipeline_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Tokens ``[M, B_m, S]``: microbatch axis replicated, batch over data."""
    return NamedSharding(mesh, P(None, "data", None))


def pipeline_param_shardings(mesh: Mesh, params: dict) -> dict:
    """Stage stacks shard their leading layer axis over ``"pipe"``;
    embedding/unembedding/final-LN replicate."""

    def param_spec(path, leaf):
        keys = [p.key for p in path if hasattr(p, "key")]
        return NamedSharding(mesh, P("pipe") if "stages" in keys else P())

    return jax.tree_util.tree_map_with_path(param_spec, params)


def pipeline_state_shardings(mesh: Mesh, state: dict) -> dict:
    """:func:`.train.state_shardings` with the stage-stacked param rules
    (Adam moments mirror their parameters either way)."""
    from .train import state_shardings

    return state_shardings(
        mesh, state, param_shardings_fn=pipeline_param_shardings
    )


def init_pipeline_train_state(
    rng: jax.Array, config: ModelConfig, train_config, n_stages: int
) -> dict:
    from .train import init_train_state

    return init_train_state(
        rng, config, train_config,
        init_fn=partial(init_pipeline_params, n_stages=n_stages),
    )


def place_pipeline_state(mesh: Mesh, state: dict) -> dict:
    from .train import place_state

    return place_state(mesh, state, state_shardings_fn=pipeline_state_shardings)


def make_pipeline_train_step(
    mesh: Mesh,
    config: ModelConfig,
    pcfg: PipelineConfig,
    train_config,
    state: dict,
):
    """Compile one pp x dp optimizer step: grads flow back through the
    ``ppermute`` schedule (reverse-pipeline collectives inserted by AD).

    Delegates to :func:`.train.make_train_step` through its loss/sharding
    seams so there is exactly one optimizer-step implementation.
    """
    from .train import make_train_step

    return make_train_step(
        mesh, config, train_config, state,
        loss=partial(pipeline_loss_fn, config=config, pcfg=pcfg, mesh=mesh,
                     remat=getattr(train_config, "remat", False)),
        state_shardings_fn=pipeline_state_shardings,
        batch_sharding_fn=pipeline_batch_sharding,
    )
