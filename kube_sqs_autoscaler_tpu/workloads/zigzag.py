"""Zig-zag ring attention: load-balanced causal sequence parallelism.

Plain ring attention (:mod:`.ring`) shards the sequence *contiguously*:
device ``d`` owns block ``d``.  Under a causal mask that is imbalanced —
device 0's queries attend to almost nothing, device ``P-1``'s to
everything — and because the ring's collectives are lockstep, every device
pays the worst device's cost each hop: ~half the attention FLOPs are
spent on fully-masked blocks.

The zig-zag layout (used by modern long-context stacks) fixes this. Split
the sequence into ``2P`` chunks; device ``d`` owns chunks ``d`` **and**
``2P-1-d`` (one early, one late).  Now every device's causal workload is
identical, and each ring hop needs only *half* the score matrix:

- hop 0 (own k/v): the full ``2c x 2c`` block with the positional causal
  mask (the only masked matmul);
- k/v from an earlier device (``e < d``): **all** local queries attend to
  the *early* k/v chunk and **none** to the late one — compute
  ``[2c, c]`` unmasked, skip the other half entirely;
- k/v from a later device (``e > d``): only the *late* local queries
  attend, to **both** k/v chunks — compute ``[c, 2c]`` unmasked.

Same online-softmax merge, same one-hop ``ppermute`` ring as
:mod:`.ring`; per-hop compute drops ~2x and is identical on every device,
so the lockstep no longer waits on stragglers.

Layout contract: q/k/v enter (and the output leaves) in **zig-zag order**
— natural position ``zigzag_permutation(S, P)[i]`` lives at permuted slot
``i``.  The loss is computed in permuted order too (permuted positional
indices and permuted shifted targets), so the model's *output* never
needs a cross-shard unpermute.  The *input* permute can live in either
place: :func:`permute_batch` applies it host-side (so the jitted step
sees pre-permuted arrays and does zero permute work on device — the
production path), while :func:`zigzag_loss_fn` accepts natural-order
tokens and permutes inside the program with static index gathers (the
convenience/reference form the tests compare against).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .ring import (
    NEG_INF as _NEG_INF,
    expand_kv,
    online_update,
    ring_rotation,
)


def zigzag_permutation(seq: int, n_devices: int) -> np.ndarray:
    """``perm[i]`` = natural position stored at zig-zag slot ``i``.

    Slots are laid out device-major: device ``d`` gets chunks ``d`` and
    ``2P-1-d`` of size ``seq / (2P)``, concatenated.  Static/host-side
    (NumPy): the permutation is data-independent.
    """
    if seq % (2 * n_devices):
        raise ValueError(
            f"seq={seq} must be divisible by 2*n_devices={2 * n_devices}"
        )
    chunk = seq // (2 * n_devices)
    out = []
    for d in range(n_devices):
        out.append(np.arange(d * chunk, (d + 1) * chunk))
        hi = 2 * n_devices - 1 - d
        out.append(np.arange(hi * chunk, (hi + 1) * chunk))
    return np.concatenate(out)


def inverse_permutation(perm: np.ndarray) -> np.ndarray:
    """Inverse of a permutation (argsort is exactly that for a bijection)."""
    return np.argsort(perm)


def _zigzag_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """Per-device body. q: ``[B, H, 2c, D]`` in zig-zag order; k/v may
    carry compact GQA heads (broadcast at the compute site via
    :func:`.ring.expand_kv`, rotated compact)."""
    seq_local = q.shape[2]
    chunk = seq_local // 2
    head_dim = q.shape[-1]
    groups = q.shape[1] // k.shape[1]
    my_index = jax.lax.axis_index(axis_name)

    scale = 1.0 / head_dim**0.5
    local = jnp.arange(chunk)
    # global positions of this device's two chunks (low: d, high: 2P-1-d)
    pos_lo = my_index * chunk + local
    pos_hi = (2 * axis_size - 1 - my_index) * chunk + local
    q_positions = jnp.concatenate([pos_lo, pos_hi])

    # fp32 statistics; q/k stay in storage dtype for the score matmuls
    # (bf16 MXU fast path with fp32 accumulation, the dense-path and
    # flash-kernel convention) and the scale folds in afterwards in fp32
    q32 = q.astype(jnp.float32)
    o0 = q32 * 0.0
    l0 = q32[..., :1] * 0.0
    m0 = q32[..., :1] * 0.0 + _NEG_INF

    def scores_for(q_part, k_part):
        return jnp.einsum(
            "bhqd,bhkd->bhqk",
            q_part,
            expand_kv(k_part, groups),
            preferred_element_type=jnp.float32,
        ) * scale

    def step(carry, step_index):
        o, l, m, k_blk, v_blk = carry
        kv_index = (my_index - step_index) % axis_size

        def diag(o, l, m):
            # own k/v: the only masked block (both causal diagonals);
            # k positions == q_positions since kv_index == my_index here
            scores = scores_for(q, k_blk)
            causal = q_positions[:, None] >= q_positions[None, :]
            return online_update(
                o, l, m, jnp.where(causal, scores, _NEG_INF),
                expand_kv(v_blk, groups),
            )

        def from_earlier(o, l, m):
            # e < d: every local q attends the early chunk, none the late
            # one — half the matmul, no mask
            scores = scores_for(q, k_blk[:, :, :chunk])
            return online_update(
                o, l, m, scores, expand_kv(v_blk[:, :, :chunk], groups)
            )

        def from_later(o, l, m):
            # e > d: only the late local queries attend, to both chunks —
            # half the matmul, no mask; early-q accumulators pass through
            scores = scores_for(q[:, :, chunk:], k_blk)
            o_hi, l_hi, m_hi = online_update(
                o[:, :, chunk:], l[:, :, chunk:], m[:, :, chunk:],
                scores, expand_kv(v_blk, groups),
            )
            return (
                jnp.concatenate([o[:, :, :chunk], o_hi], axis=2),
                jnp.concatenate([l[:, :, :chunk], l_hi], axis=2),
                jnp.concatenate([m[:, :, :chunk], m_hi], axis=2),
            )

        o, l, m = jax.lax.cond(
            kv_index == my_index,
            diag,
            lambda o, l, m: jax.lax.cond(
                kv_index < my_index, from_earlier, from_later, o, l, m
            ),
            o, l, m,
        )

        ring = ring_rotation(axis_size)
        k_next = jax.lax.ppermute(k_blk, axis_name, ring)
        v_next = jax.lax.ppermute(v_blk, axis_name, ring)
        return (o, l, m, k_next, v_next), None

    (o, l, _, _, _), _ = jax.lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(axis_size)
    )
    return (o / l).astype(q.dtype)


def _zigzag_attention_kernel_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-device zig-zag body with the Pallas flash kernel as the local
    op (the kernel counterpart of :func:`_zigzag_attention_local`).

    Same three hop shapes, each now a rectangular kernel call instead of
    a materialized score block:

    - diagonal: lo rows are plain causal over the lo chunk; hi rows run
      one call over BOTH chunks with ``q_shift=chunk`` (full over lo,
      causal within hi — exactly the zig-zag diagonal mask);
    - from earlier: one unmasked ``[2c, c]`` call against the early
      chunk;
    - from later: one unmasked ``[c, 2c]`` call for the hi rows only (lo
      rows contribute a zero-weight partial).

    Cross-hop combining is the ``(out, lse)`` merge
    (:func:`.flash.merge_attention_partials`); GQA-compact k/v feed the
    kernel directly and rotate compact.
    """
    from .flash import (
        MERGE_NEG_INF,
        flash_attention_lse,
        merge_attention_partials,
    )

    seq_local = q.shape[2]
    chunk = seq_local // 2
    my_index = jax.lax.axis_index(axis_name)

    acc0 = q.astype(jnp.float32) * 0.0
    lse0 = q[..., 0].astype(jnp.float32) * 0.0 + MERGE_NEG_INF

    def step(carry, step_index):
        acc, acc_lse, k_blk, v_blk = carry
        kv_index = (my_index - step_index) % axis_size

        def diag(k_blk, v_blk):
            out_lo, lse_lo = flash_attention_lse(
                q[:, :, :chunk], k_blk[:, :, :chunk], v_blk[:, :, :chunk],
                causal=True, interpret=interpret,
            )
            out_hi, lse_hi = flash_attention_lse(
                q[:, :, chunk:], k_blk, v_blk, causal=True, q_shift=chunk,
                interpret=interpret,
            )
            return (
                jnp.concatenate([out_lo, out_hi], axis=2),
                jnp.concatenate([lse_lo, lse_hi], axis=2),
            )

        def from_earlier(k_blk, v_blk):
            return flash_attention_lse(
                q, k_blk[:, :, :chunk], v_blk[:, :, :chunk], causal=False,
                interpret=interpret,
            )

        def from_later(k_blk, v_blk):
            out_hi, lse_hi = flash_attention_lse(
                q[:, :, chunk:], k_blk, v_blk, causal=False,
                interpret=interpret,
            )
            return (
                jnp.concatenate(
                    [jnp.zeros_like(q[:, :, :chunk]), out_hi], axis=2
                ),
                jnp.concatenate(
                    [jnp.full_like(lse_hi, MERGE_NEG_INF), lse_hi], axis=2
                ),
            )

        out_h, lse_h = jax.lax.cond(
            kv_index == my_index,
            diag,
            lambda k_blk, v_blk: jax.lax.cond(
                kv_index < my_index, from_earlier, from_later, k_blk, v_blk
            ),
            k_blk, v_blk,
        )
        acc, acc_lse = merge_attention_partials(acc, acc_lse, out_h, lse_h)

        ring = ring_rotation(axis_size)
        k_next = jax.lax.ppermute(k_blk, axis_name, ring)
        v_next = jax.lax.ppermute(v_blk, axis_name, ring)
        return (acc, acc_lse, k_next, v_next), None

    (acc, _, _, _), _ = jax.lax.scan(
        step, (acc0, lse0, k, v), jnp.arange(axis_size)
    )
    return acc.astype(q.dtype)


def make_zigzag_ring_attention(
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    data_axis: str = "data",
    model_axis: str = "model",
    use_kernel: bool | None = None,
    interpret: bool | None = None,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """Attention fn over ``mesh[seq_axis]`` for **zig-zag-ordered** inputs.

    Same signature/sharding as :func:`.ring.make_ring_attention`
    (``[B, H, S, D]``; batch over ``data_axis``, heads over
    ``model_axis``, sequence over ``seq_axis``) but the sequence axis must
    carry :func:`zigzag_permutation` order — which makes the contiguous
    shard on device ``d`` exactly its two zig-zag chunks.

    ``use_kernel``/``interpret``: same local-op selection as
    :func:`.ring.make_ring_attention` (Pallas flash hops on TPU, einsum
    reference elsewhere; tests force the kernel in interpret mode).
    """
    axis_size = mesh.shape[seq_axis]
    if axis_size < 2:
        raise ValueError("zig-zag needs a nontrivial seq axis (P >= 2)")
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    spec = P(data_axis, model_axis, seq_axis, None)
    sharded_kernel = jax.shard_map(
        partial(
            _zigzag_attention_kernel_local, axis_name=seq_axis,
            axis_size=axis_size, interpret=interpret,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    sharded_einsum = jax.shard_map(
        partial(
            _zigzag_attention_local, axis_name=seq_axis, axis_size=axis_size
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )

    def fn(q, k, v):
        # kernel only when both hop shapes tile (the diag lo call runs at
        # chunk = S_local/2; the hi/later calls at S_local) — else the
        # einsum body, rather than a trace-time block error
        from .flash import tiles_cleanly

        s_local = q.shape[2] // axis_size
        if (
            use_kernel
            and s_local % 2 == 0
            and tiles_cleanly(s_local)
            and tiles_cleanly(s_local // 2)
        ):
            return sharded_kernel(q, k, v)
        return sharded_einsum(q, k, v)

    fn._zigzag = True  # layout marker checked by the zig-zag losses
    # GQA-native: compact k/v rotate as-is (see ring.expand_kv)
    fn.gqa_native = True
    return fn


def permute_batch(tokens, n_devices: int):
    """Host-side zig-zag preparation of one natural-order token batch.

    Returns ``(tokens_zz, targets_zz, valid)`` — the permuted inputs, the
    permuted shifted targets (target at slot ``i`` is the token at natural
    position ``perm[i] + 1``), and the validity mask (the slot holding the
    last natural position has no target).  Feed these to
    :func:`zigzag_loss_from_permuted` so the jitted step does **zero**
    permute work on device; do this in the input pipeline of a real
    sequence-sharded run.
    """
    tokens = np.asarray(tokens)
    seq = tokens.shape[1]
    perm = zigzag_permutation(seq, n_devices)
    next_tokens = np.concatenate(
        [tokens[:, 1:], np.zeros_like(tokens[:, :1])], axis=1
    )
    return tokens[:, perm], next_tokens[:, perm], (perm < seq - 1)[None, :]


def _require_zigzag_attention(attention_fn, mesh: Mesh):
    """The zig-zag losses only make sense with zig-zag-layout attention.

    A natural-order attention fn (plain ring, dense causal) on permuted
    inputs computes a *wrong but finite* loss — e.g. wiring
    ``partial(zigzag_loss_fn, ...)`` through ``make_train_step``'s loss
    seam would silently inject the seam's ring attention.  Fail loudly
    instead.
    """
    if attention_fn is None:
        return make_zigzag_ring_attention(mesh)
    if not getattr(attention_fn, "_zigzag", False):
        raise ValueError(
            "zig-zag loss requires attention built by "
            "make_zigzag_ring_attention (inputs are in zig-zag order; a "
            "natural-order attention fn would apply the wrong causal mask)"
        )
    return attention_fn


def zigzag_loss_from_permuted(
    params,
    tokens_zz: jax.Array,
    targets_zz: jax.Array,
    valid: jax.Array,
    config,
    mesh: Mesh,
    attention_fn=None,
    remat: bool = False,
    forward_fn=None,
):
    """LM loss on a batch already in zig-zag order (see
    :func:`permute_batch`): forward runs with permuted positional indices,
    the loss masks the target-less slot — no permute happens on device.

    ``forward_fn(params, tokens, config, attention_fn, positions=...,
    remat=...)`` defaults to the gpt family's :func:`.model.forward`; the
    llama family passes :func:`.llama.llama_forward` (RoPE rotates by the
    permuted positions; the zig-zag attention is GQA-native, so compact
    k/v rotate as-is).
    """
    from .model import forward

    seq = tokens_zz.shape[1]
    perm = jnp.asarray(zigzag_permutation(seq, mesh.shape["seq"]))
    attend = _require_zigzag_attention(attention_fn, mesh)

    logits = (forward_fn or forward)(
        params, tokens_zz, config, attend, positions=perm, remat=remat
    )
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, targets_zz[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * valid) / (tokens_zz.shape[0] * (seq - 1))


def zigzag_loss_fn(
    params,
    tokens: jax.Array,
    config,
    mesh: Mesh,
    attention_fn=None,
    remat: bool = False,
    forward_fn=None,
):
    """Convenience/reference form: **natural-order** tokens in, permutes
    inside the traced program with static index gathers.

    On a seq-sharded mesh those gathers cross shards once per step (XLA
    lowers them to collective permutes of the int32 token array — cheap
    next to the model compute, but not free); the production input
    pipeline should pre-permute with :func:`permute_batch` and call
    :func:`zigzag_loss_from_permuted` instead.  Tests pin this form and
    the pre-permuted form to the natural-order :func:`.train.loss_fn`.
    """
    seq = tokens.shape[1]
    perm = jnp.asarray(zigzag_permutation(seq, mesh.shape["seq"]))
    tokens_zz = tokens[:, perm]
    next_tokens = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    targets_zz = next_tokens[:, perm]
    valid = (perm < seq - 1)[None, :]
    return zigzag_loss_from_permuted(
        params, tokens_zz, targets_zz, valid, config, mesh, attention_fn,
        remat=remat, forward_fn=forward_fn,
    )


def make_zigzag_loss(mesh: Mesh, config, remat: bool = False,
                     forward_fn=None, forward_factory=None):
    """The zig-zag objective in the ``make_train_step`` loss-seam shape:
    builds the zig-zag ring attention once and returns
    ``loss(params, tokens, attention_fn=None)``.  The seam's
    ``attention_fn`` (plain ring) is deliberately discarded — zig-zag
    inputs need the zig-zag schedule built here.  The one construction
    site for every consumer (the train step below, the LoRA trainer
    branch, the held-out eval, the MoE composition), so the
    schedule/forward selection cannot drift between them.

    ``forward_fn`` selects the family (see
    :func:`zigzag_loss_from_permuted`).  ``forward_factory`` (mutually
    exclusive) serves consumers whose forward collects per-trace state:
    called once per loss evaluation, it returns ``(forward_fn,
    finalize)`` where ``finalize(nll) -> loss`` folds the collected
    state into the objective — the MoE aux term rides this."""
    if forward_fn is not None and forward_factory is not None:
        raise ValueError("pass forward_fn or forward_factory, not both")
    if getattr(config, "sliding_window", None) is not None:
        # the permuted zig-zag blocks have no banded form; silently
        # training a Mistral-style config full-causal would be wrong —
        # plain (unpermuted) ring attention DOES support the window
        raise ValueError(
            "sliding_window does not compose with the zig-zag schedule; "
            "use plain sequence parallelism (windowed ring attention) "
            "or a (data, model) mesh"
        )
    attend = make_zigzag_ring_attention(mesh)

    def loss(params, tokens, attention_fn=None):  # seam signature
        if forward_factory is not None:
            fwd, finalize = forward_factory()
        else:
            fwd, finalize = forward_fn, None
        nll = zigzag_loss_fn(
            params, tokens, config, mesh, attend,
            remat=remat, forward_fn=fwd,
        )
        return finalize(nll) if finalize is not None else nll

    return loss


def make_zigzag_train_step(mesh: Mesh, config, train_config, state,
                           forward_fn=None):
    """Compile a dp x sp x tp train step whose sequence parallelism runs
    the balanced zig-zag schedule instead of plain ring attention.

    Takes **natural-order** tokens (the in-program permute documented on
    :func:`zigzag_loss_fn`).  Delegates to :func:`.train.make_train_step`
    through its ``loss`` seam; an input pipeline that pre-permutes should
    jit :func:`zigzag_loss_from_permuted` directly instead.
    ``forward_fn`` selects the family (see
    :func:`zigzag_loss_from_permuted`).
    """
    from .train import make_train_step

    loss = make_zigzag_loss(mesh, config, remat=train_config.remat,
                            forward_fn=forward_fn)
    return make_train_step(mesh, config, train_config, state, loss=loss)
