"""Multi-tenant fair admission: millions of users are not one queue.

The serving plane up to PR 9 admits strictly in arrival order — one
FIFO, one global broadcast prefix, and a flooding tenant starves every
other tenant's TTFT while it drains.  This module adds the three pieces
that turn fairness from a tax into a throughput optimization
(MQFQ-Sticky, PAPERS.md):

- :class:`TenancyConfig` — the per-tenant policy surface (names,
  weights, TTFT SLOs, prefix-pool size, stickiness knobs), validated at
  construction so the worker's never-dies loop can't trip on bad knobs
  mid-cycle;
- :class:`DeficitRoundRobin` + :class:`FairAdmission` — per-tenant
  sub-queues feeding the continuous batcher through deficit-round-robin
  admission.  Each refill cycle's batch is *picked* by deficit counters
  instead of arrival order, then still prefills as ONE ``[M, P]`` insert
  (the scheduler is pure host bookkeeping — zero new device dispatches
  or host syncs; the PR 7 ``insert_dispatches``/``host_transfers``
  counters pin it).  DRR's invariants are the classic ones: work
  conservation (no idle slot while any tenant queue is non-empty),
  bounded deficit (an empty queue resets its counter, so no tenant
  banks unbounded credit and none starves beyond a weight-proportional
  delay), deterministic order (no randomness anywhere — a fixed request
  stream admits identically every run);
- :class:`PrefixPool` — N resident prefix-cache entries with LRU
  eviction, generalizing the single ``--prefix-ids`` broadcast prefix.
  A tenant's shared prompt prefix is prefilled ONCE at install
  (one forward), then every request that reuses it gathers the cached
  KV on device inside the admission insert — a pool hit never
  re-prefills the shared region.  On the sharded plane each shard owns
  its own pool partition (its HBM, its residency), which is exactly why
  sticky routing (:meth:`~.shard_plane.ShardedBatcher.route_prefixed`)
  pays: a tenant kept on its home shard keeps its prefix hits, while
  freest-first scatter re-installs (and LRU-thrashes) the same prefix on
  every shard it touches.

Everything here is deliberately queue-shaped, not device-shaped: the
scheduler and the pool's LRU are plain Python; only the pool's KV
buffers and the install splice live on device (one tiny jit at install
time, never on the per-cycle path).  With ``tenancy=None`` nothing in
this module is even imported by the hot path — the engine keeps today's
reference behavior byte for byte.
"""

from __future__ import annotations

import time
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import cached_property
from typing import Any


@dataclass(frozen=True)
class _PoolEvent:
    """One prefix-pool decision (install/evict), timestamped — shaped
    like a :class:`~..fleet.pool.FleetEvent` so
    :func:`~..obs.trace.instant_trace_events` exports it onto the same
    Chrome-trace timeline as the fleet's supervisor decisions."""

    name: str
    t: float
    args: dict = field(default_factory=dict)


# the smallest admissible weight/quantum — AND their product: one DRR
# round earns quantum*weight of deficit, so admitting one request costs
# ~1/(quantum*weight) scheduler rounds; flooring the product (validated
# in TenancyConfig) bounds that at 100 rounds
MIN_WEIGHT = 0.01


@dataclass(frozen=True)
class TenancyConfig:
    """The multi-tenant admission policy.

    ``tenants`` names the KNOWN tenants (weights/SLOs align by index);
    unknown tenant labels arriving on the queue are still served, at
    weight 1.0 — fairness must not require pre-registration, only
    priority does.  ``weights`` empty = all 1.0.

    ``prefix_pool`` > 0 enables the per-tenant prefix-cache pool with
    that many resident entries PER SHARD; ``prefix_len`` is the pool's
    static prefix bucket (every pooled prefix must be exactly this many
    tokens — the compiled insert closes over it; the worker defaults it
    to ``seq_len``).  ``sticky`` toggles affinity-first routing on the
    sharded plane (off = today's freest-first, the FIFO-routing
    baseline the bench compares against); ``sticky_imbalance`` is how
    many free slots the freest shard may lead the home shard by before
    stickiness yields (0 = auto: the shard's slot count, i.e. yield
    only when the home shard is full).  ``fair`` toggles the DRR pick
    (off = arrival order through the same staging machinery — the FIFO
    admission baseline).  ``ttft_slo_s`` aligns per-tenant TTFT SLOs
    with ``tenants`` (empty = no SLO); the bench scores
    time-over-TTFT-SLO per tenant from it.
    """

    tenants: tuple[str, ...]
    weights: tuple[float, ...] = ()
    prefix_pool: int = 0
    prefix_len: int = 0
    sticky: bool = True
    sticky_imbalance: int = 0
    fair: bool = True
    quantum: float = 1.0
    ttft_slo_s: tuple[float, ...] = ()

    def __post_init__(self) -> None:
        if not self.tenants:
            raise ValueError("tenancy needs at least one tenant name")
        if len(set(self.tenants)) != len(self.tenants):
            raise ValueError(f"duplicate tenant names in {self.tenants}")
        for name in self.tenants:
            if not name or not isinstance(name, str):
                raise ValueError(f"tenant names must be non-empty strings "
                                 f"(got {name!r})")
        if self.weights and len(self.weights) != len(self.tenants):
            raise ValueError(
                f"{len(self.weights)} weight(s) for {len(self.tenants)} "
                "tenant(s) — counts must match"
            )
        for w in self.weights:
            if not w >= MIN_WEIGHT:
                # a round earns quantum*weight of deficit: a vanishing
                # weight makes the DRR spin ~1/(quantum*weight) full
                # rounds per admitted request inside the refill loop —
                # a legal-looking config must not be able to stall the
                # serving worker, so tiny weights are a usage error
                raise ValueError(
                    f"tenant weights must be >= {MIN_WEIGHT} (got {w}; "
                    "express shares by raising the other weights "
                    "instead of vanishing this one)"
                )
        if self.prefix_pool < 0:
            raise ValueError(
                f"prefix_pool={self.prefix_pool} must be >= 0 (0 = off)"
            )
        if self.prefix_len < 0:
            raise ValueError(
                f"prefix_len={self.prefix_len} must be >= 0"
            )
        if self.sticky_imbalance < 0:
            raise ValueError(
                f"sticky_imbalance={self.sticky_imbalance} must be >= 0 "
                "(0 = auto)"
            )
        if not self.quantum >= MIN_WEIGHT:
            raise ValueError(
                f"quantum={self.quantum} must be >= {MIN_WEIGHT} "
                "(a vanishing quantum spins the scheduler)"
            )
        if self.weights and self.quantum * min(self.weights) < MIN_WEIGHT:
            # the two floors compose: a round earns quantum*weight, so
            # quantum=0.01 with weight=0.01 would still cost ~10,000
            # rounds per admitted request — the PRODUCT is what bounds
            # the scheduler's work, so the product gets the floor
            raise ValueError(
                f"quantum * min(weight) = "
                f"{self.quantum * min(self.weights):g} must be >= "
                f"{MIN_WEIGHT} (each DRR round earns quantum*weight of "
                "deficit; a vanishing product spins the refill loop)"
            )
        if self.ttft_slo_s and len(self.ttft_slo_s) != len(self.tenants):
            raise ValueError(
                f"{len(self.ttft_slo_s)} TTFT SLO(s) for "
                f"{len(self.tenants)} tenant(s) — counts must match"
            )
        for slo in self.ttft_slo_s:
            if slo < 0:
                raise ValueError(f"TTFT SLOs must be >= 0 (got {slo})")

    # weight_of runs once per tenant per DRR round on the refill hot
    # path: dict lookups, built once (cached_property assigns through
    # the instance __dict__, which frozen dataclasses allow)
    @cached_property
    def _weight_by_tenant(self) -> "dict[str, float]":
        return dict(zip(self.tenants, self.weights))

    @cached_property
    def _slo_by_tenant(self) -> "dict[str, float]":
        return dict(zip(self.tenants, self.ttft_slo_s))

    def weight_of(self, tenant: str) -> float:
        """Configured weight, or 1.0 for tenants not pre-registered."""
        return self._weight_by_tenant.get(tenant, 1.0)

    def slo_of(self, tenant: str) -> float:
        """Configured TTFT SLO seconds, or 0.0 (= none)."""
        return self._slo_by_tenant.get(tenant, 0.0)


class DeficitRoundRobin:
    """Deficit-round-robin over per-tenant sub-queues.

    The classic Shreedhar/Varghese scheduler with per-request cost 1:
    each round visits tenants in first-seen order starting at a rotating
    cursor; a visited non-empty tenant earns ``quantum * weight`` of
    deficit and pops requests while its deficit covers them.  An
    emptied queue resets its deficit to 0 — the bounded-deficit
    invariant (credit never banks while there is nothing to spend it
    on), which also bounds any tenant's wait at a weight-proportional
    number of rounds.  ``pick`` keeps cycling rounds until ``k``
    requests are picked or every queue is empty — the work-conservation
    invariant (a free slot is never left idle while any tenant has a
    staged request).  No randomness anywhere: a fixed arrival stream
    picks identically every run (the determinism invariant all three
    are property-tested in ``tests/test_admission.py``).
    """

    def __init__(self, weight_of=None, quantum: float = 1.0,
                 keep=()) -> None:
        if not quantum > 0:
            raise ValueError(f"quantum={quantum} must be > 0")
        self._weight_of = weight_of or (lambda tenant: 1.0)
        self.quantum = quantum
        # tenants whose (empty) sub-queues stay registered forever —
        # the CONFIGURED tenants.  Unknown labels arrive from untrusted
        # message bodies, so their entries are pruned the moment they
        # drain: scheduler state stays bounded by keep + staging depth
        # no matter how many distinct labels an adversary invents.
        self._keep = frozenset(keep)
        self._queues: dict[str, deque] = {}
        self._deficit: dict[str, float] = {}
        self._order: list[str] = []  # first-seen tenant order
        self._cursor = 0
        self._ordinal = 0  # arrival stamp (the fair=False pick order)

    def push(self, tenant: str, item: Any) -> None:
        queue = self._queues.get(tenant)
        if queue is None:
            queue = self._queues[tenant] = deque()
            self._deficit[tenant] = 0.0
            self._order.append(tenant)
        queue.append((self._ordinal, item))
        self._ordinal += 1

    def depth(self, tenant: str) -> int:
        queue = self._queues.get(tenant)
        return len(queue) if queue is not None else 0

    def depths(self) -> dict[str, int]:
        """Per-tenant staged depth: every configured tenant (a drained
        one's gauge reads 0 instead of disappearing) plus whatever
        unknown labels are currently staged — drained unknowns are
        pruned, so the gauge cardinality stays bounded."""
        return {t: len(q) for t, q in self._queues.items()}

    @property
    def staged(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def deficit(self, tenant: str) -> float:
        """Introspection for the property tests."""
        return self._deficit.get(tenant, 0.0)

    def _prune(self) -> None:
        """Drop drained non-configured tenants (their deficit is already
        0 by the bounded-deficit reset, so removal changes no future
        pick; a re-arrival re-registers at the order's tail exactly like
        a first arrival).  The cursor is remapped to the same next-round
        tenant, so pruning never skips anyone's turn."""
        dead = {
            t for t in self._order
            if not self._queues[t] and t not in self._keep
        }
        if not dead:
            return
        n = len(self._order)
        survivors = [t for t in self._order if t not in dead]
        cursor = 0
        for i in range(n):
            tenant = self._order[(self._cursor + i) % n]
            if tenant not in dead:
                cursor = survivors.index(tenant)
                break
        for tenant in dead:
            del self._queues[tenant]
            del self._deficit[tenant]
        self._order = survivors
        self._cursor = cursor

    def pick(self, k: int, *, fair: bool = True) -> list[tuple[str, Any]]:
        """Pop up to ``k`` ``(tenant, item)`` pairs by deficit order.

        ``fair=False`` degrades to global arrival order across the same
        sub-queues (the FIFO-admission baseline the bench contrasts) —
        same staging, same bounds, no deficit accounting.
        """
        try:
            return self._pick(k, fair)
        finally:
            self._prune()

    def _pick(self, k: int, fair: bool) -> list[tuple[str, Any]]:
        out: list[tuple[str, Any]] = []
        if k <= 0 or not self._order:
            return out
        if not fair:
            # arrival order: items carry a monotone stage ordinal
            while len(out) < k:
                oldest, best = None, None
                for tenant in self._order:
                    queue = self._queues[tenant]
                    if queue and (best is None or queue[0][0] < best):
                        best, oldest = queue[0][0], tenant
                if oldest is None:
                    break
                out.append((oldest, self._queues[oldest].popleft()[1]))
            return out
        n = len(self._order)
        while len(out) < k and any(
            self._queues[t] for t in self._order
        ):
            for i in range(n):
                tenant = self._order[(self._cursor + i) % n]
                queue = self._queues[tenant]
                if not queue:
                    # bounded deficit: an empty queue banks nothing
                    self._deficit[tenant] = 0.0
                    continue
                if self._deficit[tenant] < 1.0:
                    # earn once per serviced round: a visit that merely
                    # RESUMES spending credit left over from a
                    # k-truncated pick must not earn again, or deficits
                    # grow without bound and weighted shares collapse
                    # toward equal whenever the per-refill pick is
                    # smaller than a tenant's round quantum
                    self._deficit[tenant] += (
                        self.quantum * self._weight_of(tenant)
                    )
                while queue and self._deficit[tenant] >= 1.0 \
                        and len(out) < k:
                    out.append((tenant, queue.popleft()[1]))
                    self._deficit[tenant] -= 1.0
                if not queue:
                    self._deficit[tenant] = 0.0
                if len(out) >= k:
                    # the rotation that keeps a small k from always
                    # favoring the first-seen tenant: resume the NEXT
                    # pick one past the tenant that filled this one —
                    # UNLESS its turn is unfinished (backlog left and
                    # deficit still ≥ 1): then the cursor stays put so
                    # the next pick resumes the same turn, or a
                    # high-weight tenant would spend each round's
                    # credit at the same one-visit-per-pick rate as
                    # weight-1 tenants and shares would collapse
                    unfinished = bool(queue) and \
                        self._deficit[tenant] >= 1.0
                    self._cursor = (
                        self._cursor + i + (0 if unfinished else 1)
                    ) % n
                    return out
        return out


class FairAdmission:
    """The worker-side staging area between the queue and the batcher.

    Receives go into per-tenant sub-queues (bounded — the queue itself
    is the backlog; staging is only the one-refill lookahead DRR needs
    to see across tenants), and each refill cycle's admission batch is
    picked by :class:`DeficitRoundRobin`.  Per-tenant staging is capped
    at ``per_tenant_limit`` so one flooding tenant cannot monopolize the
    lookahead window either: overflow messages are *handed back* to the
    queue by the worker (``change_message_visibility(0)``) instead of
    staged — at-least-once backpressure, never a drop.
    """

    def __init__(
        self,
        tenancy: TenancyConfig,
        *,
        per_tenant_limit: int,
        total_limit: int,
    ) -> None:
        if per_tenant_limit < 1 or total_limit < 1:
            raise ValueError("staging limits must be >= 1")
        self.tenancy = tenancy
        self.per_tenant_limit = per_tenant_limit
        self.total_limit = total_limit
        self.drr = DeficitRoundRobin(
            weight_of=tenancy.weight_of, quantum=tenancy.quantum,
            keep=tenancy.tenants,
        )
        # messages actually handed back to the queue on a staging-cap
        # hit — the CALLER increments it when its
        # change_message_visibility(0) went through, so the counter
        # never claims a backpressure event that did not happen
        self.overflow_total = 0

    @property
    def staged(self) -> int:
        return self.drr.staged

    @property
    def room(self) -> int:
        """How many more messages staging can hold right now."""
        return max(0, self.total_limit - self.staged)

    def stage(self, tenant: str, item: Any) -> bool:
        """Stage one parsed request; False = per-tenant/total cap hit
        (the caller hands the message back to the queue and counts it
        in :attr:`overflow_total` — only when the hand-back actually
        happened)."""
        if (self.drr.depth(tenant) >= self.per_tenant_limit
                or self.staged >= self.total_limit):
            return False
        self.drr.push(tenant, item)
        return True

    def pick(self, k: int) -> list[tuple[str, Any]]:
        return self.drr.pick(k, fair=self.tenancy.fair)

    def depths(self) -> dict[str, int]:
        depths = {t: 0 for t in self.tenancy.tenants}
        depths.update(self.drr.depths())
        return depths


def prefix_pool_key(tenant: str, prefix_ids) -> tuple[str, int]:
    """The pool's entry key: (tenant, content checksum).  A tenant that
    rotates its system prompt gets a fresh entry instead of silently
    decoding against the stale cached KV; crc32 keeps the key
    deterministic across runs (Python's ``hash`` is salted)."""
    import numpy as np

    ids = np.asarray(prefix_ids, np.int32).reshape(-1)
    return (tenant, zlib.crc32(ids.tobytes()))


class PrefixPool:
    """N resident prefix-cache entries per shard, LRU-evicted.

    The device side is one stacked cache buffer per layer entry —
    ``[shards * entries, heads, max_seq_len, head_dim]`` rows in the
    batcher's exact cache layout (bf16 k/v or int8 codes+scales, gpt or
    llama) — so the admission insert can *gather* each request's prefix
    KV by entry index inside its one compiled call.  The host side is
    one ``OrderedDict`` per shard mapping entry key -> local pool slot:
    a **hit** touches LRU and returns the global row (no forward, no
    transfer — the gather happens inside the insert that was running
    anyway); a **miss** prefills the prefix ONCE
    (:func:`~.decode.prefill_prefix` or the family/layout variant) and
    splices it into the victim's pool row with one small jitted write —
    an occasional amortized event, never on the per-cycle path.

    What the pool does NOT share across tenants: entries are keyed by
    (tenant, prefix checksum), so two tenants with byte-identical
    prefixes still get separate entries — residency is a per-tenant
    resource (one tenant's eviction pressure must not silently revoke
    another's cache hit), and nothing decoded from one tenant's prefix
    entry is ever visible to another tenant's requests.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        *,
        entries: int,
        prefix_len: int,
        shards: int = 1,
        family: str = "gpt",
        quantized_kv: bool = False,
    ) -> None:
        if entries < 1:
            raise ValueError(f"entries={entries} must be >= 1")
        if prefix_len < 1:
            raise ValueError(f"prefix_len={prefix_len} must be >= 1")
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        if prefix_len > config.max_seq_len:
            raise ValueError(
                f"prefix_len={prefix_len} exceeds max_seq_len="
                f"{config.max_seq_len}"
            )
        self.params = params
        self.config = config
        self.entries = entries
        self.prefix_len = prefix_len
        self.shards = shards
        self.family = family
        self.quantized_kv = quantized_kv
        # the stacked device rows, in the batcher's cache layout
        if quantized_kv:
            from .decode import init_quantized_cache

            cache = init_quantized_cache(
                config, shards * entries,
                kv_heads=(config.n_kv_heads if family == "llama"
                          else None),
            )
        elif family == "llama":
            from .llama import init_llama_cache

            cache = init_llama_cache(config, shards * entries)
        else:
            from .decode import init_cache

            cache = init_cache(config, shards * entries)
        self.layers = cache["layers"]
        # key -> local slot, per shard, in LRU order (oldest first)
        self._lru: list[OrderedDict] = [
            OrderedDict() for _ in range(shards)
        ]
        self.hits = 0
        self.misses = 0
        self.installs = 0
        self.evictions = 0
        self.events: deque[_PoolEvent] = deque(maxlen=1024)
        self._write_jit = None

    def resident(self, shard: int, key) -> bool:
        """Residency probe for the sticky router — never touches LRU."""
        return key in self._lru[shard]

    def resident_keys(self, shard: int) -> list:
        return list(self._lru[shard])

    def _prefill_entry(self, prefix_ids):
        """The ONE-TIME prefix prefill (the cost a pool hit amortizes
        away), through the family/layout prefill-prefix variant."""
        if self.quantized_kv:
            if self.family == "llama":
                from .llama import (
                    llama_quantized_prefill_prefix as build,
                )
            else:
                from .decode import quantized_prefill_prefix as build
        elif self.family == "llama":
            from .llama import llama_prefill_prefix as build
        else:
            from .decode import prefill_prefix as build
        return build(self.params, prefix_ids, self.config)

    def _write_entry(self, entry_cache, index: int) -> None:
        """Splice a batch-1 prefix cache into pool row ``index`` — one
        small jitted program (pool buffers donated, so the stacked rows
        roll in place install after install)."""
        import jax
        import jax.numpy as jnp

        if self._write_jit is None:
            def write(pool_layers, entry_layers, idx):
                out = []
                for pool_layer, entry in zip(pool_layers, entry_layers):
                    row = {}
                    for name, buf in pool_layer.items():
                        piece = entry[name]
                        start = (idx,) + (
                            jnp.zeros((), jnp.int32),
                        ) * (buf.ndim - 1)
                        row[name] = jax.lax.dynamic_update_slice(
                            buf, piece, start
                        )
                    out.append(row)
                return out

            self._write_jit = jax.jit(write, donate_argnums=(0,))
        self.layers = self._write_jit(
            self.layers, entry_cache["layers"],
            jnp.asarray(index, jnp.int32),
        )

    def acquire(self, shard: int, key, prefix_ids) -> int:
        """Return the GLOBAL pool row holding ``key``'s prefix KV on
        ``shard``, installing (and LRU-evicting) on a miss.  The
        returned index feeds the admission insert's device-side
        gather."""
        import numpy as np

        if not 0 <= shard < self.shards:
            raise ValueError(f"shard {shard} out of range "
                             f"[0, {self.shards})")
        lru = self._lru[shard]
        slot = lru.get(key)
        if slot is not None:
            lru.move_to_end(key)
            self.hits += 1
            return shard * self.entries + slot
        self.misses += 1
        ids = np.asarray(prefix_ids, np.int32).reshape(-1)
        if ids.size != self.prefix_len:
            raise ValueError(
                f"pooled prefixes are a static {self.prefix_len}-token "
                f"bucket; got {ids.size} tokens (the worker prepends "
                "off-bucket prefixes to the prompt instead)"
            )
        if len(lru) >= self.entries:
            victim, slot = lru.popitem(last=False)
            self.evictions += 1
            self.events.append(_PoolEvent(
                "prefix-evict", time.perf_counter(),
                {"shard": shard, "tenant": victim[0], "slot": slot},
            ))
        else:
            slot = len(lru)
        entry = self._prefill_entry(ids)
        self._write_entry(entry, shard * self.entries + slot)
        lru[key] = slot
        self.installs += 1
        self.events.append(_PoolEvent(
            "prefix-install", time.perf_counter(),
            {"shard": shard, "tenant": key[0], "slot": slot},
        ))
        return shard * self.entries + slot

    def trace_events(self, time_origin: float | None = None) -> list[dict]:
        """The pool's install/evict decisions as Chrome-trace instant
        events (``prefix-*`` names land in their own ``"prefix"``
        category; merge into a tick trace via
        ``to_chrome_trace(..., extra_events=...)`` like the fleet's)."""
        from ..obs.trace import instant_trace_events

        return instant_trace_events(self.events, time_origin)

    def stats(self) -> dict:
        return {
            "entries_per_shard": self.entries,
            "shards": self.shards,
            "prefix_len": self.prefix_len,
            "hits": self.hits,
            "misses": self.misses,
            "installs": self.installs,
            "evictions": self.evictions,
            "resident": [len(lru) for lru in self._lru],
        }
