"""Multi-tenant fair admission: millions of users are not one queue.

The serving plane up to PR 9 admits strictly in arrival order — one
FIFO, one global broadcast prefix, and a flooding tenant starves every
other tenant's TTFT while it drains.  This module adds the three pieces
that turn fairness from a tax into a throughput optimization
(MQFQ-Sticky, PAPERS.md):

- :class:`TenancyConfig` — the per-tenant policy surface (names,
  weights, TTFT SLOs, prefix-pool size, stickiness knobs), validated at
  construction so the worker's never-dies loop can't trip on bad knobs
  mid-cycle;
- :class:`DeficitRoundRobin` + :class:`FairAdmission` — per-tenant
  sub-queues feeding the continuous batcher through deficit-round-robin
  admission.  Each refill cycle's batch is *picked* by deficit counters
  instead of arrival order, then still prefills as ONE ``[M, P]`` insert
  (the scheduler is pure host bookkeeping — zero new device dispatches
  or host syncs; the PR 7 ``insert_dispatches``/``host_transfers``
  counters pin it).  DRR's invariants are the classic ones: work
  conservation (no idle slot while any tenant queue is non-empty),
  bounded deficit (an empty queue resets its counter, so no tenant
  banks unbounded credit and none starves beyond a weight-proportional
  delay), deterministic order (no randomness anywhere — a fixed request
  stream admits identically every run);
- :class:`PrefixPool` — N resident prefix-cache entries with LRU
  eviction, generalizing the single ``--prefix-ids`` broadcast prefix.
  A tenant's shared prompt prefix is prefilled ONCE at install
  (one forward), then every request that reuses it gathers the cached
  KV on device inside the admission insert — a pool hit never
  re-prefills the shared region.  On the sharded plane each shard owns
  its own pool partition (its HBM, its residency), which is exactly why
  sticky routing (:meth:`~.shard_plane.ShardedBatcher.route_prefixed`)
  pays: a tenant kept on its home shard keeps its prefix hits, while
  freest-first scatter re-installs (and LRU-thrashes) the same prefix on
  every shard it touches.

Everything here is deliberately queue-shaped, not device-shaped: the
scheduler and the pool's LRU are plain Python; only the pool's KV
buffers and the install splice live on device (one tiny jit at install
time, never on the per-cycle path).  With ``tenancy=None`` nothing in
this module is even imported by the hot path — the engine keeps today's
reference behavior byte for byte.
"""

from __future__ import annotations

import time
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import cached_property
from typing import Any


@dataclass(frozen=True)
class _PoolEvent:
    """One prefix-pool decision (install/evict), timestamped — shaped
    like a :class:`~..fleet.pool.FleetEvent` so
    :func:`~..obs.trace.instant_trace_events` exports it onto the same
    Chrome-trace timeline as the fleet's supervisor decisions."""

    name: str
    t: float
    args: dict = field(default_factory=dict)


# the smallest admissible weight/quantum — AND their product: one DRR
# round earns quantum*weight of deficit, so admitting one request costs
# ~1/(quantum*weight) scheduler rounds; flooring the product (validated
# in TenancyConfig) bounds that at 100 rounds
MIN_WEIGHT = 0.01

# the overload ladder's tier count: 1 = degrade, 2 = + evict cold
# prefix entries, 3 = + shed with explicit error replies
MAX_SHED_TIERS = 3


@dataclass(frozen=True)
class TenancyConfig:
    """The multi-tenant admission policy.

    ``tenants`` names the KNOWN tenants (weights/SLOs align by index);
    unknown tenant labels arriving on the queue are still served, at
    weight 1.0 — fairness must not require pre-registration, only
    priority does.  ``weights`` empty = all 1.0.

    ``prefix_pool`` > 0 enables the per-tenant prefix-cache pool with
    that many resident entries PER SHARD; ``prefix_len`` is the pool's
    static prefix bucket (every pooled prefix must be exactly this many
    tokens — the compiled insert closes over it; the worker defaults it
    to ``seq_len``).  ``sticky`` toggles affinity-first routing on the
    sharded plane (off = today's freest-first, the FIFO-routing
    baseline the bench compares against); ``sticky_imbalance`` is how
    many free slots the freest shard may lead the home shard by before
    stickiness yields (0 = auto: the shard's slot count, i.e. yield
    only when the home shard is full).  ``fair`` toggles the DRR pick
    (off = arrival order through the same staging machinery — the FIFO
    admission baseline).  ``ttft_slo_s`` aligns per-tenant TTFT SLOs
    with ``tenants`` (empty = no SLO); the bench scores
    time-over-TTFT-SLO per tenant from it.
    """

    tenants: tuple[str, ...]
    weights: tuple[float, ...] = ()
    prefix_pool: int = 0
    prefix_len: int = 0
    sticky: bool = True
    sticky_imbalance: int = 0
    fair: bool = True
    quantum: float = 1.0
    ttft_slo_s: tuple[float, ...] = ()
    # deadline-aware admission (EDF blended into the DRR pick): a staged
    # request whose arrival-based TTFT deadline (SentTimestamp +
    # ttft_slo_s) falls within ``urgency_window_s`` of now may jump the
    # quantum — charged against its tenant's deficit, which may go at
    # most ``urgency_budget`` requests negative (the bounded borrow that
    # keeps deadline jumps from starving compliant tenants).  0 = off:
    # the pick is byte-identical to pure DRR, deadlines or not.
    urgency_window_s: float = 0.0
    urgency_budget: float = 2.0
    # tiered load shedding under measured overload pressure (see
    # OverloadLadder): 0 = off (the PR 8 TTL shed stays the only tier);
    # 1 = degrade over-share tenants to a smaller generate_tokens;
    # 2 = + evict cold prefix-pool entries; 3 = + shed staged requests
    # from the most-over-share tenants with explicit error replies.
    shed_tiers: int = 0
    # the fair-admission staging (lookahead) window, in requests:
    # 0 = auto (per-tenant one engine-full, total two engine-fulls —
    # the PR 10 defaults).  A deeper window lets DRR/EDF reorder more
    # of the backlog (a victim's request must be STAGED before any
    # admission policy can prefer it), at bounded extra memory: the
    # queue itself remains the real backlog.
    staging_per_tenant: int = 0
    staging_total: int = 0
    # sharded admission plane (ISSUE 19): N >= 2 splits the staging
    # plane into N crash-tolerant AdmissionShard workers — tenants map
    # to shards by consistent hash (sticky, so a tenant's prefix home
    # and DRR state live on one shard), global fairness reconciled via
    # rate-bounded cross-shard credit borrowing.  1 = the single-plane
    # PR 11 behaviour, byte-identical.
    admission_shards: int = 1
    # decode-phase deadline (seconds of decode budget per generated
    # token): once a request has produced its first token, it must
    # sustain decode_slo_s per remaining token or be shed with an
    # explicit error reply (reason="decode_deadline").  0 = off — the
    # TTFT deadline remains the only enforced SLO.
    decode_slo_s: float = 0.0

    def __post_init__(self) -> None:
        if not self.tenants:
            raise ValueError("tenancy needs at least one tenant name")
        if len(set(self.tenants)) != len(self.tenants):
            raise ValueError(f"duplicate tenant names in {self.tenants}")
        for name in self.tenants:
            if not name or not isinstance(name, str):
                raise ValueError(f"tenant names must be non-empty strings "
                                 f"(got {name!r})")
        if self.weights and len(self.weights) != len(self.tenants):
            raise ValueError(
                f"{len(self.weights)} weight(s) for {len(self.tenants)} "
                "tenant(s) — counts must match"
            )
        for w in self.weights:
            if not w >= MIN_WEIGHT:
                # a round earns quantum*weight of deficit: a vanishing
                # weight makes the DRR spin ~1/(quantum*weight) full
                # rounds per admitted request inside the refill loop —
                # a legal-looking config must not be able to stall the
                # serving worker, so tiny weights are a usage error
                raise ValueError(
                    f"tenant weights must be >= {MIN_WEIGHT} (got {w}; "
                    "express shares by raising the other weights "
                    "instead of vanishing this one)"
                )
        if self.prefix_pool < 0:
            raise ValueError(
                f"prefix_pool={self.prefix_pool} must be >= 0 (0 = off)"
            )
        if self.prefix_len < 0:
            raise ValueError(
                f"prefix_len={self.prefix_len} must be >= 0"
            )
        if self.sticky_imbalance < 0:
            raise ValueError(
                f"sticky_imbalance={self.sticky_imbalance} must be >= 0 "
                "(0 = auto)"
            )
        if not self.quantum >= MIN_WEIGHT:
            raise ValueError(
                f"quantum={self.quantum} must be >= {MIN_WEIGHT} "
                "(a vanishing quantum spins the scheduler)"
            )
        if self.weights and self.quantum * min(self.weights) < MIN_WEIGHT:
            # the two floors compose: a round earns quantum*weight, so
            # quantum=0.01 with weight=0.01 would still cost ~10,000
            # rounds per admitted request — the PRODUCT is what bounds
            # the scheduler's work, so the product gets the floor
            raise ValueError(
                f"quantum * min(weight) = "
                f"{self.quantum * min(self.weights):g} must be >= "
                f"{MIN_WEIGHT} (each DRR round earns quantum*weight of "
                "deficit; a vanishing product spins the refill loop)"
            )
        if self.ttft_slo_s and len(self.ttft_slo_s) != len(self.tenants):
            raise ValueError(
                f"{len(self.ttft_slo_s)} TTFT SLO(s) for "
                f"{len(self.tenants)} tenant(s) — counts must match"
            )
        for slo in self.ttft_slo_s:
            if slo < 0:
                raise ValueError(f"TTFT SLOs must be >= 0 (got {slo})")
        if self.urgency_window_s < 0:
            raise ValueError(
                f"urgency_window_s={self.urgency_window_s} must be >= 0 "
                "(0 = off)"
            )
        if self.urgency_budget < 0:
            raise ValueError(
                f"urgency_budget={self.urgency_budget} must be >= 0"
            )
        if not 0 <= self.shed_tiers <= MAX_SHED_TIERS:
            raise ValueError(
                f"shed_tiers={self.shed_tiers} must be in "
                f"[0, {MAX_SHED_TIERS}] (0 = off)"
            )
        if self.staging_per_tenant < 0 or self.staging_total < 0:
            raise ValueError(
                "staging_per_tenant and staging_total must be >= 0 "
                "(0 = auto)"
            )
        if self.admission_shards < 1:
            raise ValueError(
                f"admission_shards={self.admission_shards} must be >= 1 "
                "(1 = the single staging plane)"
            )
        if self.decode_slo_s < 0:
            raise ValueError(
                f"decode_slo_s={self.decode_slo_s} must be >= 0 (0 = off)"
            )

    # weight_of runs once per tenant per DRR round on the refill hot
    # path: dict lookups, built once (cached_property assigns through
    # the instance __dict__, which frozen dataclasses allow)
    @cached_property
    def _weight_by_tenant(self) -> "dict[str, float]":
        return dict(zip(self.tenants, self.weights))

    @cached_property
    def _slo_by_tenant(self) -> "dict[str, float]":
        return dict(zip(self.tenants, self.ttft_slo_s))

    def weight_of(self, tenant: str) -> float:
        """Configured weight, or 1.0 for tenants not pre-registered."""
        return self._weight_by_tenant.get(tenant, 1.0)

    def slo_of(self, tenant: str) -> float:
        """Configured TTFT SLO seconds, or 0.0 (= none)."""
        return self._slo_by_tenant.get(tenant, 0.0)

    def deadline_of(
        self, tenant: str, arrived_epoch: "float | None"
    ) -> "float | None":
        """The request's arrival-based TTFT deadline (epoch seconds), or
        None when the tenant has no SLO or the queue did not stamp an
        arrival — an undeadlined request never jumps the quantum."""
        slo = self.slo_of(tenant)
        if slo <= 0 or arrived_epoch is None:
            return None
        return arrived_epoch + slo


class DeficitRoundRobin:
    """Deficit-round-robin over per-tenant sub-queues, EDF-blendable.

    The classic Shreedhar/Varghese scheduler with per-request cost 1:
    each round visits tenants in first-seen order starting at a rotating
    cursor; a visited non-empty tenant earns ``quantum * weight`` of
    deficit and pops requests while its deficit covers them.  An
    emptied queue resets its deficit (credit never banks while there is
    nothing to spend it on), which also bounds any tenant's wait at a
    weight-proportional number of rounds.  ``pick`` keeps cycling
    rounds until ``k`` requests are picked or every queue is empty —
    the work-conservation invariant (a free slot is never left idle
    while any tenant has a staged request).  No randomness anywhere: a
    fixed arrival stream picks identically every run.

    **EDF blend** (``urgency_window_s > 0`` and ``pick(..., now=...)``):
    before the fair rounds, staged HEAD requests whose deadline falls
    within the urgency window of ``now`` are picked earliest-deadline-
    first.  Two bounds keep the blend fair:

    - every jump is *charged* to its tenant's deficit, which may go at
      most ``urgency_budget`` requests negative and resets with the
      queue on empty (per-busy-period borrow — kept debt would turn
      steady trickle traffic's jumps into loans repaid in extra wait);
    - every jump also spends one urgency CREDIT from a token bucket of
      capacity ``urgency_budget`` refilling at ``quantum * weight``
      per fair round — the tenant's fair-share rate — so a sustained
      urgent stream cannot jump faster than its share no matter how it
      shapes bursts (deficit reset alone would let a drain-and-refill
      abuser re-arm unlimited jumps).

    A tenant at either cap falls back to fair order, so the combined
    invariant holds: ``-urgency_budget <= deficit <= quantum * weight
    + 1``, sustained jump rate <= fair share, and a compliant
    backlogged tenant keeps its share whatever the deadline traffic
    does.  With no deadlines staged (or the window at 0) the pick is
    byte-identical to pure DRR — all of it property-tested in
    ``tests/test_admission.py``.
    """

    def __init__(self, weight_of=None, quantum: float = 1.0,
                 keep=(), urgency_window_s: float = 0.0,
                 urgency_budget: float = 0.0) -> None:
        if not quantum > 0:
            raise ValueError(f"quantum={quantum} must be > 0")
        if urgency_window_s < 0 or urgency_budget < 0:
            raise ValueError(
                "urgency_window_s and urgency_budget must be >= 0"
            )
        self._weight_of = weight_of or (lambda tenant: 1.0)
        self.quantum = quantum
        self.urgency_window_s = urgency_window_s
        self.urgency_budget = urgency_budget
        # tenants whose (empty) sub-queues stay registered forever —
        # the CONFIGURED tenants.  Unknown labels arrive from untrusted
        # message bodies, so their entries are pruned the moment they
        # drain: scheduler state stays bounded by keep + staging depth
        # no matter how many distinct labels an adversary invents.
        self._keep = frozenset(keep)
        self._queues: dict[str, deque] = {}
        self._deficit: dict[str, float] = {}
        self._order: list[str] = []  # first-seen tenant order
        self._cursor = 0
        self._ordinal = 0  # arrival stamp (the fair=False pick order)
        # deadline jumps taken out of fair order (introspection/gauges)
        self.urgent_picks = 0
        # the urgency-credit token bucket: jumps spend from a per-tenant
        # credit (capacity = urgency_budget) that refills at quantum *
        # weight per completed fair ROUND — i.e. at the tenant's fair-
        # share rate.  The deficit charge alone is not enough: deficit
        # resets when a queue empties (per-busy-period budgets, which
        # steady trickle traffic needs), so a drain-and-refill abuser
        # could re-arm unlimited jumps by sending its urgent requests
        # two at a time.  Credit persists across busy periods and
        # refills only as rounds pass, bounding sustained jump rate to
        # the fair share however the abuser shapes its bursts.
        # _rounds counts fair-phase rotations FRACTIONALLY (a pick
        # truncated after visiting i of n tenants advances i/n), so
        # credits keep refilling even when every pick is smaller than
        # one rotation — the common case under many-tenant contention.
        self._rounds = 0.0
        self._credit: dict[str, float] = {}
        self._credit_round: dict[str, float] = {}
        # object ids of the MOST RECENT pick()'s urgent items —
        # refund() consults it so a shed urgent pick gives back its
        # credit too, attributed to the exact item (a count per tenant
        # would let a shed FAIR pick return a credit that an admitted
        # urgent jump in the same pick legitimately spent)
        self._last_urgent_ids: set[int] = set()

    def push(self, tenant: str, item: Any,
             deadline: "float | None" = None) -> None:
        """Stage one item.  ``deadline`` (epoch seconds) is the
        request's TTFT deadline; None = no SLO — the item can never
        jump the quantum."""
        queue = self._queues.get(tenant)
        if queue is None:
            queue = self._queues[tenant] = deque()
            self._deficit[tenant] = 0.0
            self._credit.setdefault(tenant, self.urgency_budget)
            self._credit_round.setdefault(tenant, self._rounds)
            self._order.append(tenant)
        queue.append((self._ordinal, deadline, item))
        self._ordinal += 1

    def depth(self, tenant: str) -> int:
        queue = self._queues.get(tenant)
        return len(queue) if queue is not None else 0

    def depths(self) -> dict[str, int]:
        """Per-tenant staged depth: every configured tenant (a drained
        one's gauge reads 0 instead of disappearing) plus whatever
        unknown labels are currently staged — drained unknowns are
        pruned, so the gauge cardinality stays bounded."""
        return {t: len(q) for t, q in self._queues.items()}

    @property
    def staged(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def deficit(self, tenant: str) -> float:
        """Introspection for the property tests."""
        return self._deficit.get(tenant, 0.0)

    def _prune(self) -> None:
        """Drop drained non-configured tenants (their deficit is already
        0 by the bounded-deficit reset, so removal changes no future
        pick; a re-arrival re-registers at the order's tail exactly like
        a first arrival).  The cursor is remapped to the same next-round
        tenant, so pruning never skips anyone's turn.  A tenant whose
        urgency credit is still refilling is kept too: pruning it
        would hand its re-registration a FULL bucket — the exact
        drain-and-refill re-arm the credit exists to prevent.  (In the
        worker only configured tenants can ever spend credit —
        unregistered labels have no SLO, so no deadline, so no jumps —
        which keeps this no-cardinality-leak: an adversarial unique
        label always drains with a full, prunable bucket.)"""
        dead = {
            t for t in self._order
            if not self._queues[t] and t not in self._keep
            and self._deficit[t] == 0.0
            and self._refill_credit(t) >= self.urgency_budget
        }
        if not dead:
            return
        n = len(self._order)
        survivors = [t for t in self._order if t not in dead]
        cursor = 0
        for i in range(n):
            tenant = self._order[(self._cursor + i) % n]
            if tenant not in dead:
                cursor = survivors.index(tenant)
                break
        for tenant in dead:
            del self._queues[tenant]
            del self._deficit[tenant]
            self._credit.pop(tenant, None)
            self._credit_round.pop(tenant, None)
        self._order = survivors
        self._cursor = cursor

    def refund(self, tenant: str, item: Any = None) -> None:
        """Give back one picked request's charges (most recent pick).

        The redelivery/TTL skew fix: a picked item that is then SHED
        (expired while staged, or a redelivered copy of an already-
        answered request) consumed no slot — without the refund its
        tenant paid a full request of deficit (and, for an urgent
        pick, an urgency credit) for nothing, so a flood of
        expired/redelivered copies would silently shrink a tenant's
        future share — or strip an SLO tenant's jump budget.  Pass
        the picked ``item`` so the credit refund is attributed to the
        exact urgent pick that spent it (fair picks spent none — a
        per-tenant count would let a shed fair pick return a credit an
        ADMITTED urgent jump in the same pick legitimately consumed);
        without the item only the deficit is refunded.  The deficit
        refund is only meaningful while the tenant still has backlog
        (an emptied queue resets anyway).  Neither refund can exceed
        its bound: each returns exactly what the pick charged."""
        if item is not None and id(item) in self._last_urgent_ids:
            self._last_urgent_ids.discard(id(item))
            if tenant in self._credit:
                self._credit[tenant] = min(
                    self.urgency_budget, self._credit[tenant] + 1.0
                )
        if self._queues.get(tenant):
            self._deficit[tenant] = self._deficit.get(tenant, 0.0) + 1.0

    def pop_over_deadline(
        self, now: float, eligible=None,
    ) -> "tuple[str, Any] | None":
        """Pop the staged HEAD item most over its deadline at ``now``
        (ties by arrival), or None when nothing staged is past due —
        the ladder's tier-3 most-over-SLO shed order.  ``eligible``
        (a set of tenant names, or None = all) restricts candidates:
        the worker passes the over-share set so a COMPLIANT tenant's
        late request is served late rather than shed."""
        best = None
        for tenant in self._order:
            if eligible is not None and tenant not in eligible:
                continue
            queue = self._queues[tenant]
            if not queue:
                continue
            ordinal, deadline, _ = queue[0]
            if deadline is None or deadline >= now:
                continue
            cand = (deadline, ordinal, tenant)
            if best is None or cand < best:
                best = cand
        if best is None:
            return None
        tenant = best[2]
        item = self._queues[tenant].popleft()[2]
        if not self._queues[tenant]:
            self._deficit[tenant] = 0.0
        return tenant, item

    def pop_tail(self, tenant: str) -> "Any | None":
        """Pop the NEWEST staged item of ``tenant`` (the ladder's tier-3
        over-share shed order: the oldest staged requests keep their
        place; the latest arrivals of the over-share tenant absorb the
        shed), or None when nothing is staged."""
        queue = self._queues.get(tenant)
        if not queue:
            return None
        item = queue.pop()[2]
        if not queue:
            self._deficit[tenant] = 0.0
        return item

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py StateProvider).  Staged
    # QUEUES are deliberately NOT serialized: staged messages are live
    # receipt handles that die with the process and redeliver through
    # the queue's visibility timeout — for queue contents, a crash is
    # the start of a new busy period.  The ACCOUNTING must survive,
    # though: urgency debt and the credit token bucket are exactly what
    # a drain-and-refill abuser re-arms by forcing a restart, and
    # deficits in debt are loans a crash must not forgive.
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        tenants = {
            t: {
                "deficit": self._deficit.get(t, 0.0),
                "credit": self._credit.get(t, self.urgency_budget),
                "credit_round": self._credit_round.get(t, self._rounds),
            }
            for t in self._order
        }
        return {
            "records": len(tenants),
            "tenants": tenants,
            "order": list(self._order),
            "cursor": self._cursor,
            "rounds": self._rounds,
            "urgent_picks": self.urgent_picks,
        }

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: "float | None" = None, max_age_s: float = 0.0,
    ) -> int:
        """Restore the scheduler's accounting (round clock, cursor,
        per-tenant deficits and urgency credits) into empty sub-queues.
        Tenants with nothing owed and a full bucket prune away on the
        next pick, exactly as live drained tenants do."""
        del rebase, now, max_age_s  # nothing here is clock-based
        order = [t for t in state.get("order", ()) if isinstance(t, str)]
        tenants = state.get("tenants") or {}
        self._rounds = float(state.get("rounds", self._rounds) or 0.0)
        recovered = 0
        for tenant in order:
            saved = tenants.get(tenant)
            if not isinstance(saved, dict):
                continue
            if tenant not in self._queues:
                self._queues[tenant] = deque()
                self._order.append(tenant)
            try:
                self._deficit[tenant] = float(saved.get("deficit", 0.0))
                self._credit[tenant] = min(
                    self.urgency_budget,
                    float(saved.get("credit", self.urgency_budget)),
                )
                self._credit_round[tenant] = min(
                    self._rounds,
                    float(saved.get("credit_round", self._rounds)),
                )
            except (TypeError, ValueError):
                continue
            recovered += 1
        cursor = state.get("cursor")
        if self._order and isinstance(cursor, int):
            self._cursor = cursor % len(self._order)
        self.urgent_picks = int(state.get("urgent_picks", 0) or 0)
        return recovered

    def pick(self, k: int, *, fair: bool = True,
             now: "float | None" = None) -> list[tuple[str, Any]]:
        """Pop up to ``k`` ``(tenant, item)`` pairs by deficit order.

        ``fair=False`` degrades to global arrival order across the same
        sub-queues (the FIFO-admission baseline the bench contrasts) —
        same staging, same bounds, no deficit accounting.  ``now``
        (epoch seconds) arms the EDF blend: staged deadlines within
        ``urgency_window_s`` of it may jump the quantum, charged
        against the bounded urgency budget.  ``now=None`` or a zero
        window is pure DRR, byte for byte.
        """
        self._last_urgent_ids = set()
        try:
            return self._pick(k, fair, now)
        finally:
            self._prune()

    def _refill_credit(self, tenant: str) -> float:
        """Lazily refill the tenant's urgency credit: quantum * weight
        per fair round elapsed since its last refill, capped at the
        budget."""
        elapsed = self._rounds - self._credit_round[tenant]
        if elapsed > 0:
            self._credit[tenant] = min(
                self.urgency_budget,
                self._credit[tenant]
                + elapsed * self.quantum * self._weight_of(tenant),
            )
            self._credit_round[tenant] = self._rounds
        return self._credit[tenant]

    def _pick_urgent(self, k: int, now: float,
                     out: list[tuple[str, Any]]) -> None:
        """The EDF phase: pop staged heads whose deadline falls within
        the urgency window, earliest deadline first (ties by arrival).
        Every jump spends one urgency CREDIT (the fair-share-rate
        token bucket) AND charges the tenant's deficit down to the
        ``-urgency_budget`` debt cap.  Runs before the fair rounds, so
        an SLO tenant about to blow its TTFT jumps the quantum — but
        its sustained jump rate can never exceed its fair share, and
        its per-busy-period borrow never exceeds the budget."""
        horizon = now + self.urgency_window_s
        while len(out) < k:
            best = None
            for tenant in self._order:
                queue = self._queues[tenant]
                if not queue:
                    continue
                ordinal, deadline, _ = queue[0]
                if deadline is None or deadline > horizon:
                    continue
                if self._deficit[tenant] - 1.0 < -self.urgency_budget:
                    continue  # debt cap: back to fair order
                if self._refill_credit(tenant) < 1.0:
                    continue  # jump rate cap: back to fair order
                cand = (deadline, ordinal, tenant)
                if best is None or cand < best:
                    best = cand
            if best is None:
                return
            tenant = best[2]
            item = self._queues[tenant].popleft()[2]
            out.append((tenant, item))
            self._deficit[tenant] -= 1.0
            self._credit[tenant] -= 1.0
            self._last_urgent_ids.add(id(item))
            self.urgent_picks += 1
            if not self._queues[tenant]:
                # the classic reset-on-empty applies to urgency debt
                # too: the budget is PER BUSY PERIOD.  A drained tenant
                # consumed no more than it arrived with, so carrying
                # its debt forward would turn every future urgent
                # request into a loan repaid in extra waiting — under
                # steady trickle arrivals that makes EDF *worse* than
                # pure DRR once the budget exhausts.  An abuser cannot
                # farm resets: refreshing the budget requires its own
                # queue to empty, i.e. it stopped flooding.
                self._deficit[tenant] = 0.0

    def _pick(self, k: int, fair: bool,
              now: "float | None") -> list[tuple[str, Any]]:
        out: list[tuple[str, Any]] = []
        if k <= 0 or not self._order:
            return out
        if not fair:
            # arrival order: items carry a monotone stage ordinal
            while len(out) < k:
                oldest, best = None, None
                for tenant in self._order:
                    queue = self._queues[tenant]
                    if queue and (best is None or queue[0][0] < best):
                        best, oldest = queue[0][0], tenant
                if oldest is None:
                    break
                out.append((oldest, self._queues[oldest].popleft()[2]))
            return out
        if self.urgency_window_s > 0 and now is not None:
            self._pick_urgent(k, now, out)
        n = len(self._order)
        while len(out) < k and any(
            self._queues[t] for t in self._order
        ):
            for i in range(n):
                tenant = self._order[(self._cursor + i) % n]
                queue = self._queues[tenant]
                if not queue:
                    # bounded deficit: an empty queue banks nothing
                    # (and urgency debt resets with it — per-busy-
                    # period budgets, see _pick_urgent)
                    self._deficit[tenant] = 0.0
                    continue
                if self._deficit[tenant] < 1.0:
                    # earn once per serviced round: a visit that merely
                    # RESUMES spending credit left over from a
                    # k-truncated pick must not earn again, or deficits
                    # grow without bound and weighted shares collapse
                    # toward equal whenever the per-refill pick is
                    # smaller than a tenant's round quantum.  A tenant
                    # in urgency debt earns its way back toward 1.0
                    # over several rounds — the repayment that keeps
                    # deadline jumps from compounding.
                    self._deficit[tenant] += (
                        self.quantum * self._weight_of(tenant)
                    )
                while queue and self._deficit[tenant] >= 1.0 \
                        and len(out) < k:
                    out.append((tenant, queue.popleft()[2]))
                    self._deficit[tenant] -= 1.0
                if not queue:
                    self._deficit[tenant] = 0.0
                if len(out) >= k:
                    # the rotation that keeps a small k from always
                    # favoring the first-seen tenant: resume the NEXT
                    # pick one past the tenant that filled this one —
                    # UNLESS its turn is unfinished (backlog left and
                    # deficit still ≥ 1): then the cursor stays put so
                    # the next pick resumes the same turn, or a
                    # high-weight tenant would spend each round's
                    # credit at the same one-visit-per-pick rate as
                    # weight-1 tenants and shares would collapse
                    unfinished = bool(queue) and \
                        self._deficit[tenant] >= 1.0
                    self._cursor = (
                        self._cursor + i + (0 if unfinished else 1)
                    ) % n
                    # a truncated pick still advances the round clock
                    # by the fraction of the rotation it visited
                    self._rounds += (i + 1) / n
                    return out
            # one full rotation completed: urgency credits accrue one
            # round of fair-share refill (see _refill_credit)
            self._rounds += 1
        return out


class FairAdmission:
    """The worker-side staging area between the queue and the batcher.

    Receives go into per-tenant sub-queues (bounded — the queue itself
    is the backlog; staging is only the one-refill lookahead DRR needs
    to see across tenants), and each refill cycle's admission batch is
    picked by :class:`DeficitRoundRobin`.  Per-tenant staging is capped
    at ``per_tenant_limit`` so one flooding tenant cannot monopolize the
    lookahead window either: overflow messages are *handed back* to the
    queue by the worker (``change_message_visibility(0)``) instead of
    staged — at-least-once backpressure, never a drop.

    The staging layer also keeps the overload ladder's flood
    classifier: a per-tenant exponentially-decayed STAGED-ARRIVAL rate
    (:meth:`note_cycle` decays, :meth:`stage` counts).  Instantaneous
    staged depth cannot tell a coordinated coalition from normal load
    (the staging caps flatten every backlogged tenant to a similar
    depth), but sustained arrival rate can — and a victim trickling
    one request every few cycles can never cross the rate floor.
    """

    #: per-cycle decay of the arrival-rate EWMA (steady state for a
    #: tenant staging r requests/cycle is r / (1 - decay) = 5r)
    ARRIVAL_DECAY = 0.8
    #: rate entries below this decay out entirely (bounds the dict)
    ARRIVAL_FLOOR = 0.05
    #: over-share = rate share > margin x weight share, AND the
    #: absolute rate is at least the floor below — both tuned so a
    #: coalition member modestly over its share still classifies while
    #: a trickling SLO victim never can
    OVER_SHARE_MARGIN = 1.25
    OVER_SHARE_MIN_RATE = 3.0
    #: how many times the min rate an SLO-carrying tenant must sustain
    #: before the shed tier may treat it as flooding (an SLO is close
    #: to a no-shed contract: only an unambiguous premium flood loses
    #: requests, and then only already-expired ones)
    PREMIUM_FLOOD_FACTOR = 3.0
    #: distinct message ids remembered for rate dedup (see stage())
    SEEN_IDS = 8192

    def __init__(
        self,
        tenancy: TenancyConfig,
        *,
        per_tenant_limit: int,
        total_limit: int,
    ) -> None:
        if per_tenant_limit < 1 or total_limit < 1:
            raise ValueError("staging limits must be >= 1")
        self.tenancy = tenancy
        self.per_tenant_limit = per_tenant_limit
        self.total_limit = total_limit
        self.drr = DeficitRoundRobin(
            weight_of=tenancy.weight_of, quantum=tenancy.quantum,
            keep=tenancy.tenants,
            urgency_window_s=tenancy.urgency_window_s,
            urgency_budget=tenancy.urgency_budget,
        )
        # messages actually handed back to the queue on a staging-cap
        # hit — the CALLER increments it when its
        # change_message_visibility(0) went through, so the counter
        # never claims a backpressure event that did not happen
        self.overflow_total = 0
        # serial host work this plane has performed (rate decays,
        # stagings, flood scans) — the admission-scale bench's virtual
        # cost model charges these to the clock, and a sharded plane
        # charges only the max over its shards (they run concurrently)
        self.host_ops = 0
        # tenant -> decayed staged-arrivals-per-cycle (the ladder's
        # flood classifier input; pure bookkeeping — nothing on the
        # admission path reads it unless a ladder asks).  Rated by
        # UNIQUE message id: a backlogged victim's messages redeliver
        # every cycle while staging is contended, and counting each
        # redelivery would read exactly like a flood — only NEW work
        # is offered load.
        self.arrival_rate: dict[str, float] = {}
        self._seen_ids: OrderedDict = OrderedDict()
        # classification is STICKY while the flood's backlog persists:
        # a flood that stops sending drops below the rate floor within
        # a few decay cycles, but its queued backlog keeps drowning
        # everyone behind it — a classified tenant stays classified
        # until its staged queue actually drains
        self._flood_sticky: set[str] = set()
        # restart grace: a rehydrated classification has NO staged
        # backlog yet (staging dies with the process; the flood's
        # messages are still redelivering), so restored sticky entries
        # survive this many cycles without depth before the ordinary
        # drains-means-done rule applies again (import_state arms it)
        self._sticky_grace: dict[str, int] = {}
        # request-lifecycle registry (obs/lifecycle.py): the worker's
        # attach_lifecycle wires it so staging stamps the "staged"
        # phase; None = tracing off, zero work on the staging path
        self.lifecycle = None

    def note_cycle(self) -> None:
        """Decay the arrival-rate EWMA one refill cycle (entries under
        :attr:`ARRIVAL_FLOOR` drop out, so the dict stays bounded by
        recent stagers no matter how many labels an adversary mints)."""
        self.host_ops += 1 + len(self.arrival_rate)
        decay = self.ARRIVAL_DECAY
        self.arrival_rate = {
            tenant: rate * decay
            for tenant, rate in self.arrival_rate.items()
            if rate * decay >= self.ARRIVAL_FLOOR
        }
        if self._sticky_grace:
            self._sticky_grace = {
                t: n - 1 for t, n in self._sticky_grace.items() if n > 1
            }

    def over_share(self) -> frozenset:
        """Tenants whose decayed staged-arrival-rate share exceeds
        their weight share by :attr:`OVER_SHARE_MARGIN` and whose
        absolute rate clears :attr:`OVER_SHARE_MIN_RATE` — the
        overload ladder's flood set.  Empty under uniform load, for a
        lone trickler, or when nothing has staged recently.  Sticky:
        a classified tenant stays in the set while its staged queue
        is non-empty even after its measured rate decays (the attack
        stopped SENDING, but its backlog is still the overload), and
        drops out the moment its backlog clears."""
        self.host_ops += len(self.arrival_rate)
        fresh: set[str] = set()
        rates = self.arrival_rate
        if len(rates) >= 2:
            total = sum(rates.values())
            if total > 0:
                weights = {
                    t: self.tenancy.weight_of(t) for t in rates
                }
                wtotal = sum(weights.values())
                fresh = {
                    tenant for tenant, rate in rates.items()
                    if rate >= self.OVER_SHARE_MIN_RATE
                    and rate * wtotal
                    > self.OVER_SHARE_MARGIN * weights[tenant] * total
                }
        self._flood_sticky = fresh | {
            t for t in self._flood_sticky
            if self.drr.depth(t) > 0 or self._sticky_grace.get(t, 0) > 0
        }
        return frozenset(self._flood_sticky)

    def adopt_flood(self, tenants) -> None:
        """Adopt peer-gossiped flood classifications (the sharded
        admission plane's gossip receive side): sticky, armed with the
        restore grace — this shard has no local backlog or offered-rate
        history for the tenant yet, so without the grace the ordinary
        drains-means-done rule would immediately un-classify a flooder
        the moment it fails over here."""
        fresh = {str(t) for t in tenants} - self._flood_sticky
        if not fresh:
            return
        self._flood_sticky |= fresh
        for tenant in fresh:
            self._sticky_grace[tenant] = self.STICKY_RESTORE_GRACE

    @property
    def staged(self) -> int:
        return self.drr.staged

    @property
    def room(self) -> int:
        """How many more messages staging can hold right now."""
        return max(0, self.total_limit - self.staged)

    def _note_offered(self, tenant: str, message_id: "str | None") -> None:
        """Count one unit of OFFERED load into the tenant's rate —
        once per distinct message id (redeliveries of the same message
        are not new work; ``message_id=None`` always counts)."""
        if message_id is not None:
            if message_id in self._seen_ids:
                self._seen_ids.move_to_end(message_id)
                return
            self._seen_ids[message_id] = True
            while len(self._seen_ids) > self.SEEN_IDS:
                self._seen_ids.popitem(last=False)
        self.arrival_rate[tenant] = (
            self.arrival_rate.get(tenant, 0.0) + 1.0
        )

    def stage(self, tenant: str, item: Any,
              deadline: "float | None" = None,
              message_id: "str | None" = None) -> bool:
        """Stage one parsed request; False = per-tenant/total cap hit
        (the caller hands the message back to the queue and counts it
        in :attr:`overflow_total` — only when the hand-back actually
        happened).  ``deadline`` is the request's arrival-based TTFT
        deadline (epoch seconds; None = no SLO), carried so the EDF
        blend can see it at pick time; ``message_id`` dedups the
        offered-load rate under redelivery."""
        self.host_ops += 1
        if self.drr.depth(tenant) >= self.per_tenant_limit:
            # offered past its OWN cap: the per-tenant flood signature
            # — counted into the rate even though nothing stages (a
            # saturated flooder's successful stages are throttled to
            # the drain rate, which would blind the classifier to the
            # sustained offered load behind them)
            self._note_offered(tenant, message_id)
            return False
        if self.staged >= self.total_limit:
            # the TOTAL cap is shared congestion, not tenant behavior:
            # counting it would accrue flood-rate onto whoever happens
            # to arrive (e.g. a victim redelivering behind a stampede)
            return False
        self.drr.push(tenant, item, deadline=deadline)
        self._note_offered(tenant, message_id)
        if self.lifecycle is not None:
            self.lifecycle.stamp(message_id, "staged", tenant=tenant)
        return True

    def pick(self, k: int,
             now: "float | None" = None) -> list[tuple[str, Any]]:
        return self.drr.pick(k, fair=self.tenancy.fair, now=now)

    def depths(self) -> dict[str, int]:
        depths = {t: 0 for t in self.tenancy.tenants}
        depths.update(self.drr.depths())
        return depths

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py StateProvider): the flood
    # classifier.  A crash used to UN-classify an active flooder — the
    # restarted worker saw zero offered-rate history, so a coalition
    # mid-attack got a fresh innocence window while its backlog drowned
    # every victim behind it.  The decayed rates, the sticky set, and
    # the seen-message-id dedup window all come back; the sticky set
    # additionally survives the first post-restart over_share() calls
    # via a redelivery grace (staged queues restart empty, and dropping
    # classification before the flood's backlog redelivers would be the
    # exact un-classify bug this section exists to fix).
    # ------------------------------------------------------------------

    #: post-restart cycles a restored sticky classification survives
    #: without backlog (the visibility-timeout redelivery window)
    STICKY_RESTORE_GRACE = 64

    def export_state(self) -> dict:
        state = {
            "drr": self.drr.export_state(),
            "arrival_rate": dict(self.arrival_rate),
            "flood_sticky": sorted(self._flood_sticky),
            "seen_ids": list(self._seen_ids),
            "overflow_total": self.overflow_total,
        }
        state["records"] = (
            state["drr"].get("records", 0)
            + len(self.arrival_rate) + len(self._flood_sticky)
        )
        return state

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: "float | None" = None, max_age_s: float = 0.0,
    ) -> int:
        recovered = 0
        drr = state.get("drr")
        if isinstance(drr, dict):
            recovered += self.drr.import_state(
                drr, rebase=rebase, now=now, max_age_s=max_age_s
            )
        rates = state.get("arrival_rate")
        if isinstance(rates, dict):
            for tenant, rate in rates.items():
                try:
                    rate = float(rate)
                except (TypeError, ValueError):
                    continue
                if rate >= self.ARRIVAL_FLOOR:
                    self.arrival_rate[str(tenant)] = rate
                    recovered += 1
        sticky = state.get("flood_sticky") or ()
        restored_sticky = {str(t) for t in sticky}
        if restored_sticky:
            self._flood_sticky |= restored_sticky
            self._sticky_grace = {
                t: self.STICKY_RESTORE_GRACE for t in restored_sticky
            }
            recovered += len(restored_sticky)
        for mid in state.get("seen_ids") or ():
            self._seen_ids[str(mid)] = True
            while len(self._seen_ids) > self.SEEN_IDS:
                self._seen_ids.popitem(last=False)
        self.overflow_total = int(state.get("overflow_total", 0) or 0)
        return recovered


#: Per-tier (enter, exit) pressure thresholds — enter at or above the
#: first, leave below the second.  The gap is the hysteresis band: a
#: pressure oscillating inside it neither enters nor exits, so the
#: ladder cannot flap tier actions at the noise floor.
TIER_THRESHOLDS: tuple[tuple[float, float], ...] = (
    (0.50, 0.35),  # tier 1: degrade over-share tenants
    (0.75, 0.60),  # tier 2: + evict cold prefix-pool entries
    (0.90, 0.75),  # tier 3: + shed with explicit error replies
)


class OverloadLadder:
    """The graceful-degradation state machine between "serving normally"
    and "cliff-edge failure".

    The worker measures a scalar overload pressure each refill cycle
    (staged-backlog fraction gated by slot occupancy — see
    ``ContinuousWorker._overload_pressure``) and feeds it here; the
    ladder answers with the active tier.  Transitions are hysteretic
    per tier (:data:`TIER_THRESHOLDS`): entry jumps straight to the
    highest tier whose enter threshold the pressure clears (a cliff
    must be answered immediately); exit descends through every tier
    whose exit threshold the pressure has fallen below (one transition
    event records the whole descent), and holds inside a tier's
    hysteresis band.
    ``tiers`` caps how far the ladder may climb (the ``shed_tiers``
    knob); every transition is recorded as an ``overload-*`` event for
    the Chrome-trace timeline and counted for the Prometheus side.

    What the tiers DO lives in the worker (degrade / evict / shed) —
    the ladder only decides WHEN, so the decision logic stays a pure,
    clock-free, property-testable function of the pressure stream.
    """

    def __init__(self, tiers: int,
                 thresholds=TIER_THRESHOLDS,
                 smoothing: float = 0.5) -> None:
        if not 1 <= tiers <= len(thresholds):
            raise ValueError(
                f"tiers={tiers} must be in [1, {len(thresholds)}]"
            )
        for enter, exit_ in thresholds:
            if not 0.0 < exit_ < enter <= 1.0:
                raise ValueError(
                    f"need 0 < exit < enter <= 1 per tier "
                    f"(got enter={enter}, exit={exit_})"
                )
        if not 0.0 < smoothing <= 1.0:
            raise ValueError(
                f"smoothing={smoothing} must be in (0, 1] (1 = none)"
            )
        self.tiers = tiers
        self.thresholds = tuple(thresholds)
        # EWMA weight of the newest pressure sample: tier actions
        # (especially tier 3's shed) drop the raw pressure the very
        # next cycle, so acting on the instantaneous value would flap
        # enter/exit every few cycles no matter how wide the
        # hysteresis band — the smoothed pressure is what transitions
        # compare against
        self.smoothing = smoothing
        self.tier = 0
        self.last_pressure = 0.0
        self._ewma: "float | None" = None
        self.transitions = 0
        # per-tier entry counters (index 1..tiers; 0 unused)
        self.entered_total = [0] * (len(thresholds) + 1)
        self.events: deque[_PoolEvent] = deque(maxlen=1024)

    def exit_threshold(self, tier: int) -> float:
        return self.thresholds[tier - 1][1]

    def update(self, pressure: float,
               now: "float | None" = None) -> int:
        """Advance the ladder one observation; returns the active tier.

        ``now`` timestamps the transition events; the default
        (``time.perf_counter()``) matches every other trace-event
        producer's timebase, so merged Chrome traces line up — only
        pass a clock that shares it (tests pin exact instants with
        explicit values)."""
        self._ewma = (
            pressure if self._ewma is None
            else self.smoothing * pressure
            + (1.0 - self.smoothing) * self._ewma
        )
        pressure = self._ewma
        self.last_pressure = pressure
        target = self.tier
        for tier in range(1, self.tiers + 1):
            if pressure >= self.thresholds[tier - 1][0]:
                target = max(target, tier)
        if target > self.tier:
            self._transition(self.tier, target, pressure, now)
            self.tier = target
        else:
            tier = self.tier
            while tier > 0 and pressure < self.exit_threshold(tier):
                tier -= 1
            if tier != self.tier:
                self._transition(self.tier, tier, pressure, now)
                self.tier = tier
        return self.tier

    def _transition(self, old: int, new: int, pressure: float,
                    now: "float | None") -> None:
        self.transitions += 1
        if new > old:
            self.entered_total[new] += 1
        self.events.append(_PoolEvent(
            "overload-enter" if new > old else "overload-exit",
            time.perf_counter() if now is None else now,
            {"from": old, "to": new, "pressure": round(pressure, 4)},
        ))

    def trace_events(self, time_origin: float | None = None) -> list[dict]:
        """Tier transitions as Chrome-trace instants (``overload-*``
        names land in their own ``"overload"`` category, mergeable into
        a tick trace via ``to_chrome_trace(..., extra_events=...)``)."""
        from ..obs.trace import instant_trace_events

        return instant_trace_events(self.events, time_origin)

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py StateProvider): a crash
    # used to reset the ladder to tier 0 — a controller that died UNDER
    # overload came back serving the same overload at full budgets for
    # the whole EWMA warm-up, the exact moment shedding mattered.
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        return {
            "records": 1,
            "tier": self.tier,
            "ewma": self._ewma,
            "last_pressure": self.last_pressure,
            "transitions": self.transitions,
            "entered_total": list(self.entered_total),
        }

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: "float | None" = None, max_age_s: float = 0.0,
    ) -> int:
        del rebase, now, max_age_s  # pressure is cycle-based, not clocked
        tier = state.get("tier")
        if not isinstance(tier, int) or not 0 <= tier <= self.tiers:
            return 0
        self.tier = tier
        ewma = state.get("ewma")
        self._ewma = float(ewma) if ewma is not None else None
        self.last_pressure = float(state.get("last_pressure", 0.0) or 0.0)
        self.transitions = int(state.get("transitions", 0) or 0)
        entered = state.get("entered_total")
        if isinstance(entered, list) and len(entered) == len(self.entered_total):
            self.entered_total = [int(n) for n in entered]
        return 1


def export_tenant_homes(homes) -> dict:
    """Sticky-home map → JSON-able state (``core/durable.py``): the
    ``(tenant, prefix-crc32)`` → home-shard assignments, LRU order
    preserved.  Losing these on restart sent every tenant through a
    fresh freest-first assignment — re-installing (and LRU-thrashing)
    its prefix on whatever shard happened to be free, the exact scatter
    sticky routing exists to prevent."""
    return {
        "records": len(homes),
        "homes": [
            [tenant, int(crc), int(shard)]
            for (tenant, crc), shard in homes.items()
        ],
    }


def import_tenant_homes(homes, state: dict, *, shards: int,
                        limit: int = 4096) -> int:
    """Inverse of :func:`export_tenant_homes` into a live OrderedDict;
    assignments pointing past the new plane's shard count are dropped
    (trust the observed world: a smaller restart plane has no shard to
    go home to)."""
    recovered = 0
    for entry in state.get("homes") or ():
        try:
            tenant, crc, shard = entry
            tenant, crc, shard = str(tenant), int(crc), int(shard)
        except (TypeError, ValueError):
            continue
        if not 0 <= shard < shards:
            continue
        homes[(tenant, crc)] = shard
        homes.move_to_end((tenant, crc))
        recovered += 1
        while len(homes) > limit:
            homes.popitem(last=False)
    return recovered


def prefix_pool_key(tenant: str, prefix_ids) -> tuple[str, int]:
    """The pool's entry key: (tenant, content checksum).  A tenant that
    rotates its system prompt gets a fresh entry instead of silently
    decoding against the stale cached KV; crc32 keeps the key
    deterministic across runs (Python's ``hash`` is salted)."""
    import numpy as np

    ids = np.asarray(prefix_ids, np.int32).reshape(-1)
    return (tenant, zlib.crc32(ids.tobytes()))


class PrefixPool:
    """N resident prefix-cache entries per shard, LRU-evicted.

    The device side is one stacked cache buffer per layer entry —
    ``[shards * entries, heads, max_seq_len, head_dim]`` rows in the
    batcher's exact cache layout (bf16 k/v or int8 codes+scales, gpt or
    llama) — so the admission insert can *gather* each request's prefix
    KV by entry index inside its one compiled call.  The host side is
    one ``OrderedDict`` per shard mapping entry key -> local pool slot:
    a **hit** touches LRU and returns the global row (no forward, no
    transfer — the gather happens inside the insert that was running
    anyway); a **miss** prefills the prefix ONCE
    (:func:`~.decode.prefill_prefix` or the family/layout variant) and
    splices it into the victim's pool row with one small jitted write —
    an occasional amortized event, never on the per-cycle path.

    What the pool does NOT share across tenants: entries are keyed by
    (tenant, prefix checksum), so two tenants with byte-identical
    prefixes still get separate entries — residency is a per-tenant
    resource (one tenant's eviction pressure must not silently revoke
    another's cache hit), and nothing decoded from one tenant's prefix
    entry is ever visible to another tenant's requests.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        *,
        entries: int,
        prefix_len: int,
        shards: int = 1,
        family: str = "gpt",
        quantized_kv: bool = False,
        mesh: Any = None,
    ) -> None:
        if entries < 1:
            raise ValueError(f"entries={entries} must be >= 1")
        if prefix_len < 1:
            raise ValueError(f"prefix_len={prefix_len} must be >= 1")
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        if prefix_len > config.max_seq_len:
            raise ValueError(
                f"prefix_len={prefix_len} exceeds max_seq_len="
                f"{config.max_seq_len}"
            )
        self.params = params
        self.config = config
        self.entries = entries
        self.prefix_len = prefix_len
        self.shards = shards
        self.family = family
        self.quantized_kv = quantized_kv
        # the stacked device rows, in the batcher's cache layout
        if quantized_kv:
            from .decode import init_quantized_cache

            cache = init_quantized_cache(
                config, shards * entries,
                kv_heads=(config.n_kv_heads if family == "llama"
                          else None),
            )
        elif family == "llama":
            from .llama import init_llama_cache

            cache = init_llama_cache(config, shards * entries)
        else:
            from .decode import init_cache

            cache = init_cache(config, shards * entries)
        self.layers = cache["layers"]
        # mesh-sharded pool rows: heads split over the "model" axis so
        # the admission insert's entry gather stays device-local per
        # shard of the mesh (the entry axis itself is replicated —
        # every device sees every entry index, only the head slices
        # differ).  Commit the stacked rows under those shardings up
        # front; the donated install write then preserves them.
        self.mesh = mesh
        if mesh is not None:
            import jax

            self.layers = jax.device_put(
                self.layers, self.layer_shardings(mesh)
            )
        # attach point for a comms CollectiveScheduler: installs are
        # recorded as PREFIX_INSTALL transfer ops when set
        self.comms = None
        # key -> local slot, per shard, in LRU order (oldest first)
        self._lru: list[OrderedDict] = [
            OrderedDict() for _ in range(shards)
        ]
        # slots handed back by evict_cold, reused lowest-first; fresh
        # slots are minted from _next_slot while any remain.  (After a
        # cold eviction len(lru) no longer names the next fresh slot,
        # so installs must never derive a slot from it — a collision
        # would silently share one KV row between two tenants.)
        self._free_slots: list[list[int]] = [[] for _ in range(shards)]
        self._next_slot: list[int] = [0] * shards
        # residency ceiling within the allocated arena (the prefix_pool
        # engine knob, sched/knobs.py): installs evict down to it, so
        # lowering it live shrinks the pool's working footprint without
        # a realloc.  Defaults to the full allocation = today's
        # behavior byte for byte.
        self.capacity = entries
        self.hits = 0
        self.misses = 0
        self.installs = 0
        self.evictions = 0
        self.events: deque[_PoolEvent] = deque(maxlen=1024)
        self._write_jit = None

    def layer_shardings(self, mesh):
        """Per-layer NamedShardings for the stacked pool rows: the
        entry axis replicated, heads over the mesh's ``model`` axis —
        the same split :func:`planes.mesh.prefix_cache_shardings`-style
        callers use for the live cache, so the pooled gather composes
        with ``--model-parallel`` without a resharding hop."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = []
        for layer in self.layers:
            row = {}
            for name, buf in layer.items():
                spec = (P(None, "model", None, None) if buf.ndim == 4
                        else P(None, "model", None))
                row[name] = NamedSharding(mesh, spec)
            out.append(row)
        return out

    def resident(self, shard: int, key) -> bool:
        """Residency probe for the sticky router — never touches LRU."""
        return key in self._lru[shard]

    def resident_keys(self, shard: int) -> list:
        return list(self._lru[shard])

    def _prefill_entry(self, prefix_ids):
        """The ONE-TIME prefix prefill (the cost a pool hit amortizes
        away), through the family/layout prefill-prefix variant."""
        if self.quantized_kv:
            if self.family == "llama":
                from .llama import (
                    llama_quantized_prefill_prefix as build,
                )
            else:
                from .decode import quantized_prefill_prefix as build
        elif self.family == "llama":
            from .llama import llama_prefill_prefix as build
        else:
            from .decode import prefill_prefix as build
        return build(self.params, prefix_ids, self.config)

    def _write_entry(self, entry_cache, index: int) -> None:
        """Splice a batch-1 prefix cache into pool row ``index`` — one
        small jitted program (pool buffers donated, so the stacked rows
        roll in place install after install)."""
        import jax
        import jax.numpy as jnp

        if self._write_jit is None:
            def write(pool_layers, entry_layers, idx):
                out = []
                for pool_layer, entry in zip(pool_layers, entry_layers):
                    row = {}
                    for name, buf in pool_layer.items():
                        piece = entry[name]
                        start = (idx,) + (
                            jnp.zeros((), jnp.int32),
                        ) * (buf.ndim - 1)
                        row[name] = jax.lax.dynamic_update_slice(
                            buf, piece, start
                        )
                    out.append(row)
                return out

            self._write_jit = jax.jit(write, donate_argnums=(0,))
        entry_layers = entry_cache["layers"]
        if self.mesh is not None:
            # the one-time prefill runs single-device, so its batch-1
            # cache is committed to one chip while the donated pool
            # rows live mesh-sharded — resharding the entry under the
            # pool's own specs first keeps the donated write's device
            # sets compatible (and splits the splice per model shard)
            entry_layers = jax.device_put(
                entry_layers, self.layer_shardings(self.mesh)
            )
        self.layers = self._write_jit(
            self.layers, entry_layers,
            jnp.asarray(index, jnp.int32),
        )

    def acquire(self, shard: int, key, prefix_ids) -> int:
        """Return the GLOBAL pool row holding ``key``'s prefix KV on
        ``shard``, installing (and LRU-evicting) on a miss.  The
        returned index feeds the admission insert's device-side
        gather."""
        import numpy as np

        if not 0 <= shard < self.shards:
            raise ValueError(f"shard {shard} out of range "
                             f"[0, {self.shards})")
        lru = self._lru[shard]
        slot = lru.get(key)
        if slot is not None:
            lru.move_to_end(key)
            self.hits += 1
            return shard * self.entries + slot
        self.misses += 1
        ids = np.asarray(prefix_ids, np.int32).reshape(-1)
        if ids.size != self.prefix_len:
            raise ValueError(
                f"pooled prefixes are a static {self.prefix_len}-token "
                f"bucket; got {ids.size} tokens (the worker prepends "
                "off-bucket prefixes to the prompt instead)"
            )
        if len(lru) >= self.capacity:
            # at the residency ceiling (the live prefix_pool knob; ==
            # the allocation by default, where this reduces to the old
            # arena-exhausted branch): evict the LRU victim.  Same-
            # batch safety holds because the knob floor keeps capacity
            # >= per-shard slots (sched/knobs.py validates).
            victim, slot = lru.popitem(last=False)
            self.evictions += 1
            self.events.append(_PoolEvent(
                "prefix-evict", time.perf_counter(),
                {"shard": shard, "tenant": victim[0], "slot": slot},
            ))
        elif self._free_slots[shard]:
            import heapq

            slot = heapq.heappop(self._free_slots[shard])
        else:
            slot = self._next_slot[shard]
            self._next_slot[shard] += 1
        entry = self._prefill_entry(ids)
        self._write_entry(entry, shard * self.entries + slot)
        lru[key] = slot
        self.installs += 1
        self.events.append(_PoolEvent(
            "prefix-install", time.perf_counter(),
            {"shard": shard, "tenant": key[0], "slot": slot},
        ))
        if self.comms is not None and self.comms.enabled:
            from ..comms.ops import PREFIX_INSTALL, array_nbytes

            self.comms.record(
                PREFIX_INSTALL, f"pool:{shard}",
                nbytes=array_nbytes(entry["layers"]),
                args={"shard": shard, "slot": slot},
            )
        return shard * self.entries + slot

    def evict_cold(self, keep: int) -> int:
        """Evict LRU-cold entries down to ``keep`` resident per shard —
        the overload ladder's tier-2 action (shrink the pool's LIVE
        footprint under memory pressure so the hottest tenants keep
        their hits while cold residency stops pinning HBM rows).
        Returns the number evicted; idempotent once resident <= keep.
        Only host bookkeeping changes — the device rows are simply
        reusable again, so this can never corrupt an in-flight gather
        (an already-dispatched insert holds its own buffer reference).
        """
        if keep < 0:
            raise ValueError(f"keep={keep} must be >= 0")
        import heapq

        evicted = 0
        for shard, lru in enumerate(self._lru):
            while len(lru) > keep:
                victim, slot = lru.popitem(last=False)
                heapq.heappush(self._free_slots[shard], slot)
                self.evictions += 1
                evicted += 1
                self.events.append(_PoolEvent(
                    "prefix-evict", time.perf_counter(),
                    {"shard": shard, "tenant": victim[0], "slot": slot,
                     "reason": "pressure"},
                ))
        return evicted

    def set_capacity(self, capacity: int) -> int:
        """Move the pool's residency ceiling within the allocated
        arena — the live ``prefix_pool`` engine knob.  Shrinking
        evicts LRU-cold entries down to the new ceiling NOW (returns
        how many); growing simply re-opens headroom up to the
        allocation.  The arena itself never reallocates (that is a
        redeploy, not a knob), and the caller (sched/knobs.py) holds
        the ``>= per-shard slots`` floor that keeps same-batch
        eviction corruption impossible."""
        capacity = int(capacity)
        if not 1 <= capacity <= self.entries:
            raise ValueError(
                f"capacity={capacity} must be in [1, {self.entries}] "
                "(the allocated arena)"
            )
        self.capacity = capacity
        return self.evict_cold(capacity)

    def trace_events(self, time_origin: float | None = None) -> list[dict]:
        """The pool's install/evict decisions as Chrome-trace instant
        events (``prefix-*`` names land in their own ``"prefix"``
        category; merge into a tick trace via
        ``to_chrome_trace(..., extra_events=...)`` like the fleet's)."""
        from ..obs.trace import instant_trace_events

        return instant_trace_events(self.events, time_origin)

    def stats(self) -> dict:
        return {
            "entries_per_shard": self.entries,
            "shards": self.shards,
            "prefix_len": self.prefix_len,
            "hits": self.hits,
            "misses": self.misses,
            "installs": self.installs,
            "evictions": self.evictions,
            "resident": [len(lru) for lru in self._lru],
        }
