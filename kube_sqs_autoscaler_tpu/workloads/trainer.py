"""Trainer binary: ``python -m kube_sqs_autoscaler_tpu.workloads.trainer``.

The end-to-end training entry point that wires the whole workload layer
together: multi-host init (:mod:`.distributed`), a topology-aware
``("data", "seq", "model")`` mesh, the sharded train step with every knob
(:mod:`.train`: remat, grad accumulation, warmup-cosine schedule;
:mod:`.zigzag` for balanced long-context), the prefetching input pipeline
(:mod:`.data`), orbax checkpoint/resume (:mod:`.checkpoint`), and JAX
device tracing (:mod:`..utils.profiling`).

The built-in data source is the synthetic token stream (deterministic,
dependency-free — this repo's workload is a *reference* workload, see the
package docstring); swap ``make_batches`` for a real corpus iterator to
train on data.  Everything else is production-shaped.

The reference (``/root/reference``) has no trainer — it is a 290-line
autoscaler (SURVEY.md §7.0); this is part of the TPU workload the
autoscaler scales.
"""

from __future__ import annotations

import argparse
import json
import logging
import time
from pathlib import Path

from ..utils.logging import configure_logging
from ..utils.platforms import honor_env_platforms as _honor_env_platforms
from ..utils.profiling import maybe_trace

log = logging.getLogger("trainer")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="kube-sqs-autoscaler-trainer")
    # model (defaults sized for a quick single-chip run)
    parser.add_argument(
        "--family", choices=("gpt", "llama"), default="gpt",
        help="gpt: learned positions/MHA/LayerNorm/GELU; "
             "llama: RoPE/GQA/RMSNorm/SwiGLU",
    )
    parser.add_argument("--vocab-size", type=int, default=8192)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-kv-heads", type=int, default=2,
                        help="llama family only: GQA KV head count")
    parser.add_argument(
        "--sliding-window", type=int, default=0, metavar="W",
        help="llama family only: Mistral-style sliding-window attention "
             "(each position attends its last W keys; 0 = full causal). "
             "Composes with --seq-parallel (windowed ring schedule) and "
             "--pipe-parallel (windowed stage kernels); not with "
             "--zigzag",
    )
    parser.add_argument("--n-layers", type=int, default=4)
    parser.add_argument(
        "--d-ff", type=int, default=None,
        help="default: 2048 (gpt GELU), 1408 (llama SwiGLU convention, "
             "matching the serving binary)",
    )
    parser.add_argument("--seq-len", type=int, default=256)
    # schedule / optimization
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--learning-rate", type=float, default=3e-4)
    parser.add_argument("--warmup-steps", type=int, default=0)
    parser.add_argument("--decay-steps", type=int, default=0)
    parser.add_argument("--grad-accum", type=int, default=1)
    parser.add_argument(
        "--grad-clip-norm", type=float, default=0.0,
        help="clip the global gradient norm before the optimizer update "
             "(0 = off)",
    )
    parser.add_argument("--remat", action="store_true")
    # parallelism
    parser.add_argument("--model-parallel", type=int, default=1)
    parser.add_argument("--seq-parallel", type=int, default=1)
    parser.add_argument(
        "--zigzag", action="store_true",
        help="balanced zig-zag schedule for the seq axis (needs seq-parallel >= 2)",
    )
    parser.add_argument(
        "--pipe-parallel", type=int, default=1,
        help="pipeline-parallel stages over a "
             "('pipe','data'[,'model'|'seq']) mesh (both families; "
             "composes with --model-parallel OR --seq-parallel — ring "
             "attention inside the GPipe stages — and with --moe/"
             "--grad-accum; not with --zigzag)",
    )
    parser.add_argument(
        "--pipe-schedule", choices=("gpipe", "1f1b"), default="gpipe",
        help="gpipe: all-forward-then-all-backward; 1f1b: interleaved, "
             "min(M, P) live stage inputs",
    )
    parser.add_argument(
        "--pipe-microbatches", type=int, default=4,
        help="microbatches per step; batch-size must divide by it",
    )
    # mixture-of-experts (both families)
    parser.add_argument(
        "--moe", action="store_true",
        help="replace the dense MLP with a top-k routed expert MLP "
             "(expert parallelism over the data axis; GELU experts for "
             "gpt, SwiGLU experts for llama)",
    )
    parser.add_argument("--moe-experts", type=int, default=8)
    parser.add_argument("--moe-top-k", type=int, default=2)
    # parameter-efficient fine-tuning
    parser.add_argument(
        "--lora-rank", type=int, default=0,
        help="train rank-N LoRA adapters on a frozen base instead of full "
             "weights (0 = off); checkpoints save the MERGED weights, so "
             "the serve binary works unchanged",
    )
    parser.add_argument("--lora-alpha", type=float, default=16.0)
    parser.add_argument(
        "--hf-checkpoint", default="", metavar="DIR",
        help="start from a Hugging Face Llama checkpoint directory "
             "(workloads.hf_convert; implies --family llama and the "
             "architecture from its config) — the usual base for "
             "--lora-rank fine-tuning",
    )
    parser.add_argument(
        "--hf-export", default="", metavar="DIR",
        help="after training, export the final weights (LoRA-merged when "
             "--lora-rank is set) as a transformers-loadable Llama/"
             "Mistral checkpoint directory (llama family only)",
    )
    parser.add_argument(
        "--topology-mesh", action="store_true",
        help="order devices along the physical ICI torus (real TPU hardware)",
    )
    # data
    parser.add_argument(
        "--data-dir", default="", metavar="DIR",
        help="train on an on-disk token corpus (*.bin shards + meta.json, "
             "see native.tokenreader.write_token_shards) through the "
             "native mmap reader; default: the synthetic stream",
    )
    # ops
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    parser.add_argument(
        "--checkpoint-keep", type=int, default=0,
        help="retain only the newest N step checkpoints (0 = keep all)",
    )
    parser.add_argument("--resume", action="store_true")
    parser.add_argument("--profile-dir", default="")
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve /metrics with tokens/s, MFU and loss gauges "
             "(0 = disabled)",
    )
    parser.add_argument("--log-every", type=int, default=10)
    parser.add_argument(
        "--eval-every", type=int, default=0, metavar="N",
        help="every N steps, evaluate mean loss on a fixed held-out set "
             "(--eval-batches batches drawn from a disjoint seed domain "
             "of the same source; 0 = no eval)",
    )
    parser.add_argument("--eval-batches", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--overfit", action="store_true",
        help="repeat the first batch every step — the standard smoke test "
             "that the whole stack can drive the loss toward zero",
    )
    return parser


def _family_forward(family: str):
    """The family's forward for objectives that take a ``forward_fn``
    seam (zig-zag): ``llama_forward`` for llama, ``None`` (the gpt
    default) otherwise."""
    if family == "llama":
        from .llama import llama_forward

        return llama_forward
    return None


def _lora_base_state(mesh, base, param_shardings_fn=None):
    """The frozen-base 'state' of a LoRA run: just the placed params —
    no optimizer moments, no step (init_lora_train_state carries those
    for the adapters).  ``param_shardings_fn`` overrides the flat layout
    rules (pipeline runs pass ``pipeline_param_shardings``)."""
    import jax

    from .train import param_shardings

    shardings_fn = param_shardings_fn or param_shardings
    return {"params": jax.device_put(base, shardings_fn(mesh, base))}


def train(args) -> dict:
    """Run the loop; returns ``{"losses": [...], "final_step": int}``."""
    import jax

    from .checkpoint import TrainCheckpointer
    from .data import (
        corpus_token_stream,
        prefetch_to_mesh,
        synthetic_token_stream,
    )
    from .distributed import initialize_from_env, make_topology_mesh
    from .model import ModelConfig, param_count
    from .train import (
        TrainConfig,
        batch_sharding,
        init_train_state,
        make_mesh,
        make_train_step,
        place_state,
    )

    initialize_from_env()
    pipe = args.pipe_parallel
    if pipe > 1:
        # the pipelined stack (either family) runs over a dedicated
        # ("pipe","data"[,"model"|"seq"]) mesh
        if args.zigzag:
            # zig-zag inside the pipeline stages: load-balanced causal
            # sp, GPipe (autodiff) or 1F1B (explicit backward); the
            # combos its objective cannot express fail fast rather than
            # silently ignore flags
            if args.seq_parallel < 2:
                raise SystemExit(
                    "--zigzag with --pipe-parallel needs "
                    "--seq-parallel >= 2"
                )
            for flag, bad in (("--moe", args.moe),
                              ("--lora-rank", bool(args.lora_rank)),
                              ("--sliding-window",
                               bool(args.sliding_window))):
                if bad:
                    raise SystemExit(
                        f"--zigzag with --pipe-parallel does not combine "
                        f"with {flag}"
                    )
        if args.batch_size % args.pipe_microbatches:
            raise SystemExit(
                f"--batch-size {args.batch_size} not divisible by "
                f"--pipe-microbatches {args.pipe_microbatches}"
            )
        if args.seq_parallel > 1:
            # pp x sp (ring attention inside the stages, both schedules)
            # and the full 4-axis pp x sp x tp (Megatron shards inside
            # the ring-attention stages) both compose
            if args.moe:
                raise SystemExit(
                    "--moe with --pipe-parallel does not combine with "
                    "--seq-parallel"
                )
    if args.sliding_window < 0:
        raise SystemExit(
            f"--sliding-window {args.sliding_window} must be >= 0 "
            "(0 = full causal)"
        )
    if args.sliding_window and args.family != "llama":
        raise SystemExit(
            "--sliding-window is a llama-family knob (the gpt family has "
            "no windowed config)"
        )
    if args.sliding_window and args.hf_checkpoint:
        raise SystemExit(
            "--sliding-window does not combine with --hf-checkpoint (the "
            "HF config carries the architecture, window included — a "
            "Mistral import brings its own)"
        )
    if args.lora_rank:
        # adapters wrap every targeted matmul weight — flat 2-D,
        # stage-stacked, or per-expert stacks (3-D flat, 4-D stacked;
        # the router stays frozen).  Resume, grad-accum, zig-zag
        # (permutes the batch, not the params), pipelines under BOTH
        # schedules (1F1B chain-rules stage grads into adapter grads),
        # and MoE — flat or pipelined — all compose; moe x zigzag lora
        # stays out of scope and fails fast.
        if args.moe and args.zigzag:
            raise SystemExit(
                "--lora-rank with --moe does not combine with --zigzag"
            )
    if args.hf_checkpoint:
        if args.moe:
            raise SystemExit(
                "--hf-checkpoint is a llama-family base; it does not "
                "combine with --moe"
            )
        if args.family != "llama":
            log.info("--hf-checkpoint implies --family llama")
            args.family = "llama"
    if args.eval_every > 0:
        # fail fast with the other combo checks, before any device work
        if args.eval_batches < 1:
            raise SystemExit(
                "--eval-every needs --eval-batches >= 1"
            )
    if args.hf_export:
        for flag, bad in (("--family gpt", args.family != "llama"
                           and not args.hf_checkpoint),
                          ("--moe", args.moe)):
            if bad:
                raise SystemExit(
                    f"--hf-export writes llama-family checkpoints; it "
                    f"does not combine with {flag}"
                )
        try:
            # probe BEFORE training: discovering a missing torch after a
            # long run (with no --checkpoint-dir) would lose the weights
            import torch  # noqa: F401
            import transformers  # noqa: F401
        except ImportError as err:
            raise SystemExit(
                f"--hf-export needs torch + transformers ({err})"
            ) from err
    train_config = TrainConfig(
        learning_rate=args.learning_rate, warmup_steps=args.warmup_steps,
        decay_steps=args.decay_steps, remat=args.remat,
        grad_accum=args.grad_accum, grad_clip_norm=args.grad_clip_norm,
    )
    if pipe > 1:
        if args.topology_mesh:
            from .distributed import make_topology_pipeline_mesh

            mesh = make_topology_pipeline_mesh(
                pipe, model_parallel=args.model_parallel,
                seq_parallel=args.seq_parallel,
            )
        else:
            from .pipeline import make_pipeline_mesh

            mesh = make_pipeline_mesh(pipe_parallel=pipe,
                                      model_parallel=args.model_parallel,
                                      seq_parallel=args.seq_parallel)
    else:
        mesh_fn = make_topology_mesh if args.topology_mesh else make_mesh
        mesh = mesh_fn(model_parallel=args.model_parallel,
                       seq_parallel=args.seq_parallel)
    log.info("Mesh: %s over %d devices", dict(mesh.shape), mesh.size)

    # per-family d_ff default: llama's SwiGLU convention differs from the
    # gpt GELU MLP, and must match the serving binary's LlamaConfig
    d_ff = args.d_ff if args.d_ff is not None else (
        1408 if args.family == "llama" else 2048
    )

    # one construction site: every moe consumer (state init, step
    # builders, eval, the manifest) reads this binding
    moe_config = None
    if args.moe:
        from .moe import MoeConfig

        moe_config = MoeConfig(n_experts=args.moe_experts,
                               top_k=args.moe_top_k)

    hf_base = None
    if args.family == "llama":
        from .llama import (
            LlamaConfig,
            init_llama_train_state,
            make_llama_train_step,
        )

        if args.hf_checkpoint:
            from .hf_convert import load_hf_llama

            model_config, hf_base = load_hf_llama(args.hf_checkpoint)
            log.info(
                "HF base: %s (d_model=%d layers=%d heads=%d/%d)",
                args.hf_checkpoint, model_config.d_model,
                model_config.n_layers, model_config.n_heads,
                model_config.n_kv_heads,
            )
            if model_config.max_seq_len < args.seq_len:
                raise SystemExit(
                    f"HF model max_seq_len={model_config.max_seq_len} < "
                    f"--seq-len {args.seq_len}"
                )
        else:
            model_config = LlamaConfig(
                vocab_size=args.vocab_size, d_model=args.d_model,
                n_heads=args.n_heads, n_kv_heads=args.n_kv_heads,
                n_layers=args.n_layers, d_ff=d_ff,
                max_seq_len=args.seq_len,
                sliding_window=args.sliding_window or None,
            )
        if pipe > 1:
            from .pipeline import (
                as_llama_pipeline_params,
                init_llama_pipeline_train_state,
                place_pipeline_state,
            )

            if hf_base is not None and model_config.n_layers % pipe:
                raise SystemExit(
                    f"HF model has n_layers={model_config.n_layers}, "
                    f"not divisible by --pipe-parallel {pipe}"
                )
            if args.lora_rank:
                # frozen stage-stacked base, params only (no full-model
                # Adam moments — the LoRA point, same as the flat branch);
                # --moe freezes a routed base (per-expert adapters)
                from .pipeline import (
                    init_llama_pipeline_params,
                    pipeline_param_shardings,
                )

                if args.moe:
                    from .moe import init_llama_moe_params
                    from .pipeline import as_llama_pipeline_params as _stack

                    if model_config.n_layers % pipe:
                        # same clear error the non-MoE init paths raise
                        # (vs an opaque sharding-divisibility failure at
                        # placement)
                        raise SystemExit(
                            f"n_layers={model_config.n_layers} not "
                            f"divisible by n_stages={pipe}"
                        )
                    base = _stack(init_llama_moe_params(
                        jax.random.key(args.seed), model_config, moe_config
                    ))
                elif hf_base is not None:
                    base = as_llama_pipeline_params(hf_base)
                else:
                    base = init_llama_pipeline_params(
                        jax.random.key(args.seed), model_config, pipe
                    )
                state = _lora_base_state(
                    mesh, base, pipeline_param_shardings,
                )
            else:
                if hf_base is not None:
                    # fine-tune the imported base THROUGH the pipeline:
                    # the flat HF weights stack into the stage layout
                    # (untied lm_head rides along — both schedules
                    # support it)
                    fresh = init_train_state(
                        jax.random.key(args.seed), model_config,
                        train_config,
                        init_fn=lambda rng, cfg: as_llama_pipeline_params(
                            hf_base
                        ),
                    )
                elif args.moe:
                    from .pipeline import init_moe_pipeline_train_state

                    fresh = init_moe_pipeline_train_state(
                        jax.random.key(args.seed), model_config, moe_config,
                        train_config, n_stages=pipe, llama=True,
                    )
                else:
                    fresh = init_llama_pipeline_train_state(
                        jax.random.key(args.seed), model_config,
                        train_config, n_stages=pipe,
                    )
                state = place_pipeline_state(mesh, fresh)
        elif args.moe and args.lora_rank:
            # frozen routed base, params only (adapters get per-expert
            # factors; see the lora combo checks above)
            from .moe import init_llama_moe_params

            state = _lora_base_state(
                mesh,
                init_llama_moe_params(jax.random.key(args.seed),
                                      model_config, moe_config),
            )
        elif args.moe:
            from .moe import init_llama_moe_train_state

            state = place_state(
                mesh,
                init_llama_moe_train_state(
                    jax.random.key(args.seed), model_config, moe_config,
                    train_config,
                ),
            )
        elif args.lora_rank:
            # params only: the base is frozen, so no full-model Adam
            # moments are ever materialized (the whole point of LoRA —
            # peak HBM stays at 1x the base, not 3x)
            from .llama import init_llama_params

            state = _lora_base_state(
                mesh,
                hf_base if hf_base is not None
                else init_llama_params(jax.random.key(args.seed),
                                       model_config),
            )
        elif hf_base is not None:
            # same state shape as a fresh init, with the imported weights
            # as the starting point (full fine-tune)
            state = place_state(
                mesh,
                init_train_state(
                    jax.random.key(args.seed), model_config, train_config,
                    init_fn=lambda rng, cfg: hf_base,
                ),
            )
        else:
            state = place_state(
                mesh,
                init_llama_train_state(jax.random.key(args.seed),
                                       model_config, train_config),
            )
    else:
        model_config = ModelConfig(
            vocab_size=args.vocab_size, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers, d_ff=d_ff,
            max_seq_len=args.seq_len,
        )
        if pipe > 1:
            from .pipeline import (
                init_pipeline_train_state,
                place_pipeline_state,
            )

            if args.lora_rank:
                # frozen stage-stacked base, params only (see llama
                # branch); --moe freezes a routed base
                from .pipeline import (
                    init_pipeline_params,
                    pipeline_param_shardings,
                )

                if args.moe:
                    from .moe import init_moe_params
                    from .pipeline import as_pipeline_params as _stack

                    if model_config.n_layers % pipe:
                        # same clear error the non-MoE init paths raise
                        raise SystemExit(
                            f"n_layers={model_config.n_layers} not "
                            f"divisible by n_stages={pipe}"
                        )
                    base = _stack(init_moe_params(
                        jax.random.key(args.seed), model_config, moe_config
                    ))
                else:
                    base = init_pipeline_params(
                        jax.random.key(args.seed), model_config, pipe
                    )
                state = _lora_base_state(
                    mesh, base, pipeline_param_shardings,
                )
            else:
                if args.moe:
                    from .pipeline import init_moe_pipeline_train_state

                    fresh = init_moe_pipeline_train_state(
                        jax.random.key(args.seed), model_config, moe_config,
                        train_config, n_stages=pipe,
                    )
                else:
                    fresh = init_pipeline_train_state(
                        jax.random.key(args.seed), model_config,
                        train_config, n_stages=pipe,
                    )
                state = place_pipeline_state(mesh, fresh)
        elif args.moe and args.lora_rank:
            # frozen routed base, params only (see llama branch)
            from .moe import init_moe_params

            state = _lora_base_state(
                mesh,
                init_moe_params(jax.random.key(args.seed), model_config,
                                moe_config),
            )
        elif args.moe:
            from .moe import init_moe_train_state

            state = place_state(
                mesh,
                init_moe_train_state(jax.random.key(args.seed), model_config,
                                     moe_config, train_config),
            )
        elif args.lora_rank:
            # params only — no full-model Adam moments (see llama branch)
            from .model import init_params

            state = _lora_base_state(
                mesh, init_params(jax.random.key(args.seed), model_config)
            )
        else:
            state = place_state(
                mesh, init_train_state(jax.random.key(args.seed), model_config,
                                       train_config)
            )
    log.info("Model: %s parameters", f"{param_count(state['params']):,}")

    # --lora-rank: swap the full train state for frozen base + adapters.
    # save_state maps the in-memory state to its checkpointed form —
    # identity normally; for LoRA the MERGED weights (+ step), so the
    # serve binary and restore_params work on LoRA checkpoints unchanged.
    lora_cfg = lora_frozen = None
    save_state = lambda s: s  # noqa: E731
    if args.lora_rank:
        from .lora import (
            LoraConfig,
            init_lora_train_state,
            init_pipeline_lora_train_state,
            lora_checkpoint_state,
            lora_param_count,
            lora_pipeline_checkpoint_state,
        )

        lora_cfg = LoraConfig(rank=args.lora_rank, alpha=args.lora_alpha)
        lora_frozen = state["params"]  # placed on the mesh, never updated
        init_adapters = (
            init_pipeline_lora_train_state if pipe > 1
            else init_lora_train_state
        )
        state = init_adapters(
            jax.random.key(args.seed + 1), lora_frozen, lora_cfg,
            train_config,
        )
        # checkpoints carry the MERGED weights (so serving and hf-export
        # read them like any flat checkpoint — a pipelined run unstacks
        # them to the same flat layout) plus the adapter train state
        # under "lora" — what restore_lora resumes from
        if pipe > 1:
            save_state = lambda s: lora_pipeline_checkpoint_state(  # noqa: E731
                lora_frozen, s, lora_cfg, llama=args.family == "llama"
            )
        else:
            save_state = lambda s: lora_checkpoint_state(  # noqa: E731
                lora_frozen, s, lora_cfg
            )
        log.info(
            "LoRA: rank %d, %s adapter parameters (base frozen)",
            args.lora_rank, f"{lora_param_count(state['adapters']):,}",
        )

    checkpointer = (
        TrainCheckpointer(args.checkpoint_dir, keep=args.checkpoint_keep)
        if args.checkpoint_dir else None
    )
    if checkpointer:
        latest = checkpointer.latest_step()
        if latest is not None and not args.resume:
            # fail fast: orbax refuses to overwrite an existing step, so
            # without --resume this run would crash at its first save —
            # after training for checkpoint_every steps.  Checked BEFORE
            # the manifest write so an aborted mistaken re-run cannot
            # clobber the dir's manifest with a different architecture.
            raise SystemExit(
                f"checkpoint dir {args.checkpoint_dir} already has step "
                f"{latest}; pass --resume to continue it or use a fresh dir"
            )
        # train→serve handoff: record the architecture next to the
        # checkpoints so a serving worker pointed at this directory can
        # reconstruct the exact model without repeating these flags.  On
        # resume an existing manifest must MATCH, never be overwritten —
        # and the check runs BEFORE the orbax restore, so a layout or
        # architecture mismatch is a one-line SystemExit, not a pytree
        # error deep inside orbax.
        from .checkpoint import MODEL_MANIFEST, load_model_layout, \
            load_model_manifest, save_model_manifest

        if args.moe:
            # moe-first: restore_params refuses "moe" checkpoints with a
            # clear error (no routed serving forward) — a pp+moe dir
            # must say moe, not pipeline, or the serve-side unstack
            # would fail deep in orbax instead
            layout = {"kind": "moe", "n_experts": args.moe_experts,
                      "top_k": args.moe_top_k}
            if pipe > 1:
                layout["pipeline_stages"] = pipe
            if args.lora_rank:
                # moe-first kind (restore_params must keep refusing to
                # serve routed weights) + the lora resume record (a
                # different rank or seed must fail loudly, like the
                # dense lora layout)
                layout["lora_rank"] = args.lora_rank
                layout["seed"] = args.seed
        elif args.lora_rank:
            # params on disk are flat MERGED weights (serving reads them
            # unchanged — a pipelined run unstacks before storing); the
            # record is what makes a dense re-run of a lora dir (or a
            # different rank) fail loudly, and marks the "lora" subtree
            # restore_lora resumes from.  seed/base are part of the
            # record because resume REBUILDS the frozen base from them —
            # a different seed or HF source would silently continue
            # against a different base; pipeline_stages likewise (the
            # stacked adapter shapes depend on it)
            layout = {"kind": "lora", "rank": args.lora_rank,
                      "seed": args.seed, "base": args.hf_checkpoint or ""}
            if pipe > 1:
                layout["pipeline_stages"] = pipe
        elif pipe > 1:
            layout = {"kind": "pipeline", "n_stages": pipe}
        else:
            layout = None
        manifest_path = Path(args.checkpoint_dir) / MODEL_MANIFEST
        if manifest_path.exists():
            prior_family, prior_config = load_model_manifest(
                args.checkpoint_dir
            )
            prior_layout = load_model_layout(args.checkpoint_dir)
            if (prior_layout, layout) == (None, None) or (
                prior_layout is not None and layout is not None
            ):
                mismatch = (prior_family, prior_config, prior_layout) != (
                    args.family, model_config, layout
                )
                hint = ""
            else:
                # a manifest with no layout record cannot distinguish a
                # dense run from a pre-layout-record --moe run, and
                # guessing wrong would corrupt the manifest — refuse with
                # the migration step instead of auto-upgrading
                mismatch = True
                hint = (
                    "; if this dir WAS trained with these exact flags "
                    "before the layout record existed, add "
                    f'"layout": {json.dumps(layout)} to its '
                    "model_config.json"
                    if layout is not None else ""
                )
            if mismatch:
                raise SystemExit(
                    f"checkpoint dir {args.checkpoint_dir} was written by a "
                    f"{prior_family} run with {prior_config} "
                    f"(layout={prior_layout}); this run's flags describe a "
                    f"different model ({args.family}, {model_config}, "
                    f"layout={layout}){hint}"
                )
        else:
            save_model_manifest(args.checkpoint_dir, args.family,
                                model_config, layout=layout)
        if args.resume and latest is not None:
            if args.lora_rank:
                # adapter-only partial restore; the frozen base was just
                # rebuilt above from the same seed / HF source
                state = checkpointer.restore_lora(mesh, state)
            else:
                shardings_fn = None
                if pipe > 1:
                    from .pipeline import pipeline_state_shardings

                    shardings_fn = pipeline_state_shardings
                state = checkpointer.restore(
                    mesh, state, state_shardings_fn=shardings_fn
                )
            log.info("Resumed from checkpoint step %d", latest)

    pipe_config = None
    if pipe > 1:
        from .pipeline import PipelineConfig

        pipe_config = PipelineConfig(
            n_microbatches=args.pipe_microbatches,
            schedule=args.pipe_schedule,
        )

    if args.lora_rank and pipe > 1:
        from .lora import make_lora_pipeline_train_step

        step_fn = make_lora_pipeline_train_step(
            mesh, model_config, pipe_config, train_config, lora_frozen,
            state, lora_cfg, llama=args.family == "llama",
            moe=(moe_config if args.moe else None),
        )
    elif args.lora_rank:
        from functools import partial as _partial

        from .lora import make_lora_train_step

        loss = None
        if args.zigzag:
            # permuted-order objective through the same loss seam: the
            # adapters wrap flat params, so zig-zag composes like any
            # other objective
            from .zigzag import make_zigzag_loss

            loss = make_zigzag_loss(
                mesh, model_config, remat=train_config.remat,
                forward_fn=_family_forward(args.family),
            )
        elif args.moe:
            # adapter-only fine-tuning of a frozen routed base: the
            # routed objective (aux term included) through the same
            # loss seam; the router stays frozen with the base
            from .moe import _require_no_remat, llama_moe_loss_fn, moe_loss_fn

            _require_no_remat(train_config)
            moe_fn = (
                llama_moe_loss_fn if args.family == "llama" else moe_loss_fn
            )
            loss = _partial(moe_fn, config=model_config, moe=moe_config)
        elif args.family == "llama":
            from .llama import llama_mesh_loss

            loss = llama_mesh_loss(model_config, train_config)
        step_fn = make_lora_train_step(
            mesh, model_config, train_config, lora_frozen, state, lora_cfg,
            loss=loss,
        )
    elif pipe > 1:
        from .pipeline import (
            make_llama_pipeline_train_step,
            make_moe_pipeline_train_step,
            make_pipeline_train_step,
            make_zigzag_pipeline_train_step,
        )

        if args.zigzag:
            step_fn = make_zigzag_pipeline_train_step(
                mesh, model_config, pipe_config, train_config, state,
                llama=args.family == "llama",
            )
        elif args.moe:
            step_fn = make_moe_pipeline_train_step(
                mesh, model_config, moe_config, pipe_config, train_config,
                state, llama=args.family == "llama",
            )
        else:
            make_pp_step = (
                make_llama_pipeline_train_step if args.family == "llama"
                else make_pipeline_train_step
            )
            step_fn = make_pp_step(mesh, model_config, pipe_config,
                                   train_config, state)
    elif args.moe and args.zigzag:
        from .moe import make_zigzag_moe_train_step

        step_fn = make_zigzag_moe_train_step(
            mesh, model_config, moe_config, train_config, state,
            llama=args.family == "llama",
        )
    elif args.moe and args.family == "llama":
        from .moe import make_llama_moe_train_step

        step_fn = make_llama_moe_train_step(mesh, model_config, moe_config,
                                            train_config, state)
    elif args.moe:
        from .moe import make_moe_train_step

        step_fn = make_moe_train_step(mesh, model_config, moe_config,
                                      train_config, state)
    elif args.zigzag:
        from .zigzag import make_zigzag_train_step

        step_fn = make_zigzag_train_step(
            mesh, model_config, train_config, state,
            forward_fn=_family_forward(args.family),
        )
    elif args.family == "llama":
        step_fn = make_llama_train_step(mesh, model_config, train_config,
                                        state)
    else:
        step_fn = make_train_step(mesh, model_config, train_config, state)

    losses = []
    start_step = int(jax.device_get(state["step"]))
    last_saved = start_step if args.resume else None

    # --- held-out evaluation (fixed batches, pure loss, no update) -------
    # every training layout evaluates: dense (either family, LoRA too)
    # through the family loss, MoE through its routed forward (pure LM
    # NLL — the aux load-balance term is a training regularizer, not a
    # quality signal), zig-zag through its permuted-order loss, pipeline
    # through the microbatched pipeline loss.
    eval_fn = eval_data = None
    if args.eval_every > 0:
        from functools import partial as _partial

        if pipe > 1:
            from .pipeline import (
                llama_pipeline_loss_fn,
                moe_pipeline_loss_fn,
                pipeline_loss_fn,
                zigzag_pipeline_loss_fn,
            )

            if args.zigzag:
                # permuted-order objective, same value as the natural one
                pp_eval = _partial(
                    zigzag_pipeline_loss_fn, config=model_config,
                    pcfg=pipe_config, mesh=mesh,
                    llama=args.family == "llama",
                )
            elif args.moe:
                # pure LM NLL through the pipelined routed forward
                pp_eval = _partial(
                    moe_pipeline_loss_fn, config=model_config,
                    moe=moe_config, pcfg=pipe_config, mesh=mesh,
                    llama=args.family == "llama", aux_weight=0.0,
                )
            else:
                pp_loss = (
                    llama_pipeline_loss_fn if args.family == "llama"
                    else pipeline_loss_fn
                )
                pp_eval = _partial(pp_loss, config=model_config,
                                   pcfg=pipe_config, mesh=mesh)

            if args.lora_rank:
                from .lora import apply_pipeline_lora

                def eval_fn_impl(state, tokens):
                    return pp_eval(
                        apply_pipeline_lora(lora_frozen, state["adapters"],
                                            lora_cfg),
                        tokens,
                    )
            else:
                def eval_fn_impl(state, tokens):
                    return pp_eval(state["params"], tokens)
        elif args.moe:
            from .moe import llama_moe_forward, moe_forward
            from .train import mesh_attention_fn, next_token_nll

            attend = mesh_attention_fn(
                mesh, window=getattr(model_config, "sliding_window", None)
            )
            moe_fwd = (
                llama_moe_forward if args.family == "llama" else moe_forward
            )
            if args.lora_rank:
                from .lora import apply_lora

                def moe_eval_params(state):
                    return apply_lora(lora_frozen, state["adapters"],
                                      lora_cfg)
            else:
                def moe_eval_params(state):
                    return state["params"]

            def eval_fn_impl(state, tokens):
                logits, _aux = moe_fwd(moe_eval_params(state), tokens,
                                       model_config, moe_config, attend)
                return next_token_nll(logits, tokens)
        elif args.zigzag:
            from .zigzag import make_zigzag_loss

            zz_eval_loss = make_zigzag_loss(
                mesh, model_config, forward_fn=_family_forward(args.family)
            )
            if args.lora_rank:
                from .lora import apply_lora

                def zz_eval_params(state):
                    return apply_lora(lora_frozen, state["adapters"],
                                      lora_cfg)
            else:
                def zz_eval_params(state):
                    return state["params"]

            def eval_fn_impl(state, tokens):
                return zz_eval_loss(zz_eval_params(state), tokens)
        else:
            from .train import mesh_attention_fn

            window = getattr(model_config, "sliding_window", None)
            attend = mesh_attention_fn(mesh, window=window)
            if args.family == "llama":
                from .llama import llama_mesh_loss

                base_loss = llama_mesh_loss(model_config, train_config)
            else:
                from .train import loss_fn as _loss_fn

                base_loss = _partial(_loss_fn, config=model_config,
                                     remat=train_config.remat)

            if args.lora_rank:
                from .lora import apply_lora

                def eval_fn_impl(state, tokens):
                    return base_loss(
                        apply_lora(lora_frozen, state["adapters"], lora_cfg),
                        tokens, attention_fn=attend,
                    )
            else:
                def eval_fn_impl(state, tokens):
                    return base_loss(state["params"], tokens,
                                     attention_fn=attend)

        eval_fn = jax.jit(eval_fn_impl)
        # a fixed held-out set from a disjoint seed domain of the same
        # source — reproducible across runs and resumes
        eval_seed = args.seed + 0x5EED
        if args.data_dir:
            eval_stream = corpus_token_stream(
                args.data_dir, args.batch_size, args.seq_len,
                seed=eval_seed, start_step=0,
            )
        else:
            eval_stream = synthetic_token_stream(
                model_config.vocab_size, args.batch_size, args.seq_len,
                seed=eval_seed,
            )
        if pipe > 1:
            from .pipeline import pipeline_batch_sharding

            m = args.pipe_microbatches
            shard = pipeline_batch_sharding(mesh)
            eval_data = [
                jax.device_put(
                    (b := next(eval_stream)).reshape(
                        m, b.shape[0] // m, b.shape[1]
                    ),
                    shard,
                )
                for _ in range(args.eval_batches)
            ]
        else:
            shard = batch_sharding(mesh)
            eval_data = [
                jax.device_put(next(eval_stream), shard)
                for _ in range(args.eval_batches)
            ]

    def run_eval(state):
        total = 0.0
        for tokens in eval_data:
            total += float(eval_fn(state, tokens))
        return total / len(eval_data)

    # opt-in /metrics with the trainer's own numbers (tokens/s, MFU, loss)
    metrics = obs_server = None
    if args.metrics_port:
        from ..obs import ObservabilityServer, WorkloadMetrics

        metrics = WorkloadMetrics()
        obs_server = ObservabilityServer(metrics, port=args.metrics_port)
        obs_server.start()

    from .perf import mfu as mfu_of, train_step_flops

    step_flops = train_step_flops(model_config, args.batch_size, args.seq_len)

    if args.data_dir:
        # cheap metadata check before any shard is mmapped
        from ..native.tokenreader import read_meta

        corpus_vocab = int(read_meta(args.data_dir)["vocab_size"])
        if corpus_vocab > model_config.vocab_size:
            raise SystemExit(
                f"corpus vocab_size={corpus_vocab} exceeds the model's "
                f"vocab_size={model_config.vocab_size}"
            )
        # counter-addressed corpus: resume parity is start_step itself,
        # no batch skipping needed — except --overfit, which must pin the
        # step-0 batch on resume too (matching the synthetic branch)
        stream = corpus_token_stream(
            args.data_dir, args.batch_size, args.seq_len, seed=args.seed,
            start_step=0 if args.overfit else start_step,
        )
    else:
        stream = synthetic_token_stream(
            model_config.vocab_size, args.batch_size, args.seq_len,
            seed=args.seed,
        )
        if start_step and not args.overfit:
            # data parity on resume: skip the batches the checkpointed run
            # already consumed so 4+4 resumed steps == one 8-step run.
            for _ in range(start_step):
                next(stream)
    if args.overfit:
        import itertools

        stream = itertools.repeat(next(stream))
    if pipe > 1:
        from .pipeline import pipeline_batch_sharding

        # microbatch-major [M, B/M, S]: the pipelined step's batch type
        m = args.pipe_microbatches
        stream = (
            b.reshape(m, b.shape[0] // m, b.shape[1]) for b in stream
        )
        batches = prefetch_to_mesh(stream, pipeline_batch_sharding(mesh))
    else:
        batches = prefetch_to_mesh(stream, batch_sharding(mesh))

    log_every = max(1, args.log_every)
    # throughput is per logging interval (the float(loss) fetch below is
    # the sync point), and the interval containing the first step is
    # excluded: it is dominated by XLA compilation, not steady-state work
    interval_start = time.perf_counter()
    interval_steps = 0
    # --steps bounds the run, so tracing it (when asked) is a bounded trace
    with maybe_trace(args.profile_dir):
        for local_step in range(args.steps):
            tokens = next(batches)
            state, loss = step_fn(state, tokens)
            interval_steps += 1
            step = start_step + local_step + 1
            if local_step % log_every == 0 or local_step == args.steps - 1:
                loss_value = float(loss)  # sync point, only when logging
                losses.append(loss_value)
                now = time.perf_counter()
                rate = ""
                if local_step > 0:
                    steps_per_sec = interval_steps / (now - interval_start)
                    tokens_per_sec = (
                        steps_per_sec * args.batch_size * args.seq_len
                    )
                    # MFU is per-chip: divide the global step FLOPs across
                    # the mesh before comparing against one chip's peak
                    mfu_value = mfu_of(
                        step_flops / mesh.size, 1.0 / steps_per_sec
                    )
                    rate = f" ({steps_per_sec:.2f} steps/s, " \
                           f"{tokens_per_sec:.0f} tokens/s" + (
                               f", {mfu_value:.1%} MFU"
                               if mfu_value is not None else ""
                           ) + ")"
                    if metrics is not None:
                        metrics.set_gauge(
                            "train_tokens_per_sec", tokens_per_sec,
                            "Trainer throughput over the last log interval.",
                        )
                        metrics.set_gauge(
                            "train_steps_per_sec", steps_per_sec,
                            "Optimizer steps per second.",
                        )
                        if mfu_value is not None:
                            metrics.set_gauge(
                                "train_mfu", mfu_value,
                                "Model FLOPs utilization (per chip).",
                            )
                if metrics is not None:
                    metrics.set_gauge("train_loss", loss_value,
                                      "Last logged training loss.")
                    metrics.set_gauge("train_step", step,
                                      "Global optimizer step.")
                interval_start = now
                interval_steps = 0
                log.info("step %d loss %.4f%s", step, loss_value, rate)
            if eval_fn is not None and step % args.eval_every == 0:
                eval_loss = run_eval(state)
                log.info("step %d eval_loss %.4f (%d held-out batches)",
                         step, eval_loss, len(eval_data))
                if metrics is not None:
                    metrics.set_gauge(
                        "eval_loss", eval_loss,
                        "Mean loss on the fixed held-out batches.",
                    )
                # eval wall time (incl. its first-call compile) must not
                # be charged to the training-throughput interval
                interval_start = time.perf_counter()
                interval_steps = 0
            # checkpoint-every 0 = only the final save below
            if (checkpointer and args.checkpoint_every > 0
                    and step % args.checkpoint_every == 0):
                # async: the write streams while training continues; the
                # next save (or the final wait) fences it
                checkpointer.save(save_state(state), wait=False)
                last_saved = step
                log.info("Checkpointed step %d", step)
    final_step = int(jax.device_get(state["step"]))
    # one save_state evaluation serves both the final checkpoint and the
    # HF export (for LoRA it merges the adapters — once, and only when
    # something actually consumes the result)
    needs_final_save = checkpointer and last_saved != final_step
    final_state = (
        save_state(state) if (needs_final_save or args.hf_export) else None
    )
    if needs_final_save:
        checkpointer.save(final_state)
    elif checkpointer:
        checkpointer.wait_until_finished()  # fence the last async save
    if args.hf_export:
        from .hf_convert import save_hf_llama

        export_params = final_state["params"]
        if pipe > 1 and not args.lora_rank:
            # pipeline-trained stacks export like any other llama run:
            # unstack to the flat layout the converter writes (a LoRA
            # run's save_state already unstacked its merged weights)
            from .pipeline import unstack_llama_layers

            export_params = unstack_llama_layers(export_params)
        save_hf_llama(
            jax.device_get(export_params), model_config, args.hf_export,
        )
        log.info("Exported transformers checkpoint to %s", args.hf_export)
    if obs_server is not None:
        obs_server.stop()
    return {"losses": losses, "final_step": final_step}


def main(argv=None) -> dict:
    configure_logging()
    args = build_parser().parse_args(argv)  # --help exits before jax loads
    _honor_env_platforms()
    return train(args)


if __name__ == "__main__":
    main()
