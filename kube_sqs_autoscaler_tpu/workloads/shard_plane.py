"""The sharded serving plane: gang-stepped data-parallel engine shards.

The PR 6 fleet scales capacity by stepping N independent
:class:`~.continuous.ContinuousWorker` replicas in a sequential Python
loop — aggregate tokens/s is host-bound again, paying N block dispatches,
N settle transfers, and N refill syncs per fleet cycle.  This module
removes that Python-rate wall by re-expressing the whole fleet's decode
as ONE program over a shard axis:

- **slot state stacks along a leading shard axis** ``[S, B, ...]``
  (stored flat as ``[S*B]`` rows — the exact
  :class:`~.continuous.ContinuousBatcher` layout, so the insert, the
  liveness masks, and every cache layout variant are reused verbatim);
- **one gang-stepped decode per cycle**:
  :func:`~.decode.gang_block_decode` ``vmap``s the PR 5 block engine
  over the shard axis — all shards advance up to ``decode_block`` tokens
  in one jitted call, per-row liveness kept device-side exactly as the
  block engine does per row.  One dispatch, however many shards.  Under
  a mesh the leading shard axis partitions over ``"data"`` (GSPMD
  places whole shards per device — the ``shard_map`` layout without the
  explicit collective plumbing, and decode itself needs NO cross-shard
  communication to overlap: the NCCL/collective-synthesis literature's
  question of which collectives to hide never arises because the only
  cross-shard product is the ``[S]`` summary below);
- **one admission plane**: the host routes each refill cycle's requests
  freest-shard-first (deterministic tie-break: lowest shard index) and
  prefills them with the existing one-shot ``[M, P]`` insert over GLOBAL
  row ids — one insert dispatch per refill cycle even when the batch
  splits across shards, zero per-request host syncs;
- **one summary transfer per cycle**: the gang step returns a per-shard
  ``[S]`` free-slot summary; the host fetches it together with the
  settled block's tokens in ONE ``jax.device_get`` — overlapped with
  the next block via the inherited dispatch-ahead double buffering.
  The summary is the plane's device-confirmed depth signal (surfaced
  per shard via :meth:`ShardedBatcher.shard_stats`); the router's
  freest-first ordering reads the host's own slot bookkeeping, which
  is authoritative and transfer-free;
- **O(1) scale**: :meth:`ShardedBatcher.set_shard_active` flips a
  device-side ``[S]`` mask bit.  A deactivated shard stops admitting
  instantly (the summary reports it full; the router skips it) while
  its in-flight rows decode to completion — drain semantics without
  spawning, rebuilding, or recompiling anything.
  :class:`~..fleet.sharded.ShardedWorkerPool` actuates this through the
  unchanged :class:`~..core.types.Scaler` seam.

Greedy outputs are byte-identical to ``S`` independent single engines on
the same request stream (hard-gated in ``bench.py --suite scale``):
rows never interact across the batch axis, and the vmapped inner
computation IS the independent engine's computation.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .continuous import ContinuousBatcher


class _ProbingFlags(list):
    """``shard_probing`` as the plain mutable list the pool's
    quarantine state machine writes in place — with each write
    invalidating the plane's cached admission availability, so the
    half-open capacity cap is visible to the very next router call."""

    def __init__(self, flags, owner) -> None:
        super().__init__(flags)
        self._owner = owner

    def __setitem__(self, index, value) -> None:
        super().__setitem__(index, value)
        self._owner._invalidate_admission_cache()


class ShardedBatcher(ContinuousBatcher):
    """``shards`` gang-stepped engine shards behind one admission plane.

    Construction mirrors :class:`~.continuous.ContinuousBatcher` with
    ``batch_size`` replaced by ``shards`` x ``shard_slots`` (shard ``s``
    owns rows ``[s*shard_slots, (s+1)*shard_slots)``).  Plain decode
    path only — beam and speculative slots amortize their own device
    calls per slot, not per shard.  Everything else composes: both
    families, greedy or sampled (shards draw independent PRNG streams
    via per-shard key folding), int8 KV, shared prefix, mesh.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        *,
        shards: int,
        shard_slots: int,
        prompt_len: int,
        generate_tokens: int,
        **kwargs,
    ) -> None:
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        if shard_slots < 1:
            raise ValueError(f"shard_slots={shard_slots} must be >= 1")
        if kwargs.get("beams", 1) > 1 or kwargs.get("draft_layers", 0):
            raise ValueError(
                "the sharded plane applies to the plain continuous "
                "decode path (not beams / speculative slots)"
            )
        mesh = kwargs.get("mesh")
        if mesh is not None and shards % mesh.shape["data"]:
            # each device must hold WHOLE shards for the [S*B] -> [S, B]
            # view to stay resharding-free under the pinned row
            # sharding; checked BEFORE the base constructor allocates
            # the full cache and device-puts state across the mesh
            raise ValueError(
                f"shards ({shards}) not divisible by the mesh's data "
                f"axis ({mesh.shape['data']})"
            )
        self.shards = shards
        self.shard_slots = shard_slots
        # per-refill admission-availability cache (see
        # _admission_rows_by_shard); None = recompute on next read
        self._avail_cache: list[list[int]] | None = None
        super().__init__(
            params, config, batch_size=shards * shard_slots,
            prompt_len=prompt_len, generate_tokens=generate_tokens,
            **kwargs,
        )
        # the device-side scale mask: True = the shard admits (its free
        # slots count in the summary).  In-flight rows of a deactivated
        # shard keep decoding — drain, not kill.
        self._shard_active = jnp.ones((shards,), bool)
        # host mirror the router consults without a device read
        self.shard_admitting = [True] * shards
        # half-open probe capacity: a probing shard admits at most ONE
        # request until its health sentinel clears it (the pool's
        # quarantine state machine flips these in place, mirroring the
        # PR 4 breaker's half-open state; writes invalidate the
        # availability cache)
        self.shard_probing = _ProbingFlags([False] * shards, self)
        # deterministic shard-fault seams (sim.faults.FleetFaultPlan):
        # device [S] masks folded into every gang dispatch + host
        # mirrors for introspection.  All-False = the healthy program.
        self._shard_poison = jnp.zeros((shards,), bool)
        self._shard_wedge = jnp.zeros((shards,), bool)
        self.shard_poisoned = [False] * shards
        self.shard_wedged = [False] * shards
        # discard a flagged shard's whole settled block (nothing garbage
        # ever reaches a slot)?  Only safe when a supervisor will
        # quarantine + evacuate the rows afterwards — the device already
        # spent their budget, so WITHOUT recovery a discard strands the
        # slots forever.  ShardedWorkerPool opts in; a standalone plane
        # keeps the pre-quarantine contract (requests complete, the
        # health flag still reports the corruption).
        self.discard_bad_blocks = False
        # health sentinels, updated at each combined settle (zero extra
        # host syncs — they ride the same device_get as the tokens):
        # last settled [S] NaN flags, per-shard tokens of the settled
        # block, consecutive no-progress busy settles, and the
        # device-vs-host admission-mask mismatch flags
        self.last_health_bad: np.ndarray | None = None
        self.shard_last_progress = [0] * shards
        # gang-only progress + completions, split out of the total so a
        # probe verdict can demand evidence the DECODE path worked: an
        # admission-insert first token alone must not re-admit a shard
        # whose gang program is still faulted
        self.shard_last_gang_progress = [0] * shards
        self.shard_last_completed = [0] * shards
        self.shard_stall_cycles = [0] * shards
        self.last_settle_busy = [0] * shards
        self.mask_mismatch = [False] * shards
        # settles to ignore for mismatch detection after a mask-ON flip:
        # the settled summary is one block older than the flip, so the
        # first post-flip settle legitimately still reports 0 free
        self._mask_grace = [0] * shards
        # per-shard emitted-token counters (the per-shard tokens/s gauge)
        self.shard_tokens = [0] * shards
        # per-shard TTFT samples (bounded like the global deque) — the
        # chaos-serve bench scores healthy-shard TTFT SLOs from these
        import collections

        self.shard_ttft: list = [
            collections.deque(maxlen=1024) for _ in range(shards)
        ]
        # the last consumed [S] free-slot summary (None until a block
        # settles) — the device-confirmed depth signal behind
        # shard_stats' device_free column, fetched in the ONE combined
        # transfer per cycle alongside the block tokens
        self.last_free_summary: np.ndarray | None = None
        # gang instrumentation: cycles that dispatched a gang block and
        # combined settle transfers (the bench gates dispatches/cycle
        # == 1 and transfers/cycle <= 1 at every shard count)
        self.gang_cycles = 0
        self.summary_transfers = 0
        self._gang_fn = self._make_gang_fn()
        # the gang scan derives its block length from the key operand's
        # shape, so the live decode_block knob applies at ANY
        # constructed size (the base class only arms it past 1)
        self._block_engine = True

    # ------------------------------------------------------------------
    # Engine identity / adoption
    # ------------------------------------------------------------------

    def _engine_key(self) -> tuple:
        return super()._engine_key() + (self.shards, self.shard_slots)

    def adopt_engine(self, source: ContinuousBatcher) -> None:
        if not isinstance(source, ShardedBatcher):
            raise ValueError(
                "a sharded plane adopts from a sharded donor only"
            )
        super().adopt_engine(source)  # validates the full engine key
        self._gang_fn = source._gang_fn

    # ------------------------------------------------------------------
    # The gang step
    # ------------------------------------------------------------------

    def _make_gang_fn(self):
        """The ONE compiled decode program for all shards: the vmapped
        block engine plus the per-shard free-slot summary, flat-state
        donated so the buffers roll in place cycle after cycle."""
        from .decode import gang_block_decode

        step_fn = self._family_step_fn()
        config = self.config
        shards = self.shards
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        eos_id = self.eos_id
        fold = self.temperature > 0.0

        def gang(params, cache, current, done, remaining, keys, active,
                 poison, wedge):
            return gang_block_decode(
                params, cache, current, done, remaining, keys, active,
                config, step_fn, shards=shards, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_id=eos_id, fold_keys=fold,
                poison=poison, wedge=wedge,
            )

        if self.mesh is None:
            return jax.jit(gang, donate_argnums=(1, 2, 3, 4))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        rows = self._rows_shard
        tokens_shard = NamedSharding(self.mesh, P(None, "data"))
        return jax.jit(
            gang,
            in_shardings=(param_shardings(self.mesh, self.params),
                          self._cache_shard, rows, rows, rows, rep, rep,
                          rep, rep),
            out_shardings=(self._cache_shard, rows, rows, rows,
                           tokens_shard, rows, rep, rep),
            donate_argnums=(1, 2, 3, 4),
        )

    # ------------------------------------------------------------------
    # Scale: device-side mask flips
    # ------------------------------------------------------------------

    def set_shard_active(self, shard: int, active: bool) -> None:
        """Flip shard ``shard``'s admission mask — the O(1) scale path.

        Deactivating stops the router and the device summary from
        offering the shard's slots; rows already in flight keep decoding
        to completion (drain).  Reactivating is the same flip back —
        nothing is spawned, moved, or recompiled."""
        self._check_shard(shard)
        self._invalidate_admission_cache()
        self.shard_admitting[shard] = bool(active)
        self._shard_active = self._shard_active.at[shard].set(bool(active))
        if active:
            # the next settle's summary predates this flip — give the
            # mismatch sentinel two settles before trusting it again
            self._mask_grace[shard] = 2

    # ------------------------------------------------------------------
    # Deterministic shard-fault seams (the chaos battery's injection
    # points — flag flips folded into the next gang dispatch, so faults
    # land at exact known cycles and every episode replays)
    # ------------------------------------------------------------------

    def inject_poison(self, shard: int, poisoned: bool = True) -> None:
        """Poisoned-logits fault: the shard's decode logits become NaN
        (its emissions are garbage; the device-side health sentinel
        flags the shard at the same settle, so nothing garbage is ever
        emitted to a slot)."""
        self._check_shard(shard)
        self.shard_poisoned[shard] = bool(poisoned)
        self._shard_poison = self._shard_poison.at[shard].set(bool(poisoned))

    def inject_wedge(self, shard: int, wedged: bool = True) -> None:
        """Wedged-shard fault: the shard's rows freeze — they compute
        but emit nothing and advance nothing, the no-progress signature
        the stall sentinel keys on."""
        self._check_shard(shard)
        self.shard_wedged[shard] = bool(wedged)
        self._shard_wedge = self._shard_wedge.at[shard].set(bool(wedged))

    def corrupt_active_mask(self, shard: int) -> None:
        """Admission-mask-corruption fault: flip the DEVICE bit off
        without touching the host mirror — the device summary and the
        router now disagree about the shard, which is exactly the
        divergence the mask-mismatch sentinel detects (re-asserting the
        mask via :meth:`set_shard_active` heals it)."""
        self._check_shard(shard)
        self._shard_active = self._shard_active.at[shard].set(False)

    def _check_shard(self, shard: int) -> None:
        if not 0 <= shard < self.shards:
            raise ValueError(
                f"shard {shard} out of range [0, {self.shards})"
            )

    # ------------------------------------------------------------------
    # Evacuation surface (the pool's quarantine path)
    # ------------------------------------------------------------------

    def kill_rows(self, rows) -> None:
        """Stop the device twins of evacuated rows: mark them done with
        no budget so every later gang block freezes them (their slots
        were freed host-side; the in-flight dispatch-ahead block may
        still compute them once, but its tokens land on non-busy slots
        and are discarded).  One tiny device op at evacuation time —
        never on the per-cycle path."""
        rows = list(rows)
        if not rows:
            return
        idx = jnp.asarray(rows, jnp.int32)
        self._done = self._done.at[idx].set(True)
        self._remaining = self._remaining.at[idx].set(0)

    def take_shard_inflight(self, shard: int) -> list[tuple]:
        """Remove and return the shard's un-finished in-flight requests
        as ``(payload, produced, budget, submitted_at)`` records (the
        :meth:`~.continuous.ContinuousBatcher.submit_resume` contract,
        minus the prompt the caller re-parses).  Slots are freed and
        their device rows killed; rows that are already complete but
        un-settled are left to finish through the normal settle path.
        Deferred first tokens are flushed first (one evacuation-time
        transfer) so a row admitted this very cycle still carries its
        first token into its next life."""
        self._check_shard(shard)
        self._invalidate_admission_cache()
        evac_t0 = (
            self.lifecycle.now_fn() if self.lifecycle is not None else None
        )
        self._settle_pending_firsts()
        from ..obs.lifecycle import request_key
        from .continuous import _Slot

        taken, killed, rids = [], [], []
        for row in self.shard_rows(shard):
            slot = self.slots[row]
            if not self._needs_decode(slot):
                continue
            taken.append(
                (slot.payload, list(slot.produced), slot.budget,
                 slot.submitted_at)
            )
            rids.append(request_key(slot.payload))
            if self.lifecycle is not None:
                # the trace survives the evacuation: submit_resume (or
                # the queue hand-back's redelivery) continues the SAME
                # chain, this only marks that the request crossed shards
                self.lifecycle.note(rids[-1], "evacuated")
            self.slots[row] = _Slot()
            killed.append(row)
        self.kill_rows(killed)
        evac_op = None
        if killed and self.comms is not None and self.comms.enabled:
            from ..comms.ops import EVACUATION_KV

            # the rows LEAVE the draining shard for host staging — the
            # (source, destination) pair the route planner charges the
            # fabric for; the shard label rides in args either way
            evac_op = self.comms.record(
                EVACUATION_KV, "host",
                source=f"shard:{shard}",
                nbytes=self._row_kv_nbytes() * len(killed),
                args={"shard": shard, "rows": len(killed)},
            )
        if killed and self.lifecycle is not None:
            # the evacuation IS a transfer: the rows' deferred tokens
            # flushed host-side and their KV abandoned — a paired
            # transfer window on each evacuated trace, so attribute_slo
            # can name a transfer-bound request (not just the fleet's
            # shard-drain instant)
            done_t = self.lifecycle.now_fn()
            route = (
                evac_op.args.get("route") if evac_op is not None else None
            )
            for rid in rids:
                if rid is None:
                    continue
                if route is not None:
                    # the evacuation hops ride THIS span: append before
                    # stamping so each trace's i-th route stays zipped
                    # onto its i-th transfer span
                    self.lifecycle.route(rid, route)
                self.lifecycle.stamp(rid, "transfer", t=evac_t0)
                self.lifecycle.stamp(rid, "transfer_done", t=done_t)
                self.lifecycle.note(rid, "transfer_evacuation_kv")
        return taken

    def clear_shard_health(self, shard: int) -> None:
        """Reset the shard's sentinel counters (on quarantine, so stale
        pre-quarantine readings can never count for or against the
        probe verdict)."""
        self.shard_stall_cycles[shard] = 0
        self.shard_last_progress[shard] = 0
        self.shard_last_gang_progress[shard] = 0
        self.shard_last_completed[shard] = 0
        self.last_settle_busy[shard] = 0
        self.mask_mismatch[shard] = False
        if self.last_health_bad is not None:
            self.last_health_bad = np.array(self.last_health_bad)
            self.last_health_bad[shard] = False

    def shard_suspects(self, stall_grace: int = 3) -> list[tuple[int, str]]:
        """Shards the latest settle's sentinels indict, with causes:
        ``poisoned-logits`` (NaN flag), ``no-progress`` (busy rows,
        zero tokens for ``stall_grace`` consecutive settles), or
        ``mask-mismatch`` (device admission mask diverged from the
        host's).  Pure introspection — quarantining is the pool's job."""
        suspects = []
        bad = self.last_health_bad
        for s in range(self.shards):
            if bad is not None and bool(bad[s]):
                suspects.append((s, "poisoned-logits"))
            elif self.shard_stall_cycles[s] >= stall_grace:
                suspects.append((s, "no-progress"))
            elif self.mask_mismatch[s]:
                suspects.append((s, "mask-mismatch"))
        return suspects

    def shard_rows(self, shard: int) -> range:
        return range(shard * self.shard_slots, (shard + 1) * self.shard_slots)

    def shard_busy(self, shard: int) -> int:
        """Slots of ``shard`` holding an in-flight request (host view)."""
        return sum(self.slots[row].busy for row in self.shard_rows(shard))

    def shard_free(self, shard: int) -> int:
        return self.shard_slots - self.shard_busy(shard)

    # ------------------------------------------------------------------
    # The admission plane: freest-first routing
    # ------------------------------------------------------------------

    def _invalidate_admission_cache(self) -> None:
        self._avail_cache = None

    def _admission_rows_by_shard(self) -> list[list[int]]:
        """Admission-eligible rows per shard — the ONE availability
        computation both routers (freest-first :attr:`free_slots` and
        sticky :meth:`route_prefixed`) consume, so probing caps and
        drain masks can never apply to one router and miss the other.
        A PROBING shard (half-open after quarantine) offers at most ONE
        slot until its health sentinel clears it.

        Memoized per refill: a host cycle reads availability several
        times (the refill's capacity check, the router's ordering, the
        overload-pressure probe) and each read used to rescan all
        ``S x B`` slot records.  Every mutation that can change
        eligibility — slot assignment/release, taint changes, mask or
        probe flips — invalidates via
        :meth:`_invalidate_admission_cache`, so ONE scan serves the
        whole cycle (pinned by the counting-audit test in
        tests/test_shard_plane.py).  Callers must treat the returned
        lists as read-only."""
        if self._avail_cache is not None:
            return self._avail_cache
        per_shard = [
            [row for row in self.shard_rows(s)
             if not self.slots[row].busy and row not in self._tainted]
            if self.shard_admitting[s] else []
            for s in range(self.shards)
        ]
        for s in range(self.shards):
            if self.shard_probing[s]:
                cap = max(0, 1 - self.shard_busy(s))
                per_shard[s] = per_shard[s][:cap]
        if self.slot_limit is not None:
            # the active-slot knob, per shard: offer at most
            # limit - busy rows (rows above a lowered limit finish —
            # drain semantics, same contract as the probing cap)
            for s in range(self.shards):
                if per_shard[s]:
                    cap = max(0, self.slot_limit - self.shard_busy(s))
                    per_shard[s] = per_shard[s][:cap]
        self._avail_cache = per_shard
        return per_shard

    @property
    def free_slots(self) -> list[int]:
        """Admission-eligible rows, ROUTED: requests are assigned one at
        a time to the currently-freest admitting shard (deterministic
        tie-break: lowest shard index), so a refill larger than any one
        shard's free slots splits across shards and equal-depth shards
        fill in index order.  ``submit_many`` consuming this order IS
        the cross-shard router — the whole refill still prefills as one
        global-row ``[M, P]`` insert."""
        self.free_slot_scans += 1  # routed orderings computed (audit)
        per_shard = self._admission_rows_by_shard()
        order: list[int] = []
        heads = [0] * self.shards
        while True:
            best, best_avail = -1, 0
            for s in range(self.shards):
                avail = len(per_shard[s]) - heads[s]
                if avail > best_avail:  # strict: ties keep the lowest s
                    best, best_avail = s, avail
            if best < 0:
                break
            order.append(per_shard[best][heads[best]])
            heads[best] += 1
        return order

    def _route_prefixed(self, keys: list) -> list[int]:
        """Affinity-first-then-freest routing for prefixed admissions.

        Each key's FIRST admission establishes its home shard (the
        freest at that moment — same deterministic lowest-index
        tie-break as :attr:`free_slots`); later admissions stick to the
        home shard, where the key's prefix entry is resident in the
        per-shard pool, so the tenant keeps its prefix-cache hits.
        Stickiness YIELDS under imbalance: when the home shard has no
        eligible slot, or the freest shard leads it by at least
        ``tenancy.sticky_imbalance`` free slots (0 = auto: the shard's
        slot count, i.e. yield only when home is full), the request
        spills to the freest shard — the home assignment is NOT moved,
        so a one-off spill pays one foreign install and the tenant
        returns home next refill.  ``tenancy.sticky=False`` degrades to
        pure freest-first (the FIFO-routing baseline the tenants bench
        compares against)."""
        per_shard = self._admission_rows_by_shard()
        heads = [0] * self.shards
        sticky = self.tenancy is not None and self.tenancy.sticky
        threshold = (
            self.tenancy.sticky_imbalance
            if self.tenancy is not None and self.tenancy.sticky_imbalance
            else self.shard_slots
        )

        def avail(s: int) -> int:
            return len(per_shard[s]) - heads[s]

        def freest() -> int:
            best, best_avail = -1, 0
            for s in range(self.shards):
                if avail(s) > best_avail:  # strict: ties keep lowest s
                    best, best_avail = s, avail(s)
            return best

        rows: list[int] = []
        for key in keys:
            pick = None
            home = self._tenant_home.get(key)
            if home is not None:
                # LRU-touch on every lookup, not just on first
                # assignment: the cap must evict cold keys, never the
                # busiest long-lived tenant's home
                self._tenant_home.move_to_end(key)
            if sticky and home is not None and avail(home) > 0:
                top = freest()
                if top < 0 or avail(top) - avail(home) < threshold:
                    pick = home
            if pick is None:
                pick = freest()
                if pick < 0:
                    raise RuntimeError(
                        "no admission-eligible slot for a routed "
                        "request (caller must size batches by "
                        "free_slots)"
                    )
                if sticky and home is None:
                    self._tenant_home[key] = pick
                    self._tenant_home.move_to_end(key)
                    while len(self._tenant_home) > 4096:
                        self._tenant_home.popitem(last=False)
            rows.append(per_shard[pick][heads[pick]])
            heads[pick] += 1
        return rows

    def _pool_shard_of(self, row: int) -> int:
        return row // self.shard_slots

    def _free_slot_count(self) -> int:
        # capacity only: skips the freest-first merge the routed
        # free_slots ordering pays
        return sum(len(rows) for rows in self._admission_rows_by_shard())

    # ------------------------------------------------------------------
    # The engine cycle
    # ------------------------------------------------------------------

    def step(self) -> list[tuple[Any, np.ndarray]]:
        """Advance ALL shards' active slots with one gang-stepped block
        dispatch; settle the previous block + any deferred first tokens
        + the ``[S]`` free summary in one combined transfer.  Same
        dispatch-ahead overlap, results, and finished-request contract
        as the single-plane block engine."""
        if self.active == 0 and not self._tainted:
            return []
        return self._step_gang()

    def _record_firsts(self, pending_host) -> None:
        # attribute prefill first tokens to their shard before the
        # shared TTFT/emit bookkeeping runs
        for _, rows in pending_host:
            for row in rows:
                self.shard_tokens[row // self.shard_slots] += 1
        super()._record_firsts(pending_host)

    def _note_ttft(self, row: int, ttft: float) -> None:
        # per-shard TTFT attribution: the chaos-serve bench gates the
        # healthy shards' p99 against the no-fault baseline
        self.shard_ttft[row // self.shard_slots].append(ttft)

    def _block_settle_arrays(self):
        # the gang block's combined settle fetches tokens/counts plus
        # the [S] free summary and health sentinel — all four prefetch
        if self._pending_block is None:
            return None
        return self._pending_block[:4]

    def _comms_source(self, rows) -> str:
        # settle pulls covering exactly one shard's rows route from
        # that shard; the gang-wide combined block pull (rows=None or
        # spanning shards) stays the generic device endpoint
        if rows:
            shards = {row // self.shard_slots for row in rows}
            if len(shards) == 1:
                return f"shard:{shards.pop()}"
        return super()._comms_source(rows)

    def _step_gang(self) -> list[tuple[Any, np.ndarray]]:
        new_block = None
        busy = sum(s.busy for s in self.slots)
        if busy and self._pending_decode_block is None:
            # staged decode_block swap: skip exactly one gang dispatch
            # so the in-flight block settles at the old size — the
            # re-dispatch boundary (see the block engine's identical
            # contract)
            (self.cache, self._current, self._done, self._remaining,
             tokens, counts, free, bad) = self._gang_fn(
                self.params, self.cache, self._current, self._done,
                self._remaining, self._block_keys(), self._shard_active,
                self._shard_poison, self._shard_wedge,
            )
            self.decode_dispatches += 1
            self.gang_cycles += 1
            new_block = (
                tokens, counts, free, bad, busy,
                [self.shard_busy(s) for s in range(self.shards)],
            )
        if self.comms is not None:
            # the dispatch-ahead window: the gang block above (or the
            # one still in flight) occupies the devices — start the
            # queued settle pulls (deferred firsts + the previous
            # block's arrays, computed a full cycle ago) device-side so
            # their copies hide behind the new block's compute
            self._comms_flush(
                overlapped=(new_block is not None
                            or self._pending_block is not None),
            )
        pending_firsts, self._pending_firsts = self._pending_firsts, []
        pending, self._pending_block = self._pending_block, new_block
        # ONE combined host transfer per cycle: deferred first tokens,
        # the settled block's tokens/counts, the [S] free summary, AND
        # the [S] health sentinel all land in a single device_get —
        # shard-fault detection costs zero additional host syncs
        firsts_dev = [arr for arr, _ in pending_firsts]
        block_dev = pending[:4] if pending is not None else ()
        # first tokens settling this cycle count as shard progress too:
        # a budget-1 row is never live in any gang block (its one token
        # comes from the admission insert), so without this a healthy
        # shard serving generate_tokens=1 traffic would read as stalled
        firsts_by_shard = [0] * self.shards
        for _, rows in pending_firsts:
            for row in rows:
                firsts_by_shard[row // self.shard_slots] += 1
        if firsts_dev or block_dev:
            block_op, self._block_op = self._block_op, None
            first_ops = [
                self._first_ops.pop(id(arr), None) for arr in firsts_dev
            ]
            firsts_host, block_host = jax.device_get(
                (firsts_dev, block_dev)
            )
            prefetched = [
                op for op in first_ops
                if op is not None and op.dispatched
            ]
            block_prefetched = (
                block_op is not None and block_op.dispatched
            )
            if self.comms is not None:
                for op in prefetched:
                    self.comms.finish(op)
                if block_prefetched:
                    self.comms.finish(block_op)
            if (self.comms is None
                    or len(prefetched) != len(firsts_dev)
                    or (block_dev and not block_prefetched)):
                # at least one fetched array had no prefetch in flight:
                # this cycle's combined settle blocked.  When the comms
                # flush covered everything, the copies ran while the new
                # gang computed and the settle is a non-blocking read.
                self.host_transfers += 1
            if pending_firsts:
                self._record_firsts([
                    (vals, rows)
                    for vals, (_, rows) in zip(firsts_host, pending_firsts)
                ])
            if pending is not None:
                toks_host, counts_host, free_host, bad_host = block_host
                self.last_free_summary = free_host
                self.last_health_bad = np.asarray(bad_host)
                self.summary_transfers += 1
                dispatched_busy = pending[4]
                dispatch_busy_by_shard = pending[5]
                self.block_capacity += self.decode_block * dispatched_busy
                progress = (
                    np.asarray(counts_host)
                    .reshape(self.shards, self.shard_slots)
                    .sum(axis=1)
                )
                for s in range(self.shards):
                    total = int(progress[s]) + firsts_by_shard[s]
                    self.shard_last_progress[s] = total
                    self.shard_last_gang_progress[s] = int(progress[s])
                    self.last_settle_busy[s] = dispatch_busy_by_shard[s]
                    # no-progress sentinel: busy rows at dispatch, zero
                    # tokens back — a wedged shard's exact signature
                    # (a poisoned one keeps "progressing", its NaN flag
                    # is the detector there)
                    if dispatch_busy_by_shard[s] > 0 and total == 0:
                        self.shard_stall_cycles[s] += 1
                    else:
                        self.shard_stall_cycles[s] = 0
                for row, slot in enumerate(self.slots):
                    if not slot.busy:
                        continue
                    shard = row // self.shard_slots
                    if (self.discard_bad_blocks
                            and bool(self.last_health_bad[shard])):
                        # the shard's logits went non-finite mid-block:
                        # every token it emitted this block is garbage —
                        # discard them all, so nothing corrupt ever
                        # reaches a slot (the quarantine path re-decodes
                        # from the last clean token)
                        continue
                    for token in toks_host[: int(counts_host[row]), row]:
                        if slot.done or len(slot.produced) >= slot.budget:
                            break
                        self._emit(slot, int(token))
                        self.shard_tokens[shard] += 1
                        self.block_tokens += 1
        # every gang block dispatched before the last quiesce has now
        # settled, so tainted rows are admissible again (see the block
        # engine's identical clear)
        if self._tainted:
            self._invalidate_admission_cache()
        self._tainted.clear()
        if self._pending_block is None:
            # nothing in flight at the old size: a staged decode_block
            # swap lands here; the next gang dispatch uses it
            self._apply_pending_decode_block()
        busy_before = [self.shard_busy(s) for s in range(self.shards)]
        finished = self._finish_ready()
        for s in range(self.shards):
            self.shard_last_completed[s] = busy_before[s] - self.shard_busy(s)
        if pending is not None:
            self._update_mask_mismatch()
        return finished

    def _update_mask_mismatch(self) -> None:
        """Compare the just-settled device ``[S]`` free summary against
        the host's post-settle slot bookkeeping.  For an honestly-active
        shard the device can only over-report free slots (its summary is
        one block older than the host view: rows the host has since
        admitted were still free to it, and rows the host just freed
        were already done to it), so ``device == 0 < host`` is
        impossible — unless the device-side admission mask diverged
        (the corruption fault).  Runs on data already in hand: no
        transfers."""
        summary = self.last_free_summary
        if summary is None:
            return
        for s in range(self.shards):
            if self._mask_grace[s] > 0:
                self._mask_grace[s] -= 1
                self.mask_mismatch[s] = False
                continue
            self.mask_mismatch[s] = (
                self.shard_admitting[s]
                and int(summary[s]) == 0
                and self.shard_free(s) > 0
            )

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    def shard_stats(self, served_since: float | None = None) -> list[dict]:
        """Per-shard gauge rows: admitting, busy slots, tokens emitted,
        tokens/s over the serving lifetime (0 before serving starts),
        and ``device_free`` — the device-confirmed free-slot count from
        the last settled ``[S]`` summary (None until a block settles;
        one cycle behind the authoritative host view by construction,
        since the summary rides the dispatch-ahead settle)."""
        now = time.perf_counter()
        elapsed = (
            now - served_since
            if served_since is not None and now > served_since else 0.0
        )
        summary = self.last_free_summary
        bad = self.last_health_bad
        return [
            {
                "shard": s,
                "active": self.shard_admitting[s],
                "probing": self.shard_probing[s],
                "active_slots": self.shard_busy(s),
                "device_free": (
                    int(summary[s]) if summary is not None else None
                ),
                "bad": bool(bad[s]) if bad is not None else False,
                "stall_cycles": self.shard_stall_cycles[s],
                "tokens": self.shard_tokens[s],
                "tokens_per_second": (
                    self.shard_tokens[s] / elapsed if elapsed > 0 else 0.0
                ),
            }
            for s in range(self.shards)
        ]
