"""The sharded serving plane: gang-stepped data-parallel engine shards.

The PR 6 fleet scales capacity by stepping N independent
:class:`~.continuous.ContinuousWorker` replicas in a sequential Python
loop — aggregate tokens/s is host-bound again, paying N block dispatches,
N settle transfers, and N refill syncs per fleet cycle.  This module
removes that Python-rate wall by re-expressing the whole fleet's decode
as ONE program over a shard axis:

- **slot state stacks along a leading shard axis** ``[S, B, ...]``
  (stored flat as ``[S*B]`` rows — the exact
  :class:`~.continuous.ContinuousBatcher` layout, so the insert, the
  liveness masks, and every cache layout variant are reused verbatim);
- **one gang-stepped decode per cycle**:
  :func:`~.decode.gang_block_decode` ``vmap``s the PR 5 block engine
  over the shard axis — all shards advance up to ``decode_block`` tokens
  in one jitted call, per-row liveness kept device-side exactly as the
  block engine does per row.  One dispatch, however many shards.  Under
  a mesh the leading shard axis partitions over ``"data"`` (GSPMD
  places whole shards per device — the ``shard_map`` layout without the
  explicit collective plumbing, and decode itself needs NO cross-shard
  communication to overlap: the NCCL/collective-synthesis literature's
  question of which collectives to hide never arises because the only
  cross-shard product is the ``[S]`` summary below);
- **one admission plane**: the host routes each refill cycle's requests
  freest-shard-first (deterministic tie-break: lowest shard index) and
  prefills them with the existing one-shot ``[M, P]`` insert over GLOBAL
  row ids — one insert dispatch per refill cycle even when the batch
  splits across shards, zero per-request host syncs;
- **one summary transfer per cycle**: the gang step returns a per-shard
  ``[S]`` free-slot summary; the host fetches it together with the
  settled block's tokens in ONE ``jax.device_get`` — overlapped with
  the next block via the inherited dispatch-ahead double buffering.
  The summary is the plane's device-confirmed depth signal (surfaced
  per shard via :meth:`ShardedBatcher.shard_stats`); the router's
  freest-first ordering reads the host's own slot bookkeeping, which
  is authoritative and transfer-free;
- **O(1) scale**: :meth:`ShardedBatcher.set_shard_active` flips a
  device-side ``[S]`` mask bit.  A deactivated shard stops admitting
  instantly (the summary reports it full; the router skips it) while
  its in-flight rows decode to completion — drain semantics without
  spawning, rebuilding, or recompiling anything.
  :class:`~..fleet.sharded.ShardedWorkerPool` actuates this through the
  unchanged :class:`~..core.types.Scaler` seam.

Greedy outputs are byte-identical to ``S`` independent single engines on
the same request stream (hard-gated in ``bench.py --suite scale``):
rows never interact across the batch axis, and the vmapped inner
computation IS the independent engine's computation.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .continuous import ContinuousBatcher


class ShardedBatcher(ContinuousBatcher):
    """``shards`` gang-stepped engine shards behind one admission plane.

    Construction mirrors :class:`~.continuous.ContinuousBatcher` with
    ``batch_size`` replaced by ``shards`` x ``shard_slots`` (shard ``s``
    owns rows ``[s*shard_slots, (s+1)*shard_slots)``).  Plain decode
    path only — beam and speculative slots amortize their own device
    calls per slot, not per shard.  Everything else composes: both
    families, greedy or sampled (shards draw independent PRNG streams
    via per-shard key folding), int8 KV, shared prefix, mesh.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        *,
        shards: int,
        shard_slots: int,
        prompt_len: int,
        generate_tokens: int,
        **kwargs,
    ) -> None:
        if shards < 1:
            raise ValueError(f"shards={shards} must be >= 1")
        if shard_slots < 1:
            raise ValueError(f"shard_slots={shard_slots} must be >= 1")
        if kwargs.get("beams", 1) > 1 or kwargs.get("draft_layers", 0):
            raise ValueError(
                "the sharded plane applies to the plain continuous "
                "decode path (not beams / speculative slots)"
            )
        mesh = kwargs.get("mesh")
        if mesh is not None and shards % mesh.shape["data"]:
            # each device must hold WHOLE shards for the [S*B] -> [S, B]
            # view to stay resharding-free under the pinned row
            # sharding; checked BEFORE the base constructor allocates
            # the full cache and device-puts state across the mesh
            raise ValueError(
                f"shards ({shards}) not divisible by the mesh's data "
                f"axis ({mesh.shape['data']})"
            )
        self.shards = shards
        self.shard_slots = shard_slots
        super().__init__(
            params, config, batch_size=shards * shard_slots,
            prompt_len=prompt_len, generate_tokens=generate_tokens,
            **kwargs,
        )
        # the device-side scale mask: True = the shard admits (its free
        # slots count in the summary).  In-flight rows of a deactivated
        # shard keep decoding — drain, not kill.
        self._shard_active = jnp.ones((shards,), bool)
        # host mirror the router consults without a device read
        self.shard_admitting = [True] * shards
        # per-shard emitted-token counters (the per-shard tokens/s gauge)
        self.shard_tokens = [0] * shards
        # the last consumed [S] free-slot summary (None until a block
        # settles) — the device-confirmed depth signal behind
        # shard_stats' device_free column, fetched in the ONE combined
        # transfer per cycle alongside the block tokens
        self.last_free_summary: np.ndarray | None = None
        # gang instrumentation: cycles that dispatched a gang block and
        # combined settle transfers (the bench gates dispatches/cycle
        # == 1 and transfers/cycle <= 1 at every shard count)
        self.gang_cycles = 0
        self.summary_transfers = 0
        self._gang_fn = self._make_gang_fn()

    # ------------------------------------------------------------------
    # Engine identity / adoption
    # ------------------------------------------------------------------

    def _engine_key(self) -> tuple:
        return super()._engine_key() + (self.shards, self.shard_slots)

    def adopt_engine(self, source: ContinuousBatcher) -> None:
        if not isinstance(source, ShardedBatcher):
            raise ValueError(
                "a sharded plane adopts from a sharded donor only"
            )
        super().adopt_engine(source)  # validates the full engine key
        self._gang_fn = source._gang_fn

    # ------------------------------------------------------------------
    # The gang step
    # ------------------------------------------------------------------

    def _make_gang_fn(self):
        """The ONE compiled decode program for all shards: the vmapped
        block engine plus the per-shard free-slot summary, flat-state
        donated so the buffers roll in place cycle after cycle."""
        from .decode import gang_block_decode

        step_fn = self._family_step_fn()
        config = self.config
        shards = self.shards
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        eos_id = self.eos_id
        fold = self.temperature > 0.0

        def gang(params, cache, current, done, remaining, keys, active):
            return gang_block_decode(
                params, cache, current, done, remaining, keys, active,
                config, step_fn, shards=shards, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_id=eos_id, fold_keys=fold,
            )

        if self.mesh is None:
            return jax.jit(gang, donate_argnums=(1, 2, 3, 4))
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .train import param_shardings

        rep = NamedSharding(self.mesh, P())
        rows = self._rows_shard
        tokens_shard = NamedSharding(self.mesh, P(None, "data"))
        return jax.jit(
            gang,
            in_shardings=(param_shardings(self.mesh, self.params),
                          self._cache_shard, rows, rows, rows, rep, rep),
            out_shardings=(self._cache_shard, rows, rows, rows,
                           tokens_shard, rows, rep),
            donate_argnums=(1, 2, 3, 4),
        )

    # ------------------------------------------------------------------
    # Scale: device-side mask flips
    # ------------------------------------------------------------------

    def set_shard_active(self, shard: int, active: bool) -> None:
        """Flip shard ``shard``'s admission mask — the O(1) scale path.

        Deactivating stops the router and the device summary from
        offering the shard's slots; rows already in flight keep decoding
        to completion (drain).  Reactivating is the same flip back —
        nothing is spawned, moved, or recompiled."""
        if not 0 <= shard < self.shards:
            raise ValueError(
                f"shard {shard} out of range [0, {self.shards})"
            )
        self.shard_admitting[shard] = bool(active)
        self._shard_active = self._shard_active.at[shard].set(bool(active))

    def shard_rows(self, shard: int) -> range:
        return range(shard * self.shard_slots, (shard + 1) * self.shard_slots)

    def shard_busy(self, shard: int) -> int:
        """Slots of ``shard`` holding an in-flight request (host view)."""
        return sum(self.slots[row].busy for row in self.shard_rows(shard))

    def shard_free(self, shard: int) -> int:
        return self.shard_slots - self.shard_busy(shard)

    # ------------------------------------------------------------------
    # The admission plane: freest-first routing
    # ------------------------------------------------------------------

    @property
    def free_slots(self) -> list[int]:
        """Admission-eligible rows, ROUTED: requests are assigned one at
        a time to the currently-freest admitting shard (deterministic
        tie-break: lowest shard index), so a refill larger than any one
        shard's free slots splits across shards and equal-depth shards
        fill in index order.  ``submit_many`` consuming this order IS
        the cross-shard router — the whole refill still prefills as one
        global-row ``[M, P]`` insert."""
        per_shard = [
            [row for row in self.shard_rows(s) if not self.slots[row].busy]
            if self.shard_admitting[s] else []
            for s in range(self.shards)
        ]
        order: list[int] = []
        heads = [0] * self.shards
        while True:
            best, best_avail = -1, 0
            for s in range(self.shards):
                avail = len(per_shard[s]) - heads[s]
                if avail > best_avail:  # strict: ties keep the lowest s
                    best, best_avail = s, avail
            if best < 0:
                break
            order.append(per_shard[best][heads[best]])
            heads[best] += 1
        return order

    # ------------------------------------------------------------------
    # The engine cycle
    # ------------------------------------------------------------------

    def step(self) -> list[tuple[Any, np.ndarray]]:
        """Advance ALL shards' active slots with one gang-stepped block
        dispatch; settle the previous block + any deferred first tokens
        + the ``[S]`` free summary in one combined transfer.  Same
        dispatch-ahead overlap, results, and finished-request contract
        as the single-plane block engine."""
        if self.active == 0:
            return []
        return self._step_gang()

    def _record_firsts(self, pending_host) -> None:
        # attribute prefill first tokens to their shard before the
        # shared TTFT/emit bookkeeping runs
        for _, rows in pending_host:
            for row in rows:
                self.shard_tokens[row // self.shard_slots] += 1
        super()._record_firsts(pending_host)

    def _step_gang(self) -> list[tuple[Any, np.ndarray]]:
        new_block = None
        busy = sum(s.busy for s in self.slots)
        if busy:
            (self.cache, self._current, self._done, self._remaining,
             tokens, counts, free) = self._gang_fn(
                self.params, self.cache, self._current, self._done,
                self._remaining, self._block_keys(), self._shard_active,
            )
            self.decode_dispatches += 1
            self.gang_cycles += 1
            new_block = (tokens, counts, free, busy)
        pending_firsts, self._pending_firsts = self._pending_firsts, []
        pending, self._pending_block = self._pending_block, new_block
        # ONE combined host transfer per cycle: deferred first tokens,
        # the settled block's tokens/counts, and the [S] summary all
        # land in a single device_get
        firsts_dev = [arr for arr, _ in pending_firsts]
        block_dev = pending[:3] if pending is not None else ()
        if firsts_dev or block_dev:
            firsts_host, block_host = jax.device_get(
                (firsts_dev, block_dev)
            )
            self.host_transfers += 1
            if pending_firsts:
                self._record_firsts([
                    (vals, rows)
                    for vals, (_, rows) in zip(firsts_host, pending_firsts)
                ])
            if pending is not None:
                toks_host, counts_host, free_host = block_host
                self.last_free_summary = free_host
                self.summary_transfers += 1
                dispatched_busy = pending[3]
                self.block_capacity += self.decode_block * dispatched_busy
                self.block_tokens += int(counts_host.sum())
                for row, slot in enumerate(self.slots):
                    if not slot.busy:
                        continue
                    shard = row // self.shard_slots
                    for token in toks_host[: int(counts_host[row]), row]:
                        if slot.done or len(slot.produced) >= slot.budget:
                            break
                        self._emit(slot, int(token))
                        self.shard_tokens[shard] += 1
        return self._finish_ready()

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    def shard_stats(self, served_since: float | None = None) -> list[dict]:
        """Per-shard gauge rows: admitting, busy slots, tokens emitted,
        tokens/s over the serving lifetime (0 before serving starts),
        and ``device_free`` — the device-confirmed free-slot count from
        the last settled ``[S]`` summary (None until a block settles;
        one cycle behind the authoritative host view by construction,
        since the summary rides the dispatch-ahead settle)."""
        now = time.perf_counter()
        elapsed = (
            now - served_since
            if served_since is not None and now > served_since else 0.0
        )
        summary = self.last_free_summary
        return [
            {
                "shard": s,
                "active": self.shard_admitting[s],
                "active_slots": self.shard_busy(s),
                "device_free": (
                    int(summary[s]) if summary is not None else None
                ),
                "tokens": self.shard_tokens[s],
                "tokens_per_second": (
                    self.shard_tokens[s] / elapsed if elapsed > 0 else 0.0
                ),
            }
            for s in range(self.shards)
        ]
