"""Model-FLOPs accounting and MFU (model FLOPs utilization).

The reference publishes no performance numbers (SURVEY.md §6), so the
workload layer's perf contract is self-generated: tokens/s and MFU
measured on the bench chip (``workbench.py`` at the repo root) and
surfaced in the trainer's log line.

Conventions (stated so the numbers are comparable across rounds):

- FLOPs are *model* FLOPs — the matmul work the architecture defines —
  not hardware FLOPs: rematerialization or a recomputing backward kernel
  does not change the number (standard MFU convention, PaLM appendix B).
- 2 FLOPs per multiply-accumulate.
- Attention score/value matmuls are counted *full* (no causal ½
  discount), again the common convention; the flash kernel's causal
  block-skip therefore shows up as higher MFU, which is the point.
- A train step is 3x the forward (backward = 2x forward).
- Peak chip FLOP/s are bf16 dense figures from the public TPU specs;
  unknown device kinds yield ``None`` (callers print tokens/s only).
"""

from __future__ import annotations

from typing import Any

# bf16 dense peak FLOP/s per chip, by jax device_kind substring.
# Ordered: more specific names first (``v5 lite`` before ``v5``).
_PEAK_FLOPS = (
    ("v6 lite", 918e12),  # Trillium / v6e
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device: Any = None) -> float | None:
    """Per-chip bf16 peak for ``device`` (default: first local device)."""
    if device is None:
        import jax

        devices = jax.local_devices()
        if not devices:
            return None
        device = devices[0]
    kind = getattr(device, "device_kind", "").lower()
    for marker, peak in _PEAK_FLOPS:
        if marker in kind:
            return peak
    return None


def _attention_flops(batch: int, seq: int, d_model: int, n_layers: int) -> float:
    # scores (q kᵀ) + values (p v): 2 matmuls of S² x Dh MACs per head
    # per layer per example = 2 (matmuls) x 2 (FLOPs/MAC) x S² x d_model
    # FLOPs — already FLOPs, not MACs (GQA changes bandwidth, not FLOPs:
    # every query head still attends)
    return n_layers * batch * 4.0 * seq * seq * d_model


def forward_flops(config: Any, batch: int, seq: int) -> float:
    """Forward-pass model FLOPs for one ``[batch, seq]`` token batch.

    Works for both families (duck-typed on the config): projection
    weights are read off the architecture, attention is counted full.
    """
    d = config.d_model
    tokens = batch * seq
    if hasattr(config, "n_kv_heads"):  # llama family
        kv_dim = config.n_kv_heads * config.head_dim
        per_token = (
            d * d  # wq
            + d * 2 * kv_dim  # wkv
            + d * d  # wo
            + d * 2 * config.d_ff  # w_gate_up
            + config.d_ff * d  # w_down
        ) * config.n_layers
    else:  # gpt family
        per_token = (
            d * 3 * d  # wqkv
            + d * d  # wo
            + d * config.d_ff  # w_up
            + config.d_ff * d  # w_down
        ) * config.n_layers
    per_token += d * config.vocab_size  # tied-embedding logits
    return 2.0 * tokens * per_token + _attention_flops(
        batch, seq, d, config.n_layers
    )


def train_step_flops(config: Any, batch: int, seq: int) -> float:
    """fwd + bwd model FLOPs for one optimizer step (bwd = 2x fwd)."""
    return 3.0 * forward_flops(config, batch, seq)


def mfu(flops: float, seconds: float, device: Any = None) -> float | None:
    """``flops / seconds`` as a fraction of the chip's bf16 peak
    (``None`` when the peak is unknown — e.g. the CPU test mesh)."""
    peak = peak_flops(device)
    if peak is None or seconds <= 0:
        return None
    return flops / seconds / peak
