"""Queue-fed inference worker: one replica of the Deployment being scaled.

The reference autoscales pods that drain an SQS queue (``README.md:7-17``);
this module is that pod's TPU-shaped equivalent: pull token batches off a
work queue, run them through the sharded jitted forward pass, report results
and throughput.  The simulator (:mod:`..sim`) and benchmarks compose many of
these with the controller to close the loop end-to-end.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .model import ModelConfig, forward_jit


@dataclass
class WorkItem:
    """One inference request: a token batch (static shape for jit reuse)."""

    tokens: Any  # int32 [batch, seq]
    id: int = 0


@dataclass
class WorkResult:
    id: int
    next_tokens: Any  # int32 [batch] — greedy next-token per sequence
    latency_s: float


class InferenceWorker:
    """Drains a work queue through a compiled forward pass.

    ``serve_forever`` mirrors the scaled pod's main loop; ``process`` is the
    single-item path used by tests and the simulator.
    """

    def __init__(
        self,
        params: Any,
        config: ModelConfig,
        forward_fn: Callable[..., Any] | None = None,
    ) -> None:
        self.params = params
        self.config = config
        # default: single-chip jit; pass train.make_forward_step(...) output
        # for a mesh-sharded serving path
        self._forward = forward_fn or (
            lambda params, tokens: forward_jit(params, tokens, config)
        )
        self.processed = 0

    def process(self, item: WorkItem) -> WorkResult:
        start = time.perf_counter()
        logits = self._forward(self.params, item.tokens)
        next_tokens = jnp.argmax(logits[:, -1, :], axis=-1)
        next_tokens.block_until_ready()
        self.processed += 1
        return WorkResult(
            id=item.id,
            next_tokens=next_tokens,
            latency_s=time.perf_counter() - start,
        )

    def serve_forever(
        self,
        work: "queue.Queue[WorkItem | None]",
        results: "queue.Queue[WorkResult]",
    ) -> None:
        """Blocking drain loop; a ``None`` item is the shutdown sentinel."""
        while True:
            item = work.get()
            if item is None:
                return
            results.put(self.process(item))


@dataclass
class WorkerPool:
    """A fixed-size pool of threads sharing one compiled model.

    Thread-per-replica is faithful to "N pods drain one queue" while staying
    in-process for tests/benchmarks; JAX dispatch releases the GIL during
    device execution, so threads overlap host-side work.
    """

    worker_factory: Callable[[], InferenceWorker]
    size: int = 1
    work: "queue.Queue[WorkItem | None]" = field(default_factory=queue.Queue)
    results: "queue.Queue[WorkResult]" = field(default_factory=queue.Queue)

    def __post_init__(self) -> None:
        self._threads: list[threading.Thread] = []
        self.workers: list[InferenceWorker] = []

    def start(self) -> None:
        for _ in range(self.size):
            worker = self.worker_factory()
            self.workers.append(worker)
            thread = threading.Thread(
                target=worker.serve_forever, args=(self.work, self.results),
                daemon=True,
            )
            thread.start()
            self._threads.append(thread)

    def submit(self, item: WorkItem) -> None:
        self.work.put(item)

    def stop(self) -> None:
        for _ in self._threads:
            self.work.put(None)
        for thread in self._threads:
            thread.join(timeout=30)
        self._threads.clear()

    def depth(self) -> int:
        """Current backlog — the quantity the autoscaler thresholds on."""
        return self.work.qsize()
