"""Autoregressive decoding with a KV cache: the serving-shaped workload.

The reference's scaled pods are queue-draining workers (``README.md:7-17``);
:mod:`.worker` models them with a full forward per request.  Real LM serving
decodes token-by-token, so this module adds the TPU-native decode path (no
reference counterpart — the reference contains no model code, SURVEY.md §2):

- **Static shapes under jit**: the cache is pre-allocated at
  ``max_seq_len`` and the current length is a traced ``int32`` scalar —
  every decode step compiles once and reuses the same executable
  regardless of position (``lax.dynamic_update_slice`` writes, an
  iota-vs-length mask reads).
- **Prefill vs decode split**: the prompt runs through one big causal
  forward (MXU-bound, reuses the model's dense/flash attention) while
  populating the cache; each generated token then runs the cheap
  single-position path (HBM-bandwidth-bound GEMVs against the cache).
- **``lax.scan`` generation**: the whole generate loop lives inside one
  jit — no per-token Python dispatch, no host↔device sync until the
  final token block comes back.
- **bf16 cache, fp32 softmax**: cache entries store in the model dtype;
  attention scores and normalization run in fp32 like the training path.
- **Mesh-ready**: :func:`cache_shardings` shards the cache's heads axis
  over ``"model"`` (matching the Megatron-sharded ``wqkv``) and batch over
  ``"data"``; :func:`make_serving_fns` pins those shardings into compiled
  prefill/decode/generate steps.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .model import (
    ModelConfig,
    _block,
    _dense_attention,
    _layer_norm,
)


def init_cache(config: ModelConfig, batch: int) -> dict:
    """Empty KV cache: per layer ``[B, H, max_seq_len, head_dim]`` in the
    model dtype, plus per-row ``length`` (int32 ``[batch]``) — rows may
    hold prompts of different lengths (ragged batches), each decoding at
    its own position."""
    shape = (batch, config.n_heads, config.max_seq_len, config.head_dim)
    return {
        "layers": [
            {
                "k": jnp.zeros(shape, config.dtype),
                "v": jnp.zeros(shape, config.dtype),
            }
            for _ in range(config.n_layers)
        ],
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _final_logits(
    params: dict, x: jax.Array, last_pos: jax.Array | None = None
) -> jax.Array:
    """Readout logits: final LN + tied-embedding readout in fp32.

    ``last_pos`` (int32 ``[batch]``) selects each row's readout position —
    the last *valid* position of a right-padded row, so a short body is
    never read out of a pad slot.  ``None`` reads position -1 (all rows
    full).
    """
    x = _layer_norm(x, params["final_ln_scale"], params["final_ln_bias"])
    logits = jnp.einsum(
        "bsd,vd->bsv", x, params["embed"], preferred_element_type=jnp.float32
    )
    if last_pos is None:
        return logits[:, -1]
    return logits[jnp.arange(logits.shape[0]), last_pos]


def prefill(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    attention_fn=None,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """Run the prompt through the model, populating a fresh cache.

    ``tokens``: int32 ``[batch, prompt_len]`` → (readout logits
    ``[batch, vocab]`` fp32, cache at ``length == prompt_len`` per row).
    The prompt occupies cache positions ``[0, prompt_len)``;
    ``attention_fn`` selects the prompt-pass attention (dense default,
    flash kernel on TPU).

    ``lengths`` (int32 ``[batch]``) marks ragged right-padded prompts:
    row ``i``'s real tokens are ``[0, lengths[i])``.  Causality already
    keeps real positions from attending pad keys (pads sit *after* every
    real position), so the forward needs no extra mask — what changes is
    the readout (each row reads its last valid position, not the pad at
    -1) and the cache lengths (row ``i`` continues decoding at
    ``lengths[i]``, overwriting its pad slots; the decode mask hides the
    still-padded tail).
    """
    batch, prompt_len = tokens.shape
    if prompt_len > config.max_seq_len:
        raise ValueError(
            f"prompt length {prompt_len} exceeds max_seq_len={config.max_seq_len}"
        )
    cache = init_cache(config, batch)
    inner = attention_fn or _dense_attention
    new_layers = []
    x = params["embed"][tokens] + params["pos_embed"][:prompt_len]
    for layer, layer_cache in zip(params["layers"], cache["layers"]):

        def attend(q, k, v, _lc=layer_cache):
            # capture this layer's k/v into the padded cache, then run the
            # normal causal attention for the prompt pass
            new_layers.append(
                {
                    "k": _lc["k"].at[:, :, :prompt_len].set(k.astype(config.dtype)),
                    "v": _lc["v"].at[:, :, :prompt_len].set(v.astype(config.dtype)),
                }
            )
            return inner(q, k, v)

        x = _block(x, layer, config, attend)
    if lengths is None:
        row_lengths = jnp.full((batch,), prompt_len, jnp.int32)
        logits = _final_logits(params, x)
    else:
        row_lengths = lengths.astype(jnp.int32)
        logits = _final_logits(params, x, last_pos=row_lengths - 1)
    return logits, {"layers": new_layers, "length": row_lengths}


def _cached_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    length: jax.Array,
    window: int | None = None,
) -> jax.Array:
    """One query position per row against the padded cache.

    ``q``: ``[B, H, 1, D]``; cache: ``[B, H, S_max, D]`` with row ``b``'s
    valid entries at positions ``<= length[b]`` (the current token was
    just written at ``length[b]``) — later positions are pads or other
    rows' leftovers and get ``-inf``.  The ``T = 1`` case of
    :func:`_chunk_cached_attention` (one implementation of the masked
    fp32 score/softmax math; ``window`` = sliding-window lookback).
    """
    return _chunk_cached_attention(q, k_cache, v_cache, length, window)


def _decode_impl(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    config: ModelConfig,
    write_and_attend,
) -> tuple[jax.Array, dict]:
    """The gpt-family decode-step skeleton every cache layout shares
    (full-precision, int8): embed at each row's position, per layer call
    ``write_and_attend(q, k, v, layer_cache, rows, pos) -> (new_entry,
    out)``, final logits.  The llama counterpart is
    ``llama._decode_step_impl`` (same seam shape)."""
    pos = cache["length"]  # [B]
    batch = tokens.shape[0]
    rows = jnp.arange(batch)
    x = params["embed"][tokens][:, None, :] + params["pos_embed"][pos][:, None, :]
    new_layers = []
    for layer, layer_cache in zip(params["layers"], cache["layers"]):

        def attend(q, k, v, _lc=layer_cache):
            entry, out = write_and_attend(q, k, v, _lc, rows, pos)
            new_layers.append(entry)
            return out

        x = _block(x, layer, config, attend)
    logits = _final_logits(params, x)
    return logits, {"layers": new_layers, "length": pos + 1}


def decode_step(
    params: dict, cache: dict, tokens: jax.Array, config: ModelConfig
) -> tuple[jax.Array, dict]:
    """One autoregressive step: feed ``tokens`` (int32 ``[batch]``, row
    ``b``'s token for position ``cache["length"][b]``), return (fp32
    logits ``[batch, vocab]`` for each row's next position, updated
    cache).  Rows advance independently — a ragged batch decodes in
    lockstep with per-row positions."""

    def write_and_attend(q, k, v, layer_cache, rows, pos):
        # write each row's k/v at its own position, then attend the
        # single query against the whole (row-masked) cache
        k_cache = layer_cache["k"].at[rows, :, pos].set(
            k[:, :, 0].astype(config.dtype)
        )
        v_cache = layer_cache["v"].at[rows, :, pos].set(
            v[:, :, 0].astype(config.dtype)
        )
        entry = {"k": k_cache, "v": v_cache}
        return entry, _cached_attention(q, k_cache, v_cache, pos)

    return _decode_impl(params, cache, tokens, config, write_and_attend)


# ---------------------------------------------------------------------------
# int8 KV cache
# ---------------------------------------------------------------------------
#
# Decode is HBM-bandwidth-bound and the cache is what it streams: every
# step reads all [B, H, S_max, D] keys AND values of every layer.  The
# same argument that halves weight bytes (.quantize) applies — store the
# cache as int8 codes with one fp32 scale per (batch, head, position)
# vector.  The per-position scale factors OUT of both attention matmuls:
#
#   scores[b,h,q,s] = (q · k[b,h,s]) / sqrt(D)
#                   = (q · codes[b,h,s]) * k_scale[b,h,s] / sqrt(D)
#   out[b,h,q]      = sum_s probs[b,h,q,s] * v[b,h,s]
#                   = sum_s (probs * v_scale)[b,h,q,s] * codes[b,h,s]
#
# so the matmuls run on the int8 codes (cast fused into the operand load,
# like the quantized weights) and the dequantize is a cheap elementwise
# scale on the [B, H, T, S] scores — nothing rematerializes a
# full-precision cache in HBM.


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-position symmetric int8 of a ``[..., T, D]`` k/v slice:
    (codes ``int8 [..., T, D]``, scale ``fp32 [..., T]``)."""
    x32 = x.astype(jnp.float32)
    max_abs = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.maximum(max_abs / 127.0, 1e-12)
    codes = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return codes, scale[..., 0]


def quantize_cache(cache: dict) -> dict:
    """A populated full-precision cache -> its int8 form (codes+scales
    per layer, same per-row ``length``)."""
    layers = []
    for lc in cache["layers"]:
        k_codes, k_scale = quantize_kv(lc["k"])
        v_codes, v_scale = quantize_kv(lc["v"])
        layers.append({
            "k_codes": k_codes, "k_scale": k_scale,
            "v_codes": v_codes, "v_scale": v_scale,
        })
    return {"layers": layers, "length": cache["length"]}


def init_quantized_cache(
    config: ModelConfig, batch: int, kv_heads: int | None = None
) -> dict:
    """An EMPTY int8 cache, allocated directly — no transient bf16
    buffers, no quantize pass over zeros (what ``quantize_cache`` of a
    fresh :func:`init_cache` would produce, at ~2.5x the startup HBM).
    ``kv_heads`` overrides the head count for the llama family's
    compact GQA layout.  Zero codes with the floor scale match
    ``quantize_kv`` of zeros exactly; empty slots are masked by the
    per-row ``length`` either way."""
    heads = kv_heads if kv_heads is not None else config.n_heads
    shape = (batch, heads, config.max_seq_len, config.head_dim)
    sshape = shape[:3]
    return {
        "layers": [
            {
                "k_codes": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.full(sshape, 1e-12, jnp.float32),
                "v_codes": jnp.zeros(shape, jnp.int8),
                "v_scale": jnp.full(sshape, 1e-12, jnp.float32),
            }
            for _ in range(config.n_layers)
        ],
        "length": jnp.zeros((batch,), jnp.int32),
    }


def quantized_prefill(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    attention_fn=None,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """:func:`prefill` with the populated cache quantized to int8.

    The prompt pass itself runs full precision (it is MXU-bound, not
    cache-bound); quantization happens once at the end — the decode
    steps that follow stream int8.
    """
    logits, cache = prefill(params, tokens, config, attention_fn, lengths)
    return logits, quantize_cache(cache)


def quantized_prefill_prefix(
    params: dict, prefix: jax.Array, config: ModelConfig, attention_fn=None
) -> dict:
    """:func:`prefill_prefix` in the int8 cache layout — the shared
    prefix's codes+scales, computed once.  Per-position quantization is
    position-local, so these codes are bitwise what
    :func:`quantized_prefill` of any concatenated prompt would write at
    the same positions."""
    return _prefill_prefix_impl(quantized_prefill, params, prefix, config,
                                attention_fn)


def quantized_prefill_with_prefix(
    params: dict,
    prefix_cache: dict,
    tokens: jax.Array,
    config: ModelConfig,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """:func:`prefill_with_prefix` over the int8 cache layout (the
    prefix cache comes from :func:`quantized_prefill_prefix`; the
    suffix chunk quantizes its own positions as it writes them)."""
    return _prefill_with_prefix_impl(
        quantized_chunk_decode, params, prefix_cache, tokens, config,
        lengths,
    )


def _quantized_chunk_cached_attention(
    q: jax.Array,
    k_codes: jax.Array,
    k_scale: jax.Array,
    v_codes: jax.Array,
    v_scale: jax.Array,
    start: jax.Array,
    window: int | None = None,
) -> jax.Array:
    """:func:`_chunk_cached_attention` over the int8 cache (factorized
    dequantize — see the section comment above)."""
    head_dim = q.shape[-1]
    chunk = q.shape[2]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_codes.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * k_scale[:, :, None, :] / (head_dim**0.5)
    key_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
    q_pos = start[:, None, None, None] + jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, chunk, 1), 2
    )
    valid = key_pos <= q_pos
    if window is not None:
        valid = valid & (key_pos > q_pos - window)
    scores = jnp.where(valid, scores, jnp.float32(-jnp.inf))
    probs = jax.nn.softmax(scores, axis=-1)
    weighted = (probs * v_scale[:, :, None, :]).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", weighted, v_codes.astype(q.dtype))


def _quantized_write_and_attend(window: int | None = None, broadcast=None):
    """The int8-cache write+attend both families' decode skeletons plug
    in: quantize the new position's k/v vectors, write codes+scales at
    each row's position, attend via the factorized dequantize.
    ``broadcast`` expands compact GQA codes/scales to full heads (llama;
    identity for the gpt full-head cache)."""
    expand = broadcast or (lambda t: t)

    def write_and_attend(q, k, v, layer_cache, rows, pos):
        kc, ks = quantize_kv(k[:, :, 0])  # [B, H, D] -> codes, [B, H]
        vc, vs = quantize_kv(v[:, :, 0])
        k_codes = layer_cache["k_codes"].at[rows, :, pos].set(kc)
        k_scale = layer_cache["k_scale"].at[rows, :, pos].set(ks)
        v_codes = layer_cache["v_codes"].at[rows, :, pos].set(vc)
        v_scale = layer_cache["v_scale"].at[rows, :, pos].set(vs)
        entry = {
            "k_codes": k_codes, "k_scale": k_scale,
            "v_codes": v_codes, "v_scale": v_scale,
        }
        return entry, _quantized_chunk_cached_attention(
            q, expand(k_codes), expand(k_scale), expand(v_codes),
            expand(v_scale), pos, window=window,
        )

    return write_and_attend


def quantized_decode_step(
    params: dict, cache: dict, tokens: jax.Array, config: ModelConfig
) -> tuple[jax.Array, dict]:
    """:func:`decode_step` against the int8 cache: same
    :func:`_decode_impl` skeleton, int8 write+attend.  Same ragged
    per-row contract."""
    return _decode_impl(
        params, cache, tokens, config, _quantized_write_and_attend()
    )


def _chunk_write(layer_cache, k, v, rows, cols, dtype):
    """Write a ``[B, H, T, D]`` chunk's k/v at each row's ``cols`` slots
    of the bf16 cache; returns the new entry.  Shared by the gpt and
    llama chunk decoders (the int8 twin: :func:`_quantized_chunk_write`).
    """
    return {
        "k": layer_cache["k"].at[rows, :, cols].set(
            k.transpose(0, 2, 1, 3).astype(dtype)
        ),
        "v": layer_cache["v"].at[rows, :, cols].set(
            v.transpose(0, 2, 1, 3).astype(dtype)
        ),
    }


def _quantized_chunk_write(layer_cache, k, v, rows, cols):
    """Quantize a ``[B, H, T, D]`` chunk's k/v per position and write the
    codes+scales at each row's ``cols`` slots; returns the new entry.
    Shared by the gpt and llama quantized chunk decoders."""
    kc, ks = quantize_kv(k)  # codes [B, H, T, D], scales [B, H, T]
    vc, vs = quantize_kv(v)
    return {
        "k_codes": layer_cache["k_codes"].at[rows, :, cols].set(
            kc.transpose(0, 2, 1, 3)
        ),
        "k_scale": layer_cache["k_scale"].at[rows, :, cols].set(
            ks.transpose(0, 2, 1)
        ),
        "v_codes": layer_cache["v_codes"].at[rows, :, cols].set(
            vc.transpose(0, 2, 1, 3)
        ),
        "v_scale": layer_cache["v_scale"].at[rows, :, cols].set(
            vs.transpose(0, 2, 1)
        ),
    }


def quantized_chunk_decode(
    params: dict, cache: dict, tokens: jax.Array, config: ModelConfig
) -> tuple[jax.Array, dict]:
    """:func:`chunk_decode` against the int8 cache: quantize the chunk's
    k/v per position, write codes+scales, attend via the factorized
    dequantize.  Per-position quantization makes the written codes
    IDENTICAL to what T :func:`quantized_decode_step` calls would write,
    so the speculative verify step stays exact relative to sequential
    quantized decode (same caveat as the bf16 pair: up to argmax ties).
    """

    def write_and_attend(q, k, v, layer_cache, rows, cols, start):
        entry = _quantized_chunk_write(layer_cache, k, v, rows, cols)
        return entry, _quantized_chunk_cached_attention(
            q, entry["k_codes"], entry["k_scale"], entry["v_codes"],
            entry["v_scale"], start,
        )

    return _chunk_decode_impl(params, cache, tokens, config,
                              write_and_attend)


def _mask_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    """Keep the ``top_k`` highest logits per row, ``-inf`` elsewhere.
    Ties at the k-th value are all kept (the usual top-k caveat)."""
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _mask_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    """Nucleus filter: keep the smallest set of tokens whose cumulative
    probability reaches ``top_p`` (the highest-probability token is always
    kept), ``-inf`` elsewhere.  One sort over the vocab per row — cheap
    against the decode step's cache GEMVs."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
    keep = exclusive_cum < top_p  # position 0 always kept (cum 0 < p)
    kth = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= kth, logits, -jnp.inf)


def _chunk_cached_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    start: jax.Array,
    window: int | None = None,
) -> jax.Array:
    """``T`` query positions per row against the padded cache.

    ``q``: ``[B, H, T, D]`` for global positions ``start[b] + t``; cache:
    ``[B, H, S_max, D]`` with the chunk's keys already written at those
    positions.  Query ``t`` attends cache entries ``<= start[b] + t`` —
    the causal mask of a chunk appended to a ragged prefix (fp32
    scores/softmax, like :func:`_cached_attention`).  ``window``
    additionally hides entries older than the query's last ``window``
    positions (sliding-window models).
    """
    head_dim = q.shape[-1]
    chunk = q.shape[2]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) / (head_dim**0.5)
    key_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
    q_pos = start[:, None, None, None] + jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, chunk, 1), 2
    )
    valid = key_pos <= q_pos
    if window is not None:
        valid = valid & (key_pos > q_pos - window)
    scores = jnp.where(valid, scores, jnp.float32(-jnp.inf))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v_cache)


def _chunk_decode_impl(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    config: ModelConfig,
    write_and_attend,
) -> tuple[jax.Array, dict]:
    """The gpt-family chunk-decode skeleton both cache layouts share:
    embed at each row's chunk positions, per layer call
    ``write_and_attend(q, k, v, layer_cache, rows, cols, start) ->
    (new_entry, out)``, full-chunk logits (same seam shape as
    :func:`_decode_impl`; the llama counterpart is
    ``llama._llama_chunk_decode_impl``)."""
    start = cache["length"]  # [B]
    batch, chunk = tokens.shape
    rows = jnp.arange(batch)[:, None]
    cols = start[:, None] + jnp.arange(chunk)[None, :]  # [B, T]
    x = (
        params["embed"][tokens]
        + params["pos_embed"][cols]
    )
    new_layers = []
    for layer, layer_cache in zip(params["layers"], cache["layers"]):

        def attend(q, k, v, _lc=layer_cache):
            entry, out = write_and_attend(q, k, v, _lc, rows, cols, start)
            new_layers.append(entry)
            return out

        x = _block(x, layer, config, attend)
    x = _layer_norm(x, params["final_ln_scale"], params["final_ln_bias"])
    logits = jnp.einsum(
        "bsd,vd->bsv", x, params["embed"], preferred_element_type=jnp.float32
    )
    return logits, {"layers": new_layers, "length": start + chunk}


def chunk_decode(
    params: dict, cache: dict, tokens: jax.Array, config: ModelConfig
) -> tuple[jax.Array, dict]:
    """Decode a ``T``-token chunk per row in ONE forward.

    ``tokens``: int32 ``[B, T]`` — row ``b``'s inputs for positions
    ``cache["length"][b] .. +T-1``.  Returns (fp32 logits ``[B, T,
    vocab]`` — entry ``t`` is the next-token distribution after
    consuming input ``t`` — and the cache advanced by ``T``).

    This is the verify step of speculative decoding (:mod:`.speculative`):
    a draft proposes T-1 tokens and the target scores them all for the
    price of one MXU-friendly ``T``-wide forward instead of T
    bandwidth-bound single-token steps.  Equivalent to T
    :func:`decode_step` calls by construction (the chunk's keys land in
    the same cache slots; the mask reproduces causality).
    """

    def write_and_attend(q, k, v, layer_cache, rows, cols, start):
        # write the chunk's k/v at each row's positions, then attend
        # the T queries against the whole (row+chunk masked) cache
        entry = _chunk_write(layer_cache, k, v, rows, cols, config.dtype)
        return entry, _chunk_cached_attention(
            q, entry["k"], entry["v"], start
        )

    return _chunk_decode_impl(params, cache, tokens, config,
                              write_and_attend)


def block_decode(
    params: dict,
    cache: dict,
    current: jax.Array,
    done: jax.Array,
    remaining: jax.Array,
    keys: jax.Array,
    config: ModelConfig,
    step_fn=None,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    freeze: jax.Array | None = None,
    corrupt: jax.Array | None = None,
    health: bool = False,
):
    """Advance every live row up to ``block = keys.shape[0]`` tokens in
    ONE compiled call — a ``lax.scan`` of decode steps with on-device
    per-row liveness masks, so the host pays one dispatch + one sync per
    *block* instead of per token.

    Per-row state (all ``[batch]``, owned by the caller across calls):

    - ``current``: the next input token (the last emitted one);
    - ``done``: the row emitted ``eos_id`` (or holds no request at all —
      frozen rows start every block with ``done=True``);
    - ``remaining``: tokens the row may still emit (its budget).

    A row is **live** at a scan step iff ``~done & (remaining > 0)``.
    Live rows run exactly the single-step computation (same
    :func:`decode_step`/:func:`_pick` math — per-row results are
    byte-identical to single-stepping, because rows never interact across
    the batch axis).  Frozen rows still *compute* (lockstep static shapes,
    the same discipline as every other masked path here) but neither
    advance — their ``length`` is restored to its pre-step value, so the
    stray k/v write lands at a fixed already-dead position that the next
    admission overwrites — nor emit, nor consume budget.

    Liveness is monotone (``done`` only sets, ``remaining`` only falls),
    so each row's emissions form a contiguous PREFIX of the block:
    returns ``(cache, current, done, remaining, tokens [block, batch],
    counts [batch])`` where ``tokens[:counts[b], b]`` are row ``b``'s
    kept tokens this block (post-eos positions hold a pad the host never
    reads).  ``eos_id`` sets ``done`` the step it is emitted — the eos
    itself is a kept token, exactly like the single-step host loop.

    Robustness seams (the sharded plane's chaos machinery; all default
    off and leave the compiled program byte-identical when unused):

    - ``freeze`` (traced bool, scalar or per-row): treat every matching
      row as non-live for the whole block — it computes (lockstep static
      shapes) but neither advances, emits, nor spends budget.  The
      deterministic "wedged shard" fault is this flag held True.
    - ``corrupt`` (traced bool, scalar or per-row): overwrite the step's
      logits with NaN BEFORE sampling — the deterministic "poisoned
      logits" fault (emitted tokens become garbage the caller must
      discard; the health flag below is how it finds out).
    - ``health=True``: additionally return a ``bad [batch]`` bool — row
      was live at some step whose logits contained a non-finite value.
      The flag is computed from the same logits the pick consumed, so a
      poisoned row can never emit silently.
    """
    if step_fn is None:
        step_fn = decode_step
    pad = eos_id if eos_id is not None else 0

    def body(carry, key):
        if health:
            cache, current, done, remaining, bad = carry
        else:
            cache, current, done, remaining = carry
        live = ~done & (remaining > 0)
        if freeze is not None:
            live = live & ~freeze
        logits, stepped = step_fn(params, cache, current, config)
        if corrupt is not None:
            nan = jnp.full_like(logits, jnp.nan)
            logits = jnp.where(jnp.reshape(corrupt, (-1, 1)), nan, logits)
        if health:
            bad = bad | (live & ~jnp.all(jnp.isfinite(logits), axis=-1))
        nxt = _pick(logits, key, temperature, top_k, top_p)
        emitted = jnp.where(live, nxt, pad)
        if eos_id is not None:
            done = done | (live & (nxt == eos_id))
        remaining = jnp.where(live, remaining - 1, remaining)
        current = jnp.where(live, nxt, current)
        cache = dict(
            stepped,
            length=jnp.where(live, stepped["length"], cache["length"]),
        )
        carry = (
            (cache, current, done, remaining, bad) if health
            else (cache, current, done, remaining)
        )
        return carry, (emitted, live)

    init = (cache, current, done, remaining)
    if health:
        init = init + (jnp.zeros(current.shape, bool),)
    carry, (tokens, lives) = jax.lax.scan(body, init, keys)
    counts = jnp.sum(lives.astype(jnp.int32), axis=0)
    if health:
        cache, current, done, remaining, bad = carry
        return cache, current, done, remaining, tokens, counts, bad
    cache, current, done, remaining = carry
    return cache, current, done, remaining, tokens, counts


def gang_block_decode(
    params: dict,
    cache: dict,
    current: jax.Array,
    done: jax.Array,
    remaining: jax.Array,
    keys: jax.Array,
    shard_active: jax.Array,
    config: ModelConfig,
    step_fn=None,
    *,
    shards: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    fold_keys: bool = False,
    poison: jax.Array | None = None,
    wedge: jax.Array | None = None,
) -> tuple[dict, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array,
           jax.Array, jax.Array]:
    """Advance ``shards`` stacked engine shards with ONE compiled call.

    The operands are the flat ``[S*B]`` row space a
    :class:`~.continuous.ContinuousBatcher` already owns; internally each
    leaf is viewed ``[S, B, ...]`` and :func:`block_decode` is ``vmap``ed
    over the leading shard axis — per-shard results are byte-identical
    to ``S`` independent engines of ``B`` slots each, because rows never
    interact across the batch axis and the vmapped inner computation is
    the independent engine's computation (pinned by the scale bench's
    parity gate).  Decode needs NO cross-shard communication at all; the
    only cross-shard product is the trailing ``[S]`` free-slot summary,
    a reduction the host fetches once per cycle — overlapped with the
    next block via the caller's dispatch-ahead — as the plane's
    device-confirmed depth signal (per-shard observability and the
    one-transfer-per-cycle contract the bench pins).  Routing itself
    reads the host's own slot bookkeeping, which is authoritative and
    costs no transfer at all.

    ``shard_active`` (bool ``[S]``) is the device-side scale mask:
    deactivated shards report 0 free slots (the admission plane stops
    routing to them instantly) while their in-flight rows keep decoding
    to completion — the drain contract.  Flipping it is O(1); no state
    moves, nothing recompiles.

    ``fold_keys`` (sampled serving): fold the shard index into each
    block key so shards draw independent PRNG streams instead of every
    shard replaying one stream.  Greedy ignores keys entirely.

    ``poison``/``wedge`` (bool ``[S]``, optional) are the deterministic
    shard-fault seams: a poisoned shard's logits become NaN before
    sampling (its emissions are garbage the caller discards on the
    health flag), a wedged shard's rows are frozen for the whole block
    (computes, emits nothing, advances nothing) — flag flips, not
    process murder, exactly like :class:`~..sim.faults.FleetFaultPlan`.

    Returns ``(cache, current, done, remaining, tokens [block, S*B],
    counts [S*B], free [S], bad [S])`` — the flat-state contract of
    :func:`block_decode` plus the per-shard free summary and the
    per-shard health sentinel (``bad[s]`` = some live row of shard
    ``s`` saw non-finite logits this block).  Both ``[S]`` vectors are
    reduced ON DEVICE and ride the caller's one combined settle
    transfer — health detection adds zero host syncs per cycle.
    """
    if step_fn is None:
        step_fn = decode_step
    rows = current.shape[0]
    if rows % shards:
        raise ValueError(f"{rows} rows not divisible by {shards} shards")
    slots = rows // shards

    def to_shards(leaf):
        return leaf.reshape((shards, slots) + leaf.shape[1:])

    def to_rows(leaf):
        return leaf.reshape((rows,) + leaf.shape[2:])

    cache_s = jax.tree.map(to_shards, cache)
    cur_s, done_s, rem_s = (to_shards(x) for x in (current, done, remaining))
    if fold_keys:
        shard_keys = jax.vmap(
            lambda s: jax.vmap(lambda k: jax.random.fold_in(k, s))(keys)
        )(jnp.arange(shards))
        key_axis = 0
    else:
        shard_keys = keys
        key_axis = None
    if poison is None:
        poison = jnp.zeros((shards,), bool)
    if wedge is None:
        wedge = jnp.zeros((shards,), bool)

    def one_shard(shard_cache, cur, done, rem, shard_keys, poisoned,
                  wedged):
        return block_decode(
            params, shard_cache, cur, done, rem, shard_keys, config,
            step_fn, temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, freeze=wedged, corrupt=poisoned, health=True,
        )

    cache_s, cur_s, done_s, rem_s, toks, counts, bad_rows = jax.vmap(
        one_shard, in_axes=(0, 0, 0, 0, key_axis, 0, 0)
    )(cache_s, cur_s, done_s, rem_s, shard_keys, poison, wedge)
    # [S, block, B] -> [block, S*B]: the host consume loop reads the same
    # (position, row) layout the single-plane block engine returns
    block = toks.shape[1]
    tokens = jnp.transpose(toks, (1, 0, 2)).reshape(block, rows)
    free = jnp.where(
        shard_active,
        jnp.sum((done_s | (rem_s <= 0)).astype(jnp.int32), axis=1),
        0,
    )
    bad = jnp.any(bad_rows, axis=1)
    return (
        jax.tree.map(to_rows, cache_s), to_rows(cur_s), to_rows(done_s),
        to_rows(rem_s), tokens, counts.reshape(rows), free, bad,
    )


# ---------------------------------------------------------------------------
# Prefix caching: share one prompt prefix's KV across a batch of requests
# ---------------------------------------------------------------------------


def _prefill_prefix_impl(prefill_fn, params, prefix, config,
                         attention_fn=None) -> dict:
    """The one prefix-build wrapper all four family/layout variants
    share: normalize the prefix to a batch-1 int32 prompt, prefill it
    with ``prefill_fn``, and return the cache."""
    prefix = jnp.asarray(prefix, jnp.int32)
    if prefix.ndim == 1:
        prefix = prefix[None, :]
    _, cache = prefill_fn(params, prefix, config, attention_fn)
    return cache


def prefill_prefix(
    params: dict, prefix: jax.Array, config: ModelConfig, attention_fn=None
) -> dict:
    """KV cache of a SHARED prompt prefix, computed once.

    Serving fleets front most requests with the same system prompt; its
    prefill FLOPs and KV bytes are identical for every request, so they
    should be paid once per process, not once per batch.  ``prefix``:
    int32 ``[prefix_len]`` (or ``[1, prefix_len]``) → a batch-1 cache at
    ``length == prefix_len`` to hand to :func:`prefill_with_prefix` (or
    its llama twin).  No reference counterpart: the reference has no
    model serving (SURVEY.md §2); the design is the standard
    prefix-cache one (vLLM's shared-prompt case), re-expressed over this
    package's padded-cache layout.
    """
    return _prefill_prefix_impl(prefill, params, prefix, config,
                                attention_fn)


def broadcast_prefix(prefix_cache: dict, batch: int) -> dict:
    """A batch-1 prefix cache -> a batch-``B`` starting cache (one
    materialized copy per row: every row decodes into its OWN cache
    slots past the shared prefix)."""
    def rows(leaf):
        return jnp.broadcast_to(leaf, (batch, *leaf.shape[1:]))

    return {
        "layers": [
            {name: rows(leaf) for name, leaf in layer.items()}
            for layer in prefix_cache["layers"]
        ],
        "length": jnp.broadcast_to(prefix_cache["length"], (batch,)),
    }


def _prefill_with_prefix_impl(
    chunk_decode_fn,
    params: dict,
    prefix_cache: dict,
    tokens: jax.Array,
    config,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """The one suffix-continuation implementation both families share
    (``chunk_decode_fn`` is the family's chunk decoder): broadcast the
    prefix, run the suffix chunk, read out each row's last valid
    position, and account ragged lengths into the cache."""
    batch, _ = tokens.shape
    cache = broadcast_prefix(prefix_cache, batch)
    start = cache["length"]
    logits_all, cache = chunk_decode_fn(params, cache, tokens, config)
    if lengths is None:
        return logits_all[:, -1], cache
    lengths = lengths.astype(jnp.int32)
    logits = logits_all[jnp.arange(batch), lengths - 1]
    return logits, dict(cache, length=start + lengths)


def prefill_with_prefix(
    params: dict,
    prefix_cache: dict,
    tokens: jax.Array,
    config: ModelConfig,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """Per-request suffixes continue from a shared prefix's cache.

    ``tokens``: int32 ``[batch, suffix_len]`` — each row's own tokens,
    occupying positions ``[P, P + suffix_len)`` after the ``P``-token
    prefix.  One :func:`chunk_decode` forward writes the suffix k/v and
    attends prefix + causal suffix, computing the same attention as
    :func:`prefill` of the concatenated prompts at
    ``suffix/(prefix+suffix)`` of the FLOPs — equal up to
    reduction-order rounding (the chunk path softmaxes over the masked
    full-cache axis; ~1e-7 in fp32, so an argmax tie could in principle
    flip a sampled token — the same caveat every kernel-vs-dense pair
    here carries).  ``lengths`` marks ragged right-padded suffixes,
    same contract as :func:`prefill`.  Returns (readout logits
    ``[batch, vocab]``, cache at ``P + suffix_len`` — or
    ``P + lengths[i]`` — per row).
    """
    return _prefill_with_prefix_impl(
        chunk_decode, params, prefix_cache, tokens, config, lengths
    )


def _concrete_prefix_len(prefix_cache: dict) -> int | None:
    """The prefix length when it is host-readable (eager callers), else
    ``None`` (inside jit the length is a tracer and bounds become the
    caller's contract)."""
    try:
        return int(prefix_cache["length"][0])
    except jax.errors.ConcretizationTypeError:
        return None


def _check_prefix_layout(prefix_cache: dict, quantized: bool) -> None:
    """A prefix cache must match the decode path's layout: int8
    codes+scales for a quantized decode (:func:`quantized_prefill_prefix`),
    bf16 k/v otherwise (:func:`prefill_prefix`) — a mismatch would
    surface as a KeyError deep inside the chunk decoder."""
    is_quantized = "k_codes" in prefix_cache["layers"][0]
    if is_quantized != quantized:
        want = "quantized (int8)" if quantized else "full-precision"
        got = "quantized (int8)" if is_quantized else "full-precision"
        raise ValueError(
            f"prefix cache layout mismatch: this decode path needs a "
            f"{want} prefix cache but was given a {got} one (build it "
            f"with the matching prefill_prefix variant)"
        )


def _check_prefix_budget(
    prefix_cache: dict | None, prompt_len: int, num_tokens: int, config,
    slack: int = 0, slack_label: str = "", model_name: str = "",
) -> None:
    """The generate-entry bound check every decode entry shares: with a
    prefix the full budget is prefix + prompt + num_tokens (+ ``slack``
    — the speculative entry passes its 2k draft window, labeled);
    eager callers get the real check (the cache length is concrete),
    traced callers the partial one (inside jit the bound is the
    caller's contract — ``__main__`` and ``ContinuousBatcher`` both
    check it)."""
    prefix_len = (
        _concrete_prefix_len(prefix_cache) or 0
        if prefix_cache is not None else 0
    )
    if prefix_len + prompt_len + num_tokens + slack > config.max_seq_len:
        extra = f" + {slack_label} ({slack})" if slack else ""
        owner = f"the {model_name} model's " if model_name else ""
        raise ValueError(
            f"prefix ({prefix_len}) + prompt ({prompt_len}) + num_tokens "
            f"({num_tokens}){extra} exceeds "
            f"{owner}max_seq_len={config.max_seq_len}"
        )


def _pick(
    logits: jax.Array,
    key: jax.Array | None,
    temperature: float,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """The one sampling policy for every decode path (both families, all
    serving surfaces): greedy at ``temperature <= 0``; otherwise
    temperature-scaled sampling, optionally truncated by ``top_k > 0``
    and/or nucleus ``top_p < 1`` (applied in that order, on the scaled
    logits — the conventional composition).

    ``top_k``/``top_p`` are static Python values, so validation raises at
    trace time (before a worker thread is mid-batch): ``top_k`` must be
    >= 0 (values past the vocab clamp to it — "keep everything"),
    ``top_p`` must be in ``(0, 1]`` (0 would mask the argmax too and
    degenerate to always emitting token 0).
    """
    if top_k < 0:
        raise ValueError(f"top_k={top_k} must be >= 0 (0 = off)")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} must be in (0, 1] (1.0 = off)")
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, warp_logits(logits, temperature, top_k, top_p)
    ).astype(jnp.int32)


def warp_logits(
    logits: jax.Array, temperature: float, top_k: int, top_p: float
) -> jax.Array:
    """The one definition of the warped sampling distribution —
    temperature scale, then top-k, then nucleus truncation.  Shared by
    :func:`_pick` (categorical over the result) and the speculative
    sampler (whose acceptance-rule exactness depends on warping the
    draft and target identically to this policy)."""
    logits = logits / temperature
    if top_k > 0:
        logits = _mask_top_k(logits, min(top_k, logits.shape[-1]))
    if top_p < 1.0:
        logits = _mask_top_p(logits, top_p)
    return logits


def generate(
    params: dict,
    prompt: jax.Array,
    num_tokens: int,
    config: ModelConfig,
    *,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    attention_fn=None,
    lengths: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
) -> jax.Array:
    """Generate ``num_tokens`` continuation tokens for each prompt.

    ``prefix_cache`` (from :func:`prefill_prefix`) prepends a shared,
    already-prefilled prompt prefix: ``prompt`` rows are then the
    per-request SUFFIXES, continued from the prefix via
    :func:`prefill_with_prefix` — the same generations as the
    concatenated prompts (up to that function's reduction-order
    rounding caveat), minus the prefix's repeated prefill cost.

    ``eos_id`` (optional) ends a row's generation: once the row emits
    that id every later position is ``eos_id`` (the shapes stay static —
    finished rows keep stepping but their output is pinned), so
    consumers can truncate at the first eos.

    Greedy at ``temperature=0`` (default), else temperature sampling with
    ``rng``, optionally truncated by ``top_k``/nucleus ``top_p`` (see
    :func:`_pick`).  Pure and jittable end-to-end: prefill once, then a
    ``lax.scan`` of decode steps — one compiled program for the entire
    episode. Returns int32 ``[batch, num_tokens]``.

    ``lengths`` (int32 ``[batch]``) marks ragged right-padded prompts:
    each row continues from its own last real token — pad slots are
    overwritten by generated tokens and never attended (see
    :func:`prefill`) — so a padded batch generates exactly what each
    prompt would generate unpadded.

    ``quantized_cache=True`` decodes through the int8 KV cache (half the
    cache bytes each step streams; see :func:`quantized_decode_step` —
    outputs match the full-precision path to int8 rounding).
    """
    batch, prompt_len = prompt.shape
    if num_tokens < 1:
        raise ValueError(f"num_tokens must be >= 1, got {num_tokens}")
    _check_prefix_budget(prefix_cache, prompt_len, num_tokens, config)
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling requires an rng key")
    if prefix_cache is not None:
        if attention_fn is not None:
            # the prefix path prefills through the chunk decoder
            # (prefill_with_prefix), which has no attention override —
            # silently ignoring the caller's kernel pick would be worse
            raise ValueError(
                "attention_fn does not apply with prefix_cache (the "
                "suffix prefill runs the chunk decoder); drop one"
            )
        _check_prefix_layout(prefix_cache, quantized_cache)
    keys = (
        jax.random.split(rng, num_tokens)
        if rng is not None
        else jnp.zeros((num_tokens, 2), jnp.uint32)
    )
    prefill_fn = quantized_prefill if quantized_cache else prefill
    step_fn = quantized_decode_step if quantized_cache else decode_step
    if prefix_cache is not None:
        pf = (quantized_prefill_with_prefix if quantized_cache
              else prefill_with_prefix)
        logits, cache = pf(
            params, prefix_cache, prompt, config, lengths=lengths
        )
    else:
        logits, cache = prefill_fn(params, prompt, config, attention_fn,
                                   lengths=lengths)
    first = _pick(logits, keys[0], temperature, top_k, top_p)
    done0 = (
        first == eos_id if eos_id is not None
        else jnp.zeros(first.shape, bool)
    )

    def body(carry, key):
        cache, token, done = carry
        logits, cache = step_fn(params, cache, token, config)
        nxt = _pick(logits, key, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done), token

    (_, last, _), produced = jax.lax.scan(
        body, (cache, first, done0), keys[1:]
    )
    produced = jnp.moveaxis(produced, 0, 1)  # [steps-1, B] -> [B, steps-1]
    return jnp.concatenate([produced, last[:, None]], axis=1)


@partial(
    jax.jit,
    static_argnames=(
        "num_tokens", "config", "temperature", "attention_fn", "top_k",
        "top_p", "eos_id", "quantized_cache",
    ),
)
def generate_jit(
    params: dict,
    prompt: jax.Array,
    num_tokens: int,
    config: ModelConfig,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    attention_fn=None,
    lengths: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int | None = None,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
) -> jax.Array:
    """Single-chip compiled :func:`generate`. ``attention_fn`` selects the
    prompt-pass attention (static, so e.g. the Pallas flash kernel gets its
    own compiled program, exactly like ``model.forward_jit_with``).
    ``prefix_cache`` is a dynamic pytree arg: one compiled program serves
    any prefix CONTENT of the same shape."""
    return generate(
        params, prompt, num_tokens, config, temperature=temperature, rng=rng,
        attention_fn=attention_fn, lengths=lengths, top_k=top_k, top_p=top_p,
        eos_id=eos_id, quantized_cache=quantized_cache,
        prefix_cache=prefix_cache,
    )


# ---------------------------------------------------------------------------
# Mesh-sharded serving
# ---------------------------------------------------------------------------


def require_serving_mesh(mesh: Mesh) -> None:
    """The one serving-mesh contract check: decode needs a
    ``(data, model)`` mesh — ring/sequence parallelism applies to
    training and prefill, not token-by-token decode.  Shared by every
    sharded serving factory (generate, beams, continuous slots)."""
    if mesh.shape.get("seq", 1) != 1:
        raise ValueError(
            "serving uses a (data, model) mesh; got seq="
            f"{mesh.shape['seq']} (ring/sequence parallelism applies to "
            "training and prefill, not token-by-token decode)"
        )


def cache_shardings(mesh: Mesh, cache: dict) -> dict:
    """Cache layout on the mesh: batch over ``data``, the cache's head
    axis over ``model`` (full heads for the gpt family via ``wqkv``'s
    output sharding; compact kv heads for llama via ``wkv``'s), positions
    unsharded.  Works for both cache layouts — bf16 ``k``/``v``
    ``[B, H, S, D]`` and the int8 codes ``[B, H, S, D]`` + scales
    ``[B, H, S]`` (same leading axes, one fewer trailing dim).  Serving
    uses no ``seq`` axis — decode has nothing to ring over."""
    four = NamedSharding(mesh, P("data", "model", None, None))
    three = NamedSharding(mesh, P("data", "model", None))

    def entry_shardings(layer: dict) -> dict:
        return {
            name: (four if leaf.ndim == 4 else three)
            for name, leaf in layer.items()
        }

    return {
        "layers": [entry_shardings(layer) for layer in cache["layers"]],
        # per-row lengths ride with their rows
        "length": NamedSharding(mesh, P("data")),
    }


def prefix_cache_shardings(mesh: Mesh, prefix_cache: dict) -> dict:
    """Shardings for a batch-1 prefix cache on a serving mesh: heads over
    ``model`` exactly like :func:`cache_shardings` (both layouts — bf16
    k/v and int8 codes+scales), but the batch axis UNSHARDED — the
    prefix is one shared row that ``broadcast_prefix`` expands to every
    data shard's rows inside the compiled generate."""
    four = NamedSharding(mesh, P(None, "model", None, None))
    three = NamedSharding(mesh, P(None, "model", None))

    def entry_shardings(layer: dict) -> dict:
        return {
            name: (four if leaf.ndim == 4 else three)
            for name, leaf in layer.items()
        }

    return {
        "layers": [entry_shardings(layer) for layer in prefix_cache["layers"]],
        "length": NamedSharding(mesh, P(None)),
    }


def compile_serving_fns(
    mesh: Mesh,
    params: Any,
    cache_template: dict,
    prefill_fn: Any,
    decode_fn: Any,
    generate_fn: Any,
    prefix_cache: dict | None = None,
):
    """The family-agnostic serving jit wiring (one implementation for the
    gpt and llama families — only the four family ops differ).

    Requires a serving mesh (``seq`` axis of size 1): tensor-parallel heads
    + data-parallel batch. Shardings are pinned on inputs and outputs so
    the cache never reshards between steps.  Family ops (config already
    bound): ``prefill_fn(params, tokens)``,
    ``decode_fn(params, cache, token)``, and
    ``generate_fn(params, prompt, num_tokens, temperature, rng, lengths,
    top_k, top_p, eos_id, prefix_cache)``.

    ``prefix_cache`` (a batch-1 cache from the family's
    ``prefill_prefix`` variant, in ``cache_template``'s layout — bf16 or
    int8) pins a shared prompt prefix into the compiled generate: it is
    device_put ONCE under :func:`prefix_cache_shardings` (heads over
    ``model``, batch replicated over ``data``) and injected as a hidden
    leading operand, so the returned generate keeps the same external
    signature and every prompt row is a suffix continuing from the
    shared prefix (identical outputs to prepending it, minus its
    repeated prefill — the single-chip ``generate(prefix_cache=...)``
    contract, sharded).

    The returned generate fn's signature is ``(params, prompt, rng,
    lengths, num_tokens, temperature=0.0, top_k=0, top_p=1.0,
    eos_id=None)``, all positional (pjit rejects kwargs when in_shardings
    is set); rng is required — pass any key under greedy (temperature=0
    ignores it) — and so are ``lengths`` (pass the full prompt length per
    row when nothing is padded), so ragged and full batches share the
    compiled layout.  ``top_k``/``top_p``/``eos_id`` are static (see
    ``_pick``; eos pins a finished row's later positions to the id, same
    contract as single-chip :func:`generate` — the done mask is per-row
    elementwise, so it shards over ``data`` like every other row state).
    """
    from .train import param_shardings

    require_serving_mesh(mesh)
    p_shard = param_shardings(mesh, params)
    tokens_1d = NamedSharding(mesh, P("data"))
    tokens_2d = NamedSharding(mesh, P("data", None))
    logits_s = NamedSharding(mesh, P("data", None))
    c_shard = cache_shardings(mesh, cache_template)

    prefill_jit = jax.jit(
        prefill_fn,
        in_shardings=(p_shard, tokens_2d),
        out_shardings=(logits_s, c_shard),
    )
    decode_jit = jax.jit(
        decode_fn,
        in_shardings=(p_shard, c_shard, tokens_1d),
        out_shardings=(logits_s, c_shard),
        donate_argnums=1,  # reuse the cache buffers step to step
    )

    if prefix_cache is None:

        def _generate(params, prompt, rng, lengths, num_tokens,
                      temperature=0.0, top_k=0, top_p=1.0, eos_id=None):
            return generate_fn(params, prompt, num_tokens, temperature, rng,
                               lengths, top_k, top_p, eos_id, None)

        generate_jit_fn = jax.jit(
            _generate,
            static_argnames=("num_tokens", "temperature", "top_k", "top_p",
                             "eos_id"),
            in_shardings=(p_shard, tokens_2d, NamedSharding(mesh, P()),
                          tokens_1d),
            out_shardings=tokens_2d,
        )
        return prefill_jit, decode_jit, generate_jit_fn

    pfx_shard = prefix_cache_shardings(mesh, prefix_cache)
    placed_prefix = jax.device_put(prefix_cache, pfx_shard)

    def _generate_pfx(params, prefix, prompt, rng, lengths, num_tokens,
                      temperature=0.0, top_k=0, top_p=1.0, eos_id=None):
        return generate_fn(params, prompt, num_tokens, temperature, rng,
                           lengths, top_k, top_p, eos_id, prefix)

    pfx_jit = jax.jit(
        _generate_pfx,
        static_argnames=("num_tokens", "temperature", "top_k", "top_p",
                         "eos_id"),
        in_shardings=(p_shard, pfx_shard, tokens_2d,
                      NamedSharding(mesh, P()), tokens_1d),
        out_shardings=tokens_2d,
    )

    def generate_with_prefix(params, prompt, rng, lengths, num_tokens,
                             temperature=0.0, top_k=0, top_p=1.0,
                             eos_id=None):
        return pfx_jit(params, placed_prefix, prompt, rng, lengths,
                       num_tokens, temperature, top_k, top_p, eos_id)

    return prefill_jit, decode_jit, generate_with_prefix


def make_serving_fns(
    mesh: Mesh,
    config: ModelConfig,
    params: Any,
    *,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
):
    """Compile (prefill, decode_step, generate) over the mesh for the
    gpt family (see :func:`compile_serving_fns` for the contract; the
    llama counterpart is ``llama.make_llama_serving_fns``).

    ``quantized_cache=True`` serves through the int8 KV cache — the
    codes/scales shard exactly like the bf16 cache (heads over
    ``model``, :func:`cache_shardings` is layout-agnostic), so decode
    streams half the cache bytes per step per shard.  ``prefix_cache``
    (from :func:`prefill_prefix` / :func:`quantized_prefill_prefix`,
    layout matching) pins a shared prompt prefix into the sharded
    generate; both options compose."""
    batch = mesh.shape["data"]
    if quantized_cache:
        template = jax.eval_shape(
            lambda: init_quantized_cache(config, batch)
        )
        prefill_fn = partial(quantized_prefill, config=config)
        decode_fn = partial(quantized_decode_step, config=config)
    else:
        template = jax.eval_shape(lambda: init_cache(config, batch))
        prefill_fn = partial(prefill, config=config)
        decode_fn = partial(decode_step, config=config)
    if prefix_cache is not None:
        _check_prefix_layout(prefix_cache, quantized_cache)
    return compile_serving_fns(
        mesh,
        params,
        template,
        prefill_fn,
        decode_fn,
        lambda params, prompt, num_tokens, temperature, rng, lengths,
               top_k, top_p, eos_id, prefix:
            generate(
                params, prompt, num_tokens, config,
                temperature=temperature, rng=rng, lengths=lengths,
                top_k=top_k, top_p=top_p, eos_id=eos_id,
                quantized_cache=quantized_cache, prefix_cache=prefix,
            ),
        prefix_cache=prefix_cache,
    )
