"""A second model family: Llama-style decoder (RoPE, GQA, RMSNorm, SwiGLU).

The reference has no model code at all (SURVEY.md §2); the package's first
workload (:mod:`.model`) is a GPT-2-shaped transformer (learned positions,
MHA, LayerNorm, GELU).  This module adds the architecture modern open
models actually ship — rotary position embeddings, grouped-query
attention, RMSNorm, and a SwiGLU MLP — as a *separate family* with the
same integration seams, so everything else (train step via
:func:`.train.make_train_step`'s ``loss`` seam, PARAM_AXES-driven
sharding, checkpointing, the serving worker) applies unchanged.

TPU-first notes:

- **GQA = smaller KV cache**: the cache stores ``n_kv_heads`` heads
  (``[B, H_kv, S, D]``); query heads share them in groups.  Decode is
  HBM-bandwidth-bound, so an 8x head reduction is ~8x less cache traffic.
  K/V are broadcast to full heads only inside the attention compute
  (XLA fuses the broadcast into the matmul).
- **RoPE in fp32**: rotation angles and the rotation itself run in fp32
  (bf16 angles visibly degrade long-context quality), output cast back.
- **RMSNorm/SwiGLU**: fp32 statistics like the sibling model's LayerNorm;
  gate/up projections fused into one matmul (``w_gate_up``) for one MXU
  pass, split on the output axis — output-axis sharding stays
  tensor-parallel via PARAM_AXES ``("model", "ff2")``.

Sharding: query heads shard over ``"model"`` like the sibling model; K/V
projections shard over ``"model"`` too, which requires
``n_kv_heads % tensor_parallel == 0`` (checked at mesh placement time by
the divisibility of the array dimension itself).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from .model import _dense_attention


@dataclass(frozen=True)
class LlamaConfig:
    """Llama-family dimensions (defaults sized for quick runs)."""

    vocab_size: int = 8192
    d_model: int = 512
    n_heads: int = 8
    n_kv_heads: int = 2  # GQA: query heads share n_heads//n_kv_heads groups
    n_layers: int = 4
    d_ff: int = 1408  # SwiGLU convention: ~2/3 * 4 * d_model, 128-aligned
    max_seq_len: int = 1024
    rope_theta: float = 10_000.0
    # RMSNorm epsilon: 1e-6 is the Llama-1/3 convention; Llama-2
    # checkpoints ship 1e-5 (carried through by .hf_convert)
    rms_eps: float = 1e-6
    # Mistral-style sliding-window attention: each position attends only
    # its last `sliding_window` keys (None = full causal).  Carried from
    # HF Mistral configs by .hf_convert; applies to training forwards,
    # prefill, and decode alike.
    sliding_window: int | None = None
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self) -> None:
        if self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_heads={self.n_heads} must be divisible by "
                f"n_kv_heads={self.n_kv_heads}"
            )
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model={self.d_model} must be divisible by "
                f"n_heads={self.n_heads}"
            )


# sharding rules for this family's parameter names live in
# model.PARAM_AXES (the one static registry, like the MoE entries) so
# placement never depends on whether this module was imported


def init_llama_params(
    rng: jax.Array, config: LlamaConfig, dense_mlp: bool = True
) -> dict:
    """Parameter pytree (scaled-normal init, bf16 storage, fp32 norms).

    ``dense_mlp=False`` skips the per-layer SwiGLU weights — for the MoE
    variant, which replaces them with routed experts and would otherwise
    throw the freshly-sampled weights away (same flag as
    :func:`.model.init_params`).
    """
    dtype = config.dtype
    head_dim = config.head_dim
    kv_dim = config.n_kv_heads * head_dim
    keys = jax.random.split(rng, 1 + config.n_layers)

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    params = {
        "embed": normal(keys[0], (config.vocab_size, config.d_model), 0.02),
        "final_norm": jnp.ones((config.d_model,), dtype),
        "layers": [],
    }
    out_scale = 0.02 / (2 * config.n_layers) ** 0.5
    for i in range(config.n_layers):
        lk = jax.random.split(keys[1 + i], 4)
        layer = {
            "attn_norm": jnp.ones((config.d_model,), dtype),
            "wq": normal(lk[0], (config.d_model, config.d_model), 0.02),
            "wkv": normal(lk[1], (config.d_model, 2 * kv_dim), 0.02),
            "wo": normal(lk[2], (config.d_model, config.d_model), out_scale),
            "mlp_norm": jnp.ones((config.d_model,), dtype),
        }
        if dense_mlp:
            layer["w_gate_up"] = normal(
                lk[3], (config.d_model, 2 * config.d_ff), 0.02
            )
            layer["w_down"] = normal(
                jax.random.fold_in(lk[3], 1),
                (config.d_ff, config.d_model), out_scale,
            )
        params["layers"].append(layer)
    return params


def _rms_norm(
    x: jax.Array, scale: jax.Array, eps: float = 1e-6
) -> jax.Array:
    """fp32 statistics, model-dtype output (no mean subtraction, no bias)."""
    x32 = x.astype(jnp.float32)
    normed = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps
    )
    return (normed * scale.astype(jnp.float32)).astype(x.dtype)


def readout_weights(params: dict) -> jax.Array:
    """The unembedding matrix ``[vocab, d_model]``: a separate ``lm_head``
    when the checkpoint ships one (untied, e.g. Llama-2 via
    :mod:`.hf_convert`), else the tied input embedding."""
    head = params.get("lm_head")
    return head if head is not None else params["embed"]


def rope_angles(
    positions: jax.Array, head_dim: int, theta: float
) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables ``[*positions.shape, head_dim/2]`` in fp32."""
    freqs = theta ** (
        -jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float
) -> jax.Array:
    """Rotate ``[B, H, S, D]`` by per-position angles (fp32 rotation).

    ``positions``: int32 ``[S]`` (broadcast over batch/heads).  Pairs
    ``(x[2i], x[2i+1])`` rotate by ``pos * theta^(-2i/D)``.
    """
    cos, sin = rope_angles(positions, x.shape[-1], theta)  # [S, D/2]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., 0::2], x32[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    return (
        jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(x.dtype)
    )


def _split_heads(t: jax.Array, n_heads: int, head_dim: int) -> jax.Array:
    batch, seq, _ = t.shape
    return t.reshape(batch, seq, n_heads, head_dim).transpose(0, 2, 1, 3)


def repeat_kv(t: jax.Array, groups: int) -> jax.Array:
    """``[B, H_kv, S, D] -> [B, H_kv*groups, S, D]`` (GQA broadcast).

    Done just before the attention matmuls; XLA fuses the broadcast, so
    the full-head K/V never lives in HBM.
    """
    if groups == 1:
        return t
    batch, kv_heads, seq, dim = t.shape
    return jnp.broadcast_to(
        t[:, :, None], (batch, kv_heads, groups, seq, dim)
    ).reshape(batch, kv_heads * groups, seq, dim)


def expand_gqa(t: jax.Array, groups: int) -> jax.Array:
    """:func:`repeat_kv` for any rank: 4-d codes/values broadcast
    directly; 3-d per-position int8-cache scales ``[B, H_kv, S]`` ride
    the same broadcast through a trailing dummy dim.  The one GQA
    expansion the quantized decode paths use for both leaf kinds."""
    if t.ndim == 3:
        return repeat_kv(t[..., None], groups)[..., 0]
    return repeat_kv(t, groups)


def _project_qkv(
    h: jax.Array, layer: dict, config: LlamaConfig, positions: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """q (full heads, rotated), k (kv heads, rotated), v (kv heads).

    Layers carry either the fused ``wkv`` (one MXU matmul — the
    single-chip layout) or split ``wk``/``wv`` (the pipeline stage
    layout, whose fully-manual tensor-parallel sharding needs contiguous
    kv heads per projection — a fused ``2*kv_dim`` axis chunks across
    the k/v boundary); both produce identical values, mirroring
    :func:`.model._project_qkv`'s two layouts.
    """
    head_dim = config.head_dim
    q = _split_heads(h @ layer["wq"], config.n_heads, head_dim)
    if "wkv" in layer:
        k, v = jnp.split(h @ layer["wkv"], 2, axis=-1)
    else:
        k, v = h @ layer["wk"], h @ layer["wv"]
    k = _split_heads(k, config.n_kv_heads, head_dim)
    v = _split_heads(v, config.n_kv_heads, head_dim)
    q = apply_rope(q, positions, config.rope_theta)
    k = apply_rope(k, positions, config.rope_theta)
    return q, k, v


def _swiglu(x: jax.Array, layer: dict) -> jax.Array:
    """SwiGLU from either the fused ``w_gate_up`` or the pipeline stage
    layout's split ``w_gate``/``w_up`` (contiguous ff columns per
    projection under tensor-parallel sharding)."""
    if "w_gate_up" in layer:
        gate, up = jnp.split(x @ layer["w_gate_up"], 2, axis=-1)
    else:
        gate, up = x @ layer["w_gate"], x @ layer["w_up"]
    return (jax.nn.silu(gate) * up) @ layer["w_down"]


def _llama_block(
    x: jax.Array,
    layer: dict,
    config: LlamaConfig,
    positions: jax.Array,
    attend,
    mlp=None,
    reduce=None,
    promote=None,
) -> jax.Array:
    """Pre-RMSNorm attention + pre-RMSNorm SwiGLU, residual both.

    ``attend(q, k, v) -> [B, H, S, D]`` receives GQA-shaped k/v
    (``H_kv`` heads); the default broadcasts to full heads and runs the
    shared dense causal kernel.  ``mlp(h, layer)`` overrides the
    feed-forward (dense :func:`_swiglu` by default; the routed SwiGLU
    expert MLP for the MoE variant).  The single source of truth for the
    family's wiring — training forward, prefill, and decode all run it.

    ``reduce``/``promote`` are the same Megatron tensor-parallel seams as
    :func:`.model._block`'s (the *g*/*f* conjugate operators for
    fully-manual ``shard_map`` execution — see that docstring): ``reduce``
    closes the row-parallel partial sums after ``wo`` and ``w_down``,
    ``promote`` guards each normed input to the column-parallel matmuls.
    Both ``None`` (default) for unsharded or GSPMD-auto execution.
    """
    h = _rms_norm(x, layer["attn_norm"], config.rms_eps)
    if promote is not None:
        h = promote(h)
    q, k, v = _project_qkv(h, layer, config, positions)
    out = attend(q, k, v)
    batch, _, seq, _ = out.shape
    out = out.transpose(0, 2, 1, 3).reshape(batch, seq, config.d_model)
    proj = out @ layer["wo"]
    if reduce is not None:
        proj = reduce(proj)
    x = x + proj
    h2 = _rms_norm(x, layer["mlp_norm"], config.rms_eps)
    if promote is not None:
        h2 = promote(h2)
    up = (mlp or _swiglu)(h2, layer)
    if reduce is not None:
        up = reduce(up)
    return x + up


def _gqa_wrap(config: LlamaConfig, inner):
    """Adapt an attention kernel to this family's GQA inputs — delegates
    to :func:`.flash.gqa_adapt`, the single owner of the broadcast
    policy (gqa-native kernels take compact k/v directly; MHA-shaped
    ones get ``repeat_kv`` fused in just before the call)."""
    from .flash import gqa_adapt

    return gqa_adapt(inner)


def _gqa_dense_attention(config: LlamaConfig):
    from .flash import windowed

    return _gqa_wrap(config, windowed(_dense_attention,
                                      config.sliding_window))


@functools.lru_cache(maxsize=None)
def llama_attention_fn_for(
    config: LlamaConfig, seq_len: int, *, backend: str | None = None
):
    """GQA-aware attention selection for a static prompt length.

    Memoized per ``(config, seq_len, backend)``: callers pass the result
    as a jit-STATIC argument (``llama_generate_jit``'s
    ``prompt_attention``, ``llama_forward_jit_with``), which is keyed by
    object identity — a fresh closure per batch would retrace and
    recompile the whole program every call.  ``LlamaConfig`` is frozen,
    so the cache key is exact; the serving worker sees one compiled
    program per length bucket, as intended.

    Same policy as :func:`.flash.attention_fn_for` (Pallas flash kernel
    on TPU when the shape tiles onto the MXU blocks, dense XLA path
    elsewhere); K/V broadcast from ``n_kv_heads`` to full heads just
    before the kernel, which is MHA-shaped.  ``config.sliding_window``
    rides along into whichever implementation wins (the flash kernel's
    windowed block-skip, or the dense mask).  Plug into
    :func:`llama_forward`/:func:`llama_forward_jit_with`.
    """
    from .flash import attention_fn_for, windowed

    return _gqa_wrap(
        config,
        windowed(
            attention_fn_for(seq_len, backend=backend),
            config.sliding_window,
        ),
    )


def llama_forward(
    params: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    attention_fn=None,
    positions: jax.Array | None = None,
    remat: bool = False,
    mlp=None,
) -> jax.Array:
    """Logits ``[B, S, vocab]`` (fp32, tied-embedding readout).

    ``attention_fn(q, k, v)`` sees GQA-shaped k/v; use
    :func:`repeat_kv` when plugging in an MHA kernel.  ``positions``
    overrides the RoPE positions (decode passes the cache offset).
    ``remat=True`` checkpoints each block like :func:`.model.forward`.
    ``mlp(h, layer)`` overrides the per-block feed-forward (the MoE
    variant's routed SwiGLU experts — see :func:`.moe.llama_moe_forward`).
    """
    from .model import unembed

    return unembed(
        llama_forward_hidden(
            params, tokens, config, attention_fn, positions, remat, mlp
        ),
        readout_weights(params),
    )


def llama_forward_hidden(
    params: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    attention_fn=None,
    positions: jax.Array | None = None,
    remat: bool = False,
    mlp=None,
) -> jax.Array:
    """:func:`llama_forward` minus the unembedding: final RMS-normed
    hidden states ``[B, S, d_model]`` (see ``model.forward_hidden``)."""
    seq = tokens.shape[1]
    if seq > config.max_seq_len:
        raise ValueError(
            f"sequence length {seq} exceeds max_seq_len={config.max_seq_len}"
        )
    if positions is None:
        positions = jnp.arange(seq)
    attend = attention_fn or _gqa_dense_attention(config)
    block = _llama_block
    if remat:
        # config/attend/mlp are static; positions is a traced argument
        block = jax.checkpoint(_llama_block, static_argnums=(2, 4, 5))
    x = params["embed"][tokens]
    for layer in params["layers"]:
        x = block(x, layer, config, positions, attend, mlp)
    return _rms_norm(x, params["final_norm"], config.rms_eps)


def llama_loss_fn(
    params: Any,
    tokens: jax.Array,
    config: LlamaConfig,
    attention_fn=None,
    remat: bool = False,
) -> jax.Array:
    from .train import fused_next_token_nll

    return fused_next_token_nll(
        readout_weights(params),
        llama_forward_hidden(
            params, tokens, config, attention_fn, remat=remat
        ),
        tokens,
    )


def init_llama_train_state(
    rng: jax.Array, config: LlamaConfig, train_config
) -> dict:
    from .train import init_train_state

    return init_train_state(
        rng, config, train_config, init_fn=init_llama_params
    )


def llama_mesh_loss(config: LlamaConfig, train_config):
    """The family objective in ``make_train_step``'s loss-seam shape:
    the seam's attention_fn (per-shard flash on TPU, ring attention on a
    ``seq`` mesh) is adapted through :func:`_gqa_wrap` — gqa-native fns
    take the compact k/v directly, MHA-shaped ones get the broadcast.
    One implementation for the full train step and the LoRA step."""

    def loss(params, tokens, attention_fn=None):
        attend = (
            _gqa_wrap(config, attention_fn)
            if attention_fn is not None
            else None
        )
        return llama_loss_fn(params, tokens, config, attention_fn=attend,
                             remat=train_config.remat)

    return loss


def make_llama_train_step(mesh, config: LlamaConfig, train_config,
                          state: dict):
    """dp x tp (x sp) train step via :func:`.train.make_train_step`'s
    seams, with :func:`llama_mesh_loss` as the objective.
    ``config.sliding_window`` rides the shared attention seam (windowed
    flash/dense per shard on a ``(data, model)`` mesh; the windowed ring
    schedule on a ``seq`` mesh — long-context Mistral-style training
    under sequence parallelism)."""
    from .train import make_train_step

    return make_train_step(
        mesh, config, train_config, state,
        loss=llama_mesh_loss(config, train_config),
        window=config.sliding_window,
    )


# ---------------------------------------------------------------------------
# GQA KV-cache decoding
# ---------------------------------------------------------------------------


def init_llama_cache(config: LlamaConfig, batch: int) -> dict:
    """KV cache with only ``n_kv_heads`` heads: the GQA memory win.
    ``length`` is per-row (int32 ``[batch]``) — ragged batches decode in
    lockstep at their own positions, like :func:`.decode.init_cache`."""
    shape = (batch, config.n_kv_heads, config.max_seq_len, config.head_dim)
    return {
        "layers": [
            {"k": jnp.zeros(shape, config.dtype),
             "v": jnp.zeros(shape, config.dtype)}
            for _ in range(config.n_layers)
        ],
        "length": jnp.zeros((batch,), jnp.int32),
    }


def init_llama_rolling_cache(config: LlamaConfig, batch: int) -> dict:
    """Rolling-buffer KV cache for sliding-window models: only
    ``sliding_window`` positions per layer — O(window) HBM instead of
    O(max_seq_len) — with position ``p`` living in slot ``p % window``.

    The windowed attention mask makes this exact, not approximate: a
    query at position ``p`` may only attend ``p - window + 1 .. p``, and
    those are precisely the positions the ring of slots retains (older
    entries are the ones overwritten).  Slot ``s``'s occupant is
    recoverable from arithmetic alone — the largest ``c <= p`` with
    ``c ≡ s (mod window)`` — so validity needs no bookkeeping beyond the
    per-row ``length`` the full cache already carries.
    """
    if config.sliding_window is None:
        raise ValueError(
            "rolling cache requires a sliding_window config (a full-"
            "attention model needs every past position — use "
            "init_llama_cache)"
        )
    shape = (batch, config.n_kv_heads, config.sliding_window,
             config.head_dim)
    return {
        "layers": [
            {"k": jnp.zeros(shape, config.dtype),
             "v": jnp.zeros(shape, config.dtype)}
            for _ in range(config.n_layers)
        ],
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _rolling_cached_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    window: int,
) -> jax.Array:
    """One query per row against the ring of ``window`` slots.

    ``q``: ``[B, H, 1, D]`` at global position ``pos[b]``; slot ``s``
    holds position ``c_s = pos - ((pos - s) mod window)``; slots with
    ``c_s < 0`` (warm-up) are masked.  fp32 scores/softmax, identical
    numerics to the masked full-cache path — order of keys is
    irrelevant to attention, and RoPE was applied at each key's absolute
    position before it was stored.
    """
    head_dim = q.shape[-1]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) / (head_dim**0.5)
    slots = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
    p = pos[:, None, None, None]
    occupant = p - jnp.remainder(p - slots, window)
    scores = jnp.where(occupant >= 0, scores, jnp.float32(-jnp.inf))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v_cache)


def llama_rolling_prefill(
    params: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    prompt_attention=None,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """Prompt pass for the rolling cache: the full windowed forward runs
    as usual, then each layer's LAST ``min(window, length)`` keys/values
    are gathered into their slots (earlier positions would have been
    overwritten anyway).  Same readout contract as :func:`llama_prefill`.
    """
    window = config.sliding_window
    if window is None:
        raise ValueError("rolling prefill requires a sliding_window config")
    readout, row_lengths, captured = _prefill_forward(
        params, tokens, config, prompt_attention, lengths
    )

    # slot s <- position c_s = (len-1) - ((len-1 - s) mod window): the
    # newest prompt position congruent to s; warm-up slots (c_s < 0)
    # hold zeros and stay masked by the attention arithmetic
    slots = jnp.arange(window)[None, :]  # [1, W]
    last = (row_lengths - 1)[:, None]  # [B, 1]
    source = last - jnp.remainder(last - slots, window)  # [B, W]
    gather_idx = jnp.clip(source, 0)[:, None, :, None]  # [B, 1, W, 1]
    new_layers = []
    for layer_kv in captured:
        k = jnp.take_along_axis(
            layer_kv["k"].astype(config.dtype), gather_idx, axis=2
        )
        v = jnp.take_along_axis(
            layer_kv["v"].astype(config.dtype), gather_idx, axis=2
        )
        keep = (source >= 0)[:, None, :, None]
        new_layers.append({
            "k": jnp.where(keep, k, 0).astype(config.dtype),
            "v": jnp.where(keep, v, 0).astype(config.dtype),
        })
    return readout, {"layers": new_layers, "length": row_lengths}


def llama_rolling_decode_step(
    params: dict, cache: dict, tokens: jax.Array, config: LlamaConfig
) -> tuple[jax.Array, dict]:
    """One token per row against the rolling cache: write at
    ``pos % window``, attend the ring (same contract as
    :func:`llama_decode_step`)."""
    window = config.sliding_window
    if window is None:
        raise ValueError(
            "rolling decode requires a sliding_window config"
        )
    slot_axis = cache["layers"][0]["k"].shape[2]
    if slot_axis != window:
        # a full-size cache here would write at pos % window inside a
        # max_seq_len buffer and score mostly-zero slots — wrong logits
        # with no error; refuse the mismatched layout instead
        raise ValueError(
            f"rolling decode needs a window-sized cache ({window} slots), "
            f"got {slot_axis} — build it with init_llama_rolling_cache/"
            "llama_rolling_prefill"
        )

    def attend_cache(q, k_cache, v_cache, pos):
        return _rolling_cached_attention(q, k_cache, v_cache, pos, window)

    return _decode_step_impl(
        params, cache, tokens, config,
        _full_cache_write_and_attend(
            config, lambda pos: jnp.remainder(pos, window), attend_cache
        ),
    )


def _final_logits(
    params: dict,
    x: jax.Array,
    eps: float,
    last_pos: jax.Array | None = None,
) -> jax.Array:
    # eps is required (no default): a defaulted 1e-6 here would silently
    # diverge from LlamaConfig.rms_eps for Llama-2 (1e-5) checkpoints
    x = _rms_norm(x, params["final_norm"], eps)
    logits = jnp.einsum(
        "bsd,vd->bsv", x, readout_weights(params),
        preferred_element_type=jnp.float32,
    )
    if last_pos is None:
        return logits[:, -1]
    return logits[jnp.arange(logits.shape[0]), last_pos]


def _prefill_forward(
    params: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    prompt_attention,
    lengths: jax.Array | None,
):
    """The shared prompt pass of both cache layouts: validation, the
    window-aware kernel selection, the forward with per-layer GQA k/v
    capture, and the ragged readout.  Returns ``(readout, row_lengths,
    captured)`` — cache population (full-slice write vs ring gather) is
    the caller's job."""
    batch, prompt_len = tokens.shape
    if prompt_len > config.max_seq_len:
        raise ValueError(
            f"prompt length {prompt_len} exceeds max_seq_len="
            f"{config.max_seq_len}"
        )
    inner = (
        _gqa_wrap(config, prompt_attention)
        if prompt_attention is not None
        else _gqa_dense_attention(config)  # window-aware default
    )
    captured: list[dict] = []

    def attend(q, k, v):
        # k/v arrive GQA-shaped [B, H_kv, S, D]
        captured.append({"k": k, "v": v})
        return inner(q, k, v)

    logits = llama_forward(params, tokens, config, attention_fn=attend)
    if lengths is None:
        row_lengths = jnp.full((batch,), prompt_len, jnp.int32)
        readout = logits[:, -1]
    else:
        row_lengths = lengths.astype(jnp.int32)
        readout = logits[jnp.arange(batch), row_lengths - 1]
    return readout, row_lengths, captured


def llama_prefill(
    params: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    prompt_attention=None,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """Prompt pass populating a fresh GQA cache (same contract as
    :func:`.decode.prefill`, including ragged right-padded prompts via
    ``lengths``).  ``prompt_attention`` is a causal kernel for the
    prompt pass — pass :func:`llama_attention_fn_for`'s pick (it carries
    the config's sliding window into flash/dense; a plain
    ``.flash.attention_fn_for`` pick would prefill a windowed model
    full-causal).  Default: window-aware dense.
    """
    batch, prompt_len = tokens.shape
    readout, row_lengths, captured = _prefill_forward(
        params, tokens, config, prompt_attention, lengths
    )
    cache = init_llama_cache(config, batch)
    new_layers = [
        {
            "k": layer["k"].at[:, :, :prompt_len].set(
                kv["k"].astype(config.dtype)
            ),
            "v": layer["v"].at[:, :, :prompt_len].set(
                kv["v"].astype(config.dtype)
            ),
        }
        for layer, kv in zip(cache["layers"], captured)
    ]
    return readout, {"layers": new_layers, "length": row_lengths}


def _decode_step_impl(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    write_and_attend,
) -> tuple[jax.Array, dict]:
    """The one decode-step skeleton every cache layout shares (full,
    rolling-ring, int8): embed at the absolute position, per layer call
    ``write_and_attend(q, k, v, layer_cache, rows, pos) -> (new_entry,
    out)`` — which writes the new position into its layout's slot(s) and
    attends against it — then final logits.  Layout-specific pieces (the
    slot arithmetic, the cache-entry dtype, the masked-attention math)
    live entirely in the callback."""
    pos = cache["length"]  # [B]
    batch = tokens.shape[0]
    rows = jnp.arange(batch)
    # RoPE rotates by each row's absolute position: [B, 1, 1] broadcasts
    # against the [B, H, 1, D/2] rotation pairs
    positions = pos[:, None, None]
    x = params["embed"][tokens][:, None, :]
    new_layers = []
    for layer, layer_cache in zip(params["layers"], cache["layers"]):

        def attend(q, k, v, _lc=layer_cache):
            entry, out = write_and_attend(q, k, v, _lc, rows, pos)
            new_layers.append(entry)
            return out

        x = _llama_block(x, layer, config, positions, attend)
    return (
        _final_logits(params, x, config.rms_eps),
        {"layers": new_layers, "length": pos + 1},
    )


def _full_cache_write_and_attend(
    config: LlamaConfig, write_slot_of, cached_attention
):
    """The full-precision k/v write for :func:`_decode_step_impl`:
    write at ``write_slot_of(pos)``, GQA-broadcast, attend via
    ``cached_attention(q, k_cache, v_cache, pos)``."""
    groups = config.n_heads // config.n_kv_heads

    def write_and_attend(q, k, v, layer_cache, rows, pos):
        slot = write_slot_of(pos)
        k_cache = layer_cache["k"].at[rows, :, slot].set(
            k[:, :, 0].astype(config.dtype)
        )
        v_cache = layer_cache["v"].at[rows, :, slot].set(
            v[:, :, 0].astype(config.dtype)
        )
        entry = {"k": k_cache, "v": v_cache}
        return entry, cached_attention(
            q, repeat_kv(k_cache, groups), repeat_kv(v_cache, groups), pos
        )

    return write_and_attend


def llama_decode_step(
    params: dict, cache: dict, tokens: jax.Array, config: LlamaConfig
) -> tuple[jax.Array, dict]:
    """One token per row (int32 ``[batch]``) against the GQA cache; same
    contract as :func:`.decode.decode_step` (reuses its masked
    cached-attention math via :func:`.decode._cached_attention`), with
    per-row positions."""
    from .decode import _cached_attention

    def attend_cache(q, k_cache, v_cache, pos):
        return _cached_attention(q, k_cache, v_cache, pos,
                                 window=config.sliding_window)

    return _decode_step_impl(
        params, cache, tokens, config,
        _full_cache_write_and_attend(config, lambda pos: pos, attend_cache),
    )


def llama_quantized_prefill(
    params: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    prompt_attention=None,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """:func:`llama_prefill` with the populated GQA cache quantized to
    int8 (codes + per-position scales — see ``decode.quantize_cache``;
    the compact kv-head cache is the part decode streams, so the GQA
    memory win and the int8 bandwidth win compose)."""
    from .decode import quantize_cache

    logits, cache = llama_prefill(params, tokens, config, prompt_attention,
                                  lengths=lengths)
    return logits, quantize_cache(cache)


def llama_quantized_decode_step(
    params: dict, cache: dict, tokens: jax.Array, config: LlamaConfig
) -> tuple[jax.Array, dict]:
    """:func:`llama_decode_step` against the int8 GQA cache: quantize
    the new position's compact k/v vectors, write codes+scales, broadcast
    to full heads, attend via the factorized dequantize
    (``decode._quantized_chunk_cached_attention`` — the per-position
    scales ride the broadcast exactly like the values do).  Same
    :func:`_decode_step_impl` skeleton as the other cache layouts."""
    from .decode import _quantized_write_and_attend

    groups = config.n_heads // config.n_kv_heads
    return _decode_step_impl(
        params, cache, tokens, config,
        _quantized_write_and_attend(
            window=config.sliding_window,
            broadcast=lambda t: expand_gqa(t, groups),
        ),
    )


def llama_chunk_decode(
    params: dict, cache: dict, tokens: jax.Array, config: LlamaConfig
) -> tuple[jax.Array, dict]:
    """Decode a ``T``-token chunk per row in one forward (the llama
    counterpart of :func:`.decode.chunk_decode` — GQA cache writes, RoPE
    at per-row chunk positions, same start-offset causal mask).  Entry
    ``t`` of the fp32 ``[B, T, vocab]`` logits is the next-token
    distribution after consuming input ``t``; the cache advances by
    ``T``.  The verify step of llama-family speculative decoding."""
    from .decode import _chunk_cached_attention, _chunk_write

    groups = config.n_heads // config.n_kv_heads

    def write_and_attend(q, k, v, layer_cache, rows, cols, start):
        entry = _chunk_write(layer_cache, k, v, rows, cols, config.dtype)
        return entry, _chunk_cached_attention(
            q, repeat_kv(entry["k"], groups), repeat_kv(entry["v"], groups),
            start, window=config.sliding_window,
        )

    return _llama_chunk_decode_impl(params, cache, tokens, config,
                                    write_and_attend)


def _llama_chunk_decode_impl(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    write_and_attend,
) -> tuple[jax.Array, dict]:
    """The llama-family chunk-decode skeleton both cache layouts share:
    embed, RoPE at per-row chunk positions, per layer
    ``write_and_attend(q, k, v, layer_cache, rows, cols, start) ->
    (new_entry, out)``, full-chunk logits (the chunk counterpart of
    :func:`_decode_step_impl`)."""
    start = cache["length"]  # [B]
    batch, chunk = tokens.shape
    rows = jnp.arange(batch)[:, None]
    cols = start[:, None] + jnp.arange(chunk)[None, :]  # [B, T]
    # [B, 1, T] RoPE positions broadcast against [B, H, T, D/2] angles
    positions = start[:, None, None] + jnp.arange(chunk)[None, None, :]
    x = params["embed"][tokens]
    new_layers = []
    for layer, layer_cache in zip(params["layers"], cache["layers"]):

        def attend(q, k, v, _lc=layer_cache):
            entry, out = write_and_attend(q, k, v, _lc, rows, cols, start)
            new_layers.append(entry)
            return out

        x = _llama_block(x, layer, config, positions, attend)
    x = _rms_norm(x, params["final_norm"], config.rms_eps)
    from .model import unembed

    logits = unembed(x, readout_weights(params))
    return logits, {"layers": new_layers, "length": start + chunk}


def llama_quantized_chunk_decode(
    params: dict, cache: dict, tokens: jax.Array, config: LlamaConfig
) -> tuple[jax.Array, dict]:
    """:func:`llama_chunk_decode` against the int8 GQA cache (the llama
    counterpart of ``decode.quantized_chunk_decode`` — compact codes and
    scales broadcast to full heads at the attention, window included)."""
    from .decode import (
        _quantized_chunk_cached_attention,
        _quantized_chunk_write,
    )

    groups = config.n_heads // config.n_kv_heads

    def write_and_attend(q, k, v, layer_cache, rows, cols, start):
        entry = _quantized_chunk_write(layer_cache, k, v, rows, cols)
        return entry, _quantized_chunk_cached_attention(
            q,
            expand_gqa(entry["k_codes"], groups),
            expand_gqa(entry["k_scale"], groups),
            expand_gqa(entry["v_codes"], groups),
            expand_gqa(entry["v_scale"], groups),
            start, window=config.sliding_window,
        )

    return _llama_chunk_decode_impl(params, cache, tokens, config,
                                    write_and_attend)


def llama_prefill_prefix(
    params: dict, prefix: jax.Array, config: LlamaConfig,
    prompt_attention=None,
) -> dict:
    """KV cache of a SHARED prompt prefix, computed once — the llama
    twin of :func:`.decode.prefill_prefix` (compact GQA cache; RoPE is
    position-absolute so the cached keys are already rotated for their
    slots)."""
    from .decode import _prefill_prefix_impl

    return _prefill_prefix_impl(llama_prefill, params, prefix, config,
                                prompt_attention)


def llama_prefill_with_prefix(
    params: dict,
    prefix_cache: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """Per-request suffixes continue from a shared prefix's cache — the
    llama twin of :func:`.decode.prefill_with_prefix` (one
    :func:`llama_chunk_decode` forward; RoPE offsets come from the
    cache's per-row lengths, window semantics included; same
    reduction-order rounding caveat)."""
    from .decode import _prefill_with_prefix_impl

    return _prefill_with_prefix_impl(
        llama_chunk_decode, params, prefix_cache, tokens, config, lengths
    )


def llama_quantized_prefill_prefix(
    params: dict, prefix: jax.Array, config: LlamaConfig,
    prompt_attention=None,
) -> dict:
    """:func:`llama_prefill_prefix` in the int8 GQA cache layout."""
    from .decode import _prefill_prefix_impl

    return _prefill_prefix_impl(llama_quantized_prefill, params, prefix,
                                config, prompt_attention)


def llama_quantized_prefill_with_prefix(
    params: dict,
    prefix_cache: dict,
    tokens: jax.Array,
    config: LlamaConfig,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """:func:`llama_prefill_with_prefix` over the int8 GQA cache
    layout."""
    from .decode import _prefill_with_prefix_impl

    return _prefill_with_prefix_impl(
        llama_quantized_chunk_decode, params, prefix_cache, tokens,
        config, lengths,
    )


def llama_generate(
    params: dict,
    prompt: jax.Array,
    num_tokens: int,
    config: LlamaConfig,
    *,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    prompt_attention=None,
    lengths: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    rolling: bool = False,
    eos_id: int | None = None,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
) -> jax.Array:
    """Greedy/temperature/top-k/top-p generation, one compiled program
    (same contract and scan structure as :func:`.decode.generate`,
    including ragged prompts via ``lengths``; sampling policy is
    ``decode._pick``).  ``prompt_attention`` selects the prefill
    kernel (see :func:`llama_prefill`).  ``rolling=True`` decodes
    through the O(window) rolling-buffer cache (sliding-window configs
    only; identical outputs — the window mask already hides everything
    the ring evicts).  ``quantized_cache=True`` decodes through the int8
    GQA cache (half the cache bytes per step; outputs match to int8
    rounding).  ``prefix_cache`` (from :func:`llama_prefill_prefix`)
    prepends a shared, already-prefilled prefix — ``prompt`` rows are
    the per-request suffixes."""
    from .decode import _pick

    from .decode import _check_prefix_budget

    batch, prompt_len = prompt.shape
    if num_tokens < 1:
        raise ValueError(f"num_tokens must be >= 1, got {num_tokens}")
    _check_prefix_budget(prefix_cache, prompt_len, num_tokens, config)
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling requires an rng key")
    if rolling and quantized_cache:
        raise ValueError(
            "rolling and quantized_cache do not compose (the ring's slot "
            "arithmetic is a full-precision layout); pick one"
        )
    if prefix_cache is not None:
        if rolling:
            raise ValueError(
                "prefix_cache rides the padded cache layout; it does not "
                "combine with the rolling-buffer cache"
            )
        if prompt_attention is not None:
            # same contract as decode.generate: the suffix prefill runs
            # the chunk decoder, which has no attention override
            raise ValueError(
                "prompt_attention does not apply with prefix_cache (the "
                "suffix prefill runs the chunk decoder); drop one"
            )
        from .decode import _check_prefix_layout

        _check_prefix_layout(prefix_cache, quantized_cache)
    keys = (
        jax.random.split(rng, num_tokens)
        if rng is not None
        else jnp.zeros((num_tokens, 2), jnp.uint32)
    )
    if quantized_cache:
        prefill_fn = llama_quantized_prefill
        step_fn = llama_quantized_decode_step
    else:
        prefill_fn = llama_rolling_prefill if rolling else llama_prefill
        step_fn = llama_rolling_decode_step if rolling else llama_decode_step
    if prefix_cache is not None:
        pf = (llama_quantized_prefill_with_prefix if quantized_cache
              else llama_prefill_with_prefix)
        logits, cache = pf(
            params, prefix_cache, prompt, config, lengths=lengths
        )
    else:
        logits, cache = prefill_fn(params, prompt, config, prompt_attention,
                                   lengths=lengths)
    first = _pick(logits, keys[0], temperature, top_k, top_p)
    done0 = (
        first == eos_id if eos_id is not None
        else jnp.zeros(first.shape, bool)
    )

    def body(carry, key):
        cache, token, done = carry
        logits, cache = step_fn(params, cache, token, config)
        nxt = _pick(logits, key, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done), token

    (_, last, _), produced = jax.lax.scan(
        body, (cache, first, done0), keys[1:]
    )
    produced = jnp.moveaxis(produced, 0, 1)
    return jnp.concatenate([produced, last[:, None]], axis=1)


# ---------------------------------------------------------------------------
# Mesh-sharded serving (the llama counterpart of decode.make_serving_fns)
# ---------------------------------------------------------------------------


def make_llama_serving_fns(
    mesh,
    config: LlamaConfig,
    params: dict,
    *,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
):
    """Compile (prefill, decode_step, generate) over a ``(data, model)``
    mesh — same contract as :func:`.decode.make_serving_fns` (shared jit
    wiring via :func:`.decode.compile_serving_fns`), with the compact GQA
    cache sharded by *kv* head over ``model`` (requires
    ``n_kv_heads % model_parallel == 0``).

    ``quantized_cache=True`` serves through the int8 GQA cache (codes and
    scales shard by kv head over ``model`` exactly like the bf16 cache);
    ``prefix_cache`` (from :func:`llama_prefill_prefix` /
    :func:`llama_quantized_prefill_prefix`) pins a shared prompt prefix
    into the sharded generate.  Both options compose."""
    from .decode import (
        _check_prefix_layout,
        compile_serving_fns,
        init_quantized_cache,
    )

    batch = mesh.shape["data"]
    if quantized_cache:
        template = jax.eval_shape(
            lambda: init_quantized_cache(config, batch,
                                         kv_heads=config.n_kv_heads)
        )
        prefill_fn = partial(llama_quantized_prefill, config=config)
        decode_fn = partial(llama_quantized_decode_step, config=config)
    else:
        template = jax.eval_shape(lambda: init_llama_cache(config, batch))
        prefill_fn = partial(llama_prefill, config=config)
        decode_fn = partial(llama_decode_step, config=config)
    if prefix_cache is not None:
        _check_prefix_layout(prefix_cache, quantized_cache)
    return compile_serving_fns(
        mesh,
        params,
        template,
        prefill_fn,
        decode_fn,
        lambda params, prompt, num_tokens, temperature, rng, lengths,
               top_k, top_p, eos_id, prefix:
            llama_generate(
                params, prompt, num_tokens, config,
                temperature=temperature, rng=rng, lengths=lengths,
                top_k=top_k, top_p=top_p, eos_id=eos_id,
                quantized_cache=quantized_cache, prefix_cache=prefix,
            ),
        prefix_cache=prefix_cache,
    )


@partial(jax.jit, static_argnums=2)
def llama_forward_jit(
    params: dict, tokens: jax.Array, config: LlamaConfig
) -> jax.Array:
    """Single-chip jitted forward (the serving worker's classify path)."""
    return llama_forward(params, tokens, config)


@partial(jax.jit, static_argnums=(2, 3))
def llama_forward_jit_with(
    params: dict, tokens: jax.Array, config: LlamaConfig, attention_fn
) -> jax.Array:
    """Jitted forward with a chosen attention implementation (e.g. the
    flash-backed :func:`llama_attention_fn_for` result); static so each
    implementation gets its own compiled program."""
    return llama_forward(params, tokens, config, attention_fn)


@partial(
    jax.jit,
    static_argnames=(
        "num_tokens", "config", "temperature", "prompt_attention", "top_k",
        "top_p", "rolling", "eos_id", "quantized_cache",
    ),
)
def llama_generate_jit(
    params: dict,
    prompt: jax.Array,
    num_tokens: int,
    config: LlamaConfig,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    prompt_attention=None,
    lengths: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    rolling: bool = False,
    eos_id: int | None = None,
    quantized_cache: bool = False,
    prefix_cache: dict | None = None,
) -> jax.Array:
    return llama_generate(
        params, prompt, num_tokens, config, temperature=temperature, rng=rng,
        prompt_attention=prompt_attention, lengths=lengths, top_k=top_k,
        top_p=top_p, rolling=rolling, eos_id=eos_id,
        quantized_cache=quantized_cache, prefix_cache=prefix_cache,
    )
