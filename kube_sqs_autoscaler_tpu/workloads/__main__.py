"""Worker binary: ``python -m kube_sqs_autoscaler_tpu.workloads``.

Runs one queue-draining inference worker — the process a scaled Deployment
replica executes.  ``--demo N`` self-feeds a local in-memory queue with N
random messages instead of connecting to AWS (no credentials needed), which
is also the quickest way to see the full workload path run.

Two flags close the train→serve loop:

- ``--checkpoint-dir DIR`` serves the weights a trainer
  (``python -m ...workloads.trainer --checkpoint-dir DIR``) saved there,
  reading the ``model_config.json`` manifest for the architecture;
- ``--model-parallel TP`` shards serving over a ``(data, model)`` mesh
  (classify via ``train.make_forward_step``, generate via
  ``decode.make_serving_fns`` / ``llama.make_llama_serving_fns``).
"""

from __future__ import annotations

import argparse
import json
import logging
import time

from ..utils.logging import configure_logging
from ..utils.platforms import honor_env_platforms as _honor_env_platforms


def main(argv=None) -> None:
    configure_logging()
    _honor_env_platforms()
    log = logging.getLogger("worker")
    parser = argparse.ArgumentParser(prog="kube-sqs-autoscaler-worker")
    parser.add_argument("--sqs-queue-url", default="", help="The sqs queue url")
    parser.add_argument("--aws-region", default="", help="Your AWS region")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument(
        "--generate-tokens", type=int, default=0, metavar="N",
        help="decode N continuation tokens per message (KV-cache generate "
             "mode) instead of one classify forward",
    )
    parser.add_argument(
        "--temperature", type=float, default=0.0,
        help="generate-mode sampling temperature (0 = greedy; single-chip "
             "default path)",
    )
    parser.add_argument(
        "--top-k", type=int, default=0,
        help="sample only the k highest-probability tokens (0 = off; "
             "needs --temperature > 0)",
    )
    parser.add_argument(
        "--top-p", type=float, default=1.0,
        help="nucleus sampling: smallest token set with cumulative "
             "probability >= p (1.0 = off; needs --temperature > 0)",
    )
    parser.add_argument(
        "--family", choices=("gpt", "llama"), default="gpt",
        help="model family served: gpt (learned pos/MHA) or llama "
             "(RoPE/GQA — n_kv_heads-sized KV cache)",
    )
    parser.add_argument(
        "--checkpoint-dir", default="", metavar="DIR",
        help="serve the weights a trainer checkpointed here (reads the "
             "model_config.json manifest for family + dimensions; "
             "default: random init — smoke/bench mode)",
    )
    parser.add_argument(
        "--hf-checkpoint", default="", metavar="DIR",
        help="serve a Hugging Face Llama checkpoint directory "
             "(transformers format; converted via workloads.hf_convert — "
             "implies --family llama)",
    )
    parser.add_argument(
        "--model-parallel", type=int, default=0, metavar="TP",
        help="shard serving over a (data, model) mesh with this "
             "tensor-parallel degree (0 = single chip)",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve /metrics with serve-cycle latency summaries "
             "(p50/p99/max from the worker's SpanTimer; 0 = disabled)",
    )
    parser.add_argument(
        "--continuous", action="store_true",
        help="continuous batching: rolling decode slots that refill as "
             "each message finishes instead of batch-at-a-time (requires "
             "--generate-tokens >= 1; both families, sampling/eos/"
             "tokenizer/replies supported; composes with "
             "--model-parallel — slots shard batch-over-data, "
             "heads-over-model — with --quantize-kv, --prefix-ids, and "
             "--speculative-draft-layers)",
    )
    parser.add_argument(
        "--decode-block", type=int, default=1, metavar="B",
        help="continuous serving: advance every live slot up to B tokens "
             "per device call (one jitted lax.scan with on-device "
             "eos/budget masks, double-buffered so host bookkeeping "
             "overlaps device compute) instead of one token per "
             "host round-trip; greedy results are identical to "
             "--decode-block 1 (sampled runs draw the same policy but "
             "consume RNG keys in a different order; requires "
             "--continuous; plain decode path only — not with --beams "
             "or --speculative-draft-layers)",
    )
    parser.add_argument(
        "--shards", type=int, default=1, metavar="S",
        help="sharded serving plane: stack S gang-stepped engine shards "
             "of --batch-size slots each behind ONE admission plane — "
             "all shards advance in a single jitted decode call per "
             "cycle, refills route freest-shard-first, and greedy "
             "outputs stay byte-identical to S independent workers "
             "(requires --continuous; not with --beams; composes with "
             "--speculative-draft-layers — draft-and-verify rounds "
             "gang-step over the whole plane, single-chip; under "
             "--model-parallel the mesh's data axis must divide S, so "
             "each device holds whole shards)",
    )
    parser.add_argument(
        "--topology", default="", metavar="SHAPE",
        choices=("", "ring", "mesh2d", "torus", "two-tier"),
        help="topology-aware collective routing: model the fleet as a "
             "link graph of this shape (ring, mesh2d, torus, or "
             "two-tier ICI-islands-over-DCN), derived from the live "
             "--shards/--model-parallel geometry, and attach a "
             "route-planning CollectiveScheduler — transfers get "
             "concrete multi-hop routes (large KV moves chunked "
             "across link-disjoint paths), dispatch order respects a "
             "per-link virtual-time ledger, and /metrics gains "
             "link_bytes_total/link_utilization plus a "
             "/debug/topology endpoint (default: off — the WHEN-only "
             "scheduler, byte-identical; requires --continuous)",
    )
    parser.add_argument(
        "--tenants", default="", metavar="NAME,NAME,...",
        help="multi-tenant fair admission: per-tenant sub-queues feed "
             "the continuous batcher through deficit-round-robin "
             "admission (one flooding tenant can no longer starve the "
             "others' TTFT), with per-tenant Prometheus gauges; message "
             "bodies opt in via {'tenant': ..., 'ids': [...]} and "
             "unlabeled traffic lands on the FIRST listed tenant "
             "(single default tenant = the reference FIFO path, "
             "byte-identical results; requires --continuous; not with "
             "--beams; composes with --speculative-draft-layers via "
             "the decode plane, single-chip)",
    )
    parser.add_argument(
        "--tenant-weights", default="", metavar="W,W,...",
        help="DRR weights aligned with --tenants (floats >= 0.01, one "
             "per tenant; default: all 1.0 — equal shares)",
    )
    parser.add_argument(
        "--tenant-slos", default="", metavar="S,S,...",
        help="per-tenant TTFT SLOs in seconds aligned with --tenants "
             "(floats >= 0, one per tenant; 0 = none).  Scored per "
             "tenant, biases the DRR pick when --urgency-window is set "
             "(EDF-blended admission), weighs the tenant's staged "
             "backlog in the fleet autoscaler's depth signal, and "
             "orders the overload ladder's tier-3 shed",
    )
    parser.add_argument(
        "--urgency-window", type=float, default=0.0, metavar="SECONDS",
        help="EDF-blended admission: a staged request whose "
             "arrival-based TTFT deadline (SentTimestamp + its "
             "tenant's --tenant-slos entry) is within this window of "
             "now jumps the DRR quantum, charged against a bounded "
             "per-tenant urgency budget so deadline jumps can never "
             "starve a compliant tenant (0 = off — pure DRR, "
             "byte-identical; requires --tenants)",
    )
    parser.add_argument(
        "--shed-tiers", type=int, default=0, metavar="N",
        help="tiered load shedding under measured overload pressure "
             "(staged backlog x slot occupancy, hysteretic "
             "transitions): 1 = degrade over-share tenants to half "
             "--generate-tokens, 2 = + evict cold prefix-pool "
             "entries, 3 = + shed staged requests from the "
             "most-over-share tenants with explicit error replies "
             "(exactly-once, never a silent drop); exported as "
             "overload_tier / requests_shed_total{reason=...} "
             "(0 = off; requires --tenants)",
    )
    parser.add_argument(
        "--admission-shards", type=int, default=1, metavar="N",
        help="sharded admission plane: split fair-admission staging "
             "across N crash-tolerant admission shards — tenants map "
             "to shards by consistent hash (sticky: a tenant's prefix "
             "home and DRR state live on ONE shard), each shard runs "
             "its own DRR/EDF + overload ladder over its slice, "
             "global fairness reconciles through rate-bounded "
             "cross-shard credit borrowing, and flood classifications "
             "gossip between shards (journaled as kind='admission' "
             "lines when --journal-path is set); a killed shard hands "
             "its staged requests back to the queue and rehydrates "
             "its deficit/flood state next cycle (1 = the single "
             "staging plane, byte-identical; requires --tenants)",
    )
    parser.add_argument(
        "--decode-slo-budget", type=float, default=0.0,
        metavar="SECONDS",
        help="decode-phase deadline enforcement: once a request has "
             "its first token it must sustain this many seconds per "
             "remaining generated token or be shed MID-decode with an "
             "explicit error reply — deadlines extended past TTFT "
             "into decode; exported as "
             "requests_shed_total{reason='decode_deadline'} "
             "(0 = off; requires --tenants)",
    )
    parser.add_argument(
        "--prefix-pool", type=int, default=0, metavar="N",
        help="per-tenant prefix-cache pool: keep N resident prefix "
             "entries per shard with LRU eviction — a tenant's shared "
             "prompt prefix ({'prefix': [...]} in the body, exactly "
             "--seq-len tokens) is prefilled once at install and every "
             "reuse gathers the cached KV inside the one admission "
             "insert; on the sharded plane requests route sticky "
             "(affinity-first-then-freest) so tenants keep their hits "
             "(0 = off; requires --tenants; not with --prefix-ids; "
             "composes with --model-parallel when the KV head count "
             "divides the mesh's model axis)",
    )
    parser.add_argument(
        "--request-ttl", type=float, default=0.0, metavar="SECONDS",
        help="continuous serving: shed requests already older than this "
             "on arrival (queue SentTimestamp age) with an explicit "
             "{'error': 'expired'} reply instead of occupying a decode "
             "slot — answered exactly once, never silently dropped; "
             "exported as requests_shed_total (0 = off; requires "
             "--continuous)",
    )
    parser.add_argument(
        "--speculative-draft-layers", type=int, default=0, metavar="N",
        help="speculative decoding with an early-exit self-draft: the "
             "model's own first N layers propose tokens and the full "
             "model verifies them in one chunk forward (greedy output "
             "identical to plain greedy decode; --temperature > 0 runs "
             "full speculative SAMPLING — every emitted token an exact "
             "warped-target sample; requires --generate-tokens >= 1; "
             "composes with --continuous (draft-and-verify rounds inside "
             "the rolling slots, per-slot accept counts), with "
             "--model-parallel, --quantize-kv, and --prefix-ids, in any "
             "combination; not with --beams)",
    )
    parser.add_argument(
        "--speculative-draft-tokens", type=int, default=4, metavar="K",
        help="proposals per speculative round (each round emits 1..K+1 "
             "tokens for one full-model pass)",
    )
    parser.add_argument(
        "--beams", type=int, default=1, metavar="W",
        help="beam-search generation with W beams (deterministic — does "
             "not combine with --temperature or "
             "--speculative-draft-layers; 1 = greedy/sampled decode; "
             "composes with --continuous — each rolling slot owns W "
             "beam rows and finishes independently — with "
             "--model-parallel, --quantize-kv, and --prefix-ids)",
    )
    parser.add_argument(
        "--length-penalty", type=float, default=0.0, metavar="ALPHA",
        help="GNMT length normalization for --beams > 1: finished beams "
             "rank by score / ((5 + len) / 6) ** ALPHA, favoring longer "
             "continuations as ALPHA grows (0 = raw log-prob ranking; "
             "applies to the standalone, mesh, and --continuous beam "
             "paths alike)",
    )
    parser.add_argument(
        "--quantize", choices=("none", "int8"), default="none",
        help="int8: post-training per-channel weight quantization of the "
             "served matmul weights (half the HBM bytes per decode step; "
             "composes with --model-parallel — codes shard like the bf16 "
             "weights would)",
    )
    parser.add_argument(
        "--quantize-kv", action="store_true",
        help="int8 KV cache: decode streams int8 codes + per-position "
             "scales instead of bf16 k/v (half the cache bytes per "
             "generated token; requires --generate-tokens >= 1; composes "
             "with --continuous — rolling slots store int8 — with "
             "--model-parallel — codes/scales shard by head like the "
             "bf16 cache — with --prefix-ids, with --beams, and with "
             "--speculative-draft-layers)",
    )
    parser.add_argument(
        "--result-queue-url", default="",
        help="publish one JSON reply per message to this queue "
             "(classify: {'next_token': N}; generate: {'tokens': [...]}"
             " plus decoded 'text' when --tokenizer is set)",
    )
    parser.add_argument(
        "--eos-id", type=int, default=-1, metavar="ID",
        help="stop generating a row once it emits this token id (pads "
             "with it afterwards; -1 = none / auto from --tokenizer)",
    )
    parser.add_argument(
        "--tokenizer", default="", metavar="DIR",
        help="text-in/text-out: load a transformers tokenizer and encode "
             "plain-text or {'text': ...} message bodies (and decode "
             "generate-mode replies)",
    )
    parser.add_argument(
        "--prefix-ids", default="", metavar="ID,ID,...",
        help="shared prompt prefix (comma-separated token ids), prefilled "
             "ONCE at startup and reused by every request: message bodies "
             "become per-request suffixes continuing from the cached "
             "prefix (identical outputs to prepending the prefix to every "
             "prompt, minus its repeated prefill cost; "
             "--generate-tokens >= 1; composes with --continuous — slots "
             "start past the shared prefix — with --model-parallel — the "
             "prefix shards by head over the mesh — with --quantize-kv, "
             "--beams, and --speculative-draft-layers, in any "
             "combination)",
    )
    parser.add_argument(
        "--fleet-max-replicas", type=int, default=0, metavar="N",
        help="autoscale a POOL of continuous workers between "
             "--fleet-min-replicas and N with the real control loop: "
             "replicas share the already-built params and compiled "
             "programs (O(1) spin-up), drain gracefully on scale-down, "
             "and survive worker death via supervised re-dispatch "
             "(0 = single worker; requires --continuous and --demo; "
             "plain decode path — not with --beams / "
             "--speculative-draft-layers)",
    )
    parser.add_argument(
        "--fleet-min-replicas", type=int, default=1, metavar="N",
        help="lower replica bound for --fleet-max-replicas",
    )
    parser.add_argument(
        "--scheduler", action="store_true",
        help="run the fleet demo's control loop + serving cycles on the "
             "ONE event-driven scheduler (sched/: a priority-ordered "
             "event queue over one clock) instead of the hand-rolled "
             "interleave — byte-identical behavior with no knobs armed, "
             "and the seam --knobs actuates through (requires "
             "--fleet-max-replicas)",
    )
    parser.add_argument(
        "--knobs", default="", metavar="KNOB,KNOB,...",
        help="arm live engine knobs for actuation between cycles at "
             "safe points: decode-block (re-dispatch boundary; needs "
             "--decode-block >= 2 or --shards >= 2), slot-limit "
             "(per-shard admission cap), shards (drain/retire mask "
             "flips; needs --shards >= 2), speculative (round-overlap "
             "toggle; needs --speculative-draft-layers, not --beams), "
             "prefix-pool (residency ceiling; needs --prefix-pool).  "
             "Every change is journaled, snapshotted, and exported as "
             "engine_knob{knob=...} gauges (requires --continuous and "
             "--scheduler)",
    )
    parser.add_argument(
        "--journal-path", default="", metavar="PATH",
        help="append the fleet control loop's tick records to this "
             "JSONL flight journal (the controller CLI's recorder, "
             "pointed at the serving fleet; the header meta stamps the "
             "deployment knobs incl. the tenancy config so a reader "
             "knows which admission policy ran; requires "
             "--fleet-max-replicas; empty = disabled)",
    )
    parser.add_argument(
        "--demo", type=int, default=0, metavar="N",
        help="process N random messages from a local in-memory queue and exit",
    )
    args = parser.parse_args(argv)
    if args.beams < 1:
        raise SystemExit(f"--beams {args.beams} must be >= 1")
    if args.beams > 1:
        # args-only checks fail BEFORE the mesh is built or a checkpoint
        # restored (same convention as the --quantize check above)
        for flag, bad in (
            ("--temperature > 0 (beam search is deterministic)",
             args.temperature > 0.0),
            ("--speculative-draft-layers",
             bool(args.speculative_draft_layers)),
            ("--generate-tokens >= 1 required", args.generate_tokens < 1),
        ):
            if bad:
                raise SystemExit(f"--beams does not support {flag}")
    if args.length_penalty < 0.0:
        raise SystemExit(
            f"--length-penalty {args.length_penalty} must be >= 0"
        )
    if args.length_penalty > 0.0 and args.beams < 2:
        # fail loudly instead of silently ignoring a dead knob (this was
        # exactly the bug: the config existed but nothing consumed it)
        raise SystemExit("--length-penalty requires --beams > 1")
    if args.quantize_kv and args.generate_tokens < 1:
        raise SystemExit("--quantize-kv requires --generate-tokens >= 1")
    if args.decode_block < 1:
        raise SystemExit(f"--decode-block {args.decode_block} must be >= 1")
    if args.decode_block > 1:
        # args-only checks fail BEFORE the mesh is built or a checkpoint
        # restored (same convention as the --beams checks above)
        if not args.continuous:
            raise SystemExit("--decode-block requires --continuous")
        if args.beams > 1 or (
            args.speculative_draft_layers
            and not (args.shards > 1 or args.tenants)
        ):
            # spec + shards/tenants rides the gang plane, whose block
            # engine carries plain rows; fused spec stays excluded
            raise SystemExit(
                "--decode-block applies to the plain continuous decode "
                "path (not --beams; --speculative-draft-layers only "
                "with --shards / --tenants, where the decode plane's "
                "gang engine carries it)"
            )
    if args.request_ttl < 0:
        raise SystemExit(
            f"--request-ttl {args.request_ttl} must be >= 0 (0 = off)"
        )
    if args.request_ttl > 0 and not args.continuous:
        # args-only check, same convention as --decode-block above
        raise SystemExit("--request-ttl requires --continuous")
    if args.shards < 1:
        raise SystemExit(f"--shards {args.shards} must be >= 1")
    if args.topology and not args.continuous:
        # args-only check, same convention as --decode-block above
        raise SystemExit("--topology requires --continuous")
    # --speculative-draft-layers with --shards or --tenants routes to
    # the decode-plane engine (planes/engine.py): draft-and-verify
    # rounds gang-step over the whole [S, B] plane, so these
    # combinations are legal now.  --beams stays a usage error (beam
    # search is deterministic; there is no draft round), and the plane
    # is single-chip, so --model-parallel is rejected args-only here
    # rather than mid-build.
    spec_on_plane = bool(args.speculative_draft_layers) and (
        args.shards > 1 or bool(args.tenants)
    )
    if spec_on_plane and args.model_parallel:
        raise SystemExit(
            "--speculative-draft-layers with --shards / --tenants runs "
            "on the single-chip decode plane (not with --model-parallel)"
        )
    if args.shards > 1:
        # args-only checks fail BEFORE the mesh is built or a checkpoint
        # restored (same convention as the --decode-block checks above)
        if not args.continuous:
            raise SystemExit("--shards requires --continuous")
        if args.beams > 1:
            raise SystemExit(
                "--shards applies to the plain continuous decode path "
                "(not --beams)"
            )
    tenancy = None
    if args.tenants:
        # args-only checks fail BEFORE the mesh is built or a checkpoint
        # restored (same convention as the --decode-block checks above)
        if not args.continuous:
            raise SystemExit("--tenants requires --continuous")
        if args.beams > 1:
            raise SystemExit(
                "--tenants applies to the plain continuous decode path "
                "(not --beams)"
            )
        tenant_names = tuple(
            s.strip() for s in args.tenants.split(",") if s.strip()
        )
        if not tenant_names:
            raise SystemExit("--tenants is empty")
        weights: tuple[float, ...] = ()
        if args.tenant_weights:
            try:
                weights = tuple(
                    float(s) for s in args.tenant_weights.split(",")
                    if s.strip()
                )
            except ValueError as err:
                raise SystemExit(
                    f"--tenant-weights must be floats ({err})"
                )
        slos: tuple[float, ...] = ()
        if args.tenant_slos:
            try:
                slos = tuple(
                    float(s) for s in args.tenant_slos.split(",")
                    if s.strip()
                )
            except ValueError as err:
                raise SystemExit(f"--tenant-slos must be floats ({err})")
        if args.urgency_window < 0:
            raise SystemExit(
                f"--urgency-window {args.urgency_window} must be >= 0 "
                "(0 = off)"
            )
        if args.urgency_window > 0 and not any(s > 0 for s in slos):
            raise SystemExit(
                "--urgency-window needs at least one positive "
                "--tenant-slos entry (without a deadline nothing can "
                "jump the quantum)"
            )
        if not 0 <= args.shed_tiers <= 3:
            raise SystemExit(
                f"--shed-tiers {args.shed_tiers} must be in [0, 3] "
                "(0 = off)"
            )
        if args.admission_shards < 1:
            raise SystemExit(
                f"--admission-shards {args.admission_shards} must be "
                ">= 1 (1 = the single staging plane)"
            )
        if args.decode_slo_budget < 0:
            raise SystemExit(
                f"--decode-slo-budget {args.decode_slo_budget} must be "
                ">= 0 (0 = off)"
            )
        if args.prefix_pool < 0:
            raise SystemExit(
                f"--prefix-pool {args.prefix_pool} must be >= 0 (0 = off)"
            )
        if args.prefix_pool:
            if args.prefix_ids:
                raise SystemExit(
                    "--prefix-pool and --prefix-ids are mutually "
                    "exclusive (the pool generalizes the single "
                    "broadcast prefix)"
                )
            if args.prefix_pool < args.batch_size:
                raise SystemExit(
                    f"--prefix-pool {args.prefix_pool} must be >= "
                    f"--batch-size {args.batch_size} (one refill can "
                    "admit that many distinct prefixes per shard; a "
                    "smaller pool could LRU-evict an entry the same "
                    "admission batch still references)"
                )
        from .tenancy import TenancyConfig

        try:
            tenancy = TenancyConfig(
                tenants=tenant_names, weights=weights,
                prefix_pool=args.prefix_pool,
                prefix_len=args.seq_len if args.prefix_pool else 0,
                ttft_slo_s=slos,
                urgency_window_s=args.urgency_window,
                shed_tiers=args.shed_tiers,
                admission_shards=args.admission_shards,
                decode_slo_s=args.decode_slo_budget,
            )
        except ValueError as err:
            # weight/SLO/tenant count mismatches, non-positive weights,
            # bad urgency/shed knobs: usage errors at startup, never
            # mid-cycle tracebacks
            raise SystemExit(str(err))
    elif args.tenant_weights:
        raise SystemExit("--tenant-weights requires --tenants")
    elif args.tenant_slos:
        raise SystemExit("--tenant-slos requires --tenants")
    elif args.urgency_window:
        raise SystemExit("--urgency-window requires --tenants")
    elif args.shed_tiers:
        raise SystemExit("--shed-tiers requires --tenants")
    elif args.admission_shards != 1:
        raise SystemExit("--admission-shards requires --tenants")
    elif args.decode_slo_budget:
        raise SystemExit("--decode-slo-budget requires --tenants")
    elif args.prefix_pool:
        raise SystemExit("--prefix-pool requires --tenants")
    if args.journal_path and not args.fleet_max_replicas:
        raise SystemExit(
            "--journal-path records the fleet control loop "
            "(requires --fleet-max-replicas)"
        )
    if args.scheduler and not args.fleet_max_replicas:
        raise SystemExit(
            "--scheduler drives the fleet demo's loop + cycles "
            "(requires --fleet-max-replicas)"
        )
    knob_names: tuple = ()
    if args.knobs:
        # args-only checks fail BEFORE the mesh is built or a checkpoint
        # restored (same convention as the --decode-block checks above)
        from ..sched.knobs import KnobError, parse_knob_names

        try:
            knob_names = parse_knob_names(args.knobs)
        except KnobError as err:
            raise SystemExit(str(err))
        if not args.continuous:
            raise SystemExit("--knobs requires --continuous")
        if not args.scheduler:
            raise SystemExit(
                "--knobs actuates through the scheduler's between-cycle "
                "safe point (requires --scheduler)"
            )
        if "speculative" in knob_names:
            if args.beams > 1:
                raise SystemExit(
                    "the speculative knob does not combine with --beams "
                    "(beam search is deterministic; there is no "
                    "draft-and-verify round to toggle)"
                )
            if not args.speculative_draft_layers:
                raise SystemExit(
                    "the speculative knob requires "
                    "--speculative-draft-layers (there is no "
                    "draft-and-verify engine to toggle)"
                )
        if "decode_block" in knob_names and (
            (args.decode_block < 2 and args.shards < 2)
            or args.beams > 1
            or (args.speculative_draft_layers and not spec_on_plane)
        ):
            # the full _block_engine predicate, args-only: fails before
            # the mesh is built, like every other startup check here
            raise SystemExit(
                "the decode-block knob needs the block/gang decode "
                "engine: set --decode-block >= 2 or --shards >= 2 "
                "(plain continuous path only — not with --beams / "
                "--speculative-draft-layers)"
            )
        if "shards" in knob_names and args.shards < 2:
            raise SystemExit(
                "the shards knob needs the sharded plane (--shards >= 2)"
            )
        if "prefix_pool" in knob_names and not args.prefix_pool:
            raise SystemExit(
                "the prefix-pool knob requires --prefix-pool"
            )
    prefix_ids: list[int] = []
    if args.prefix_ids:
        try:
            prefix_ids = [
                int(s) for s in args.prefix_ids.split(",") if s.strip()
            ]
        except ValueError as err:
            raise SystemExit(f"--prefix-ids must be integers ({err})")
        if not prefix_ids:
            raise SystemExit("--prefix-ids is empty")
        # the prefix rides the padded cache (bf16 or int8, single-chip
        # or head-sharded over a (data, model) mesh) through every
        # decode mode — only the generate requirement remains
        if args.generate_tokens < 1:
            raise SystemExit(
                "--prefix-ids requires --generate-tokens >= 1"
            )
    if args.top_k < 0:
        raise SystemExit(f"--top-k {args.top_k} must be >= 0 (0 = off)")
    if not 0.0 < args.top_p <= 1.0:
        raise SystemExit(
            f"--top-p {args.top_p} must be in (0, 1] (1.0 = off)"
        )
    if args.fleet_max_replicas:
        # args-only checks fail BEFORE the mesh is built or a checkpoint
        # restored (same convention as the --beams checks above)
        if not args.continuous:
            raise SystemExit("--fleet-max-replicas requires --continuous")
        if args.beams > 1 or args.speculative_draft_layers:
            raise SystemExit(
                "--fleet-max-replicas applies to the plain continuous "
                "decode path (replica spin-up adopts the donor's "
                "compiled engine; not with --beams / "
                "--speculative-draft-layers)"
            )
        if not 1 <= args.fleet_min_replicas <= args.fleet_max_replicas:
            raise SystemExit(
                f"need 1 <= --fleet-min-replicas "
                f"({args.fleet_min_replicas}) <= --fleet-max-replicas "
                f"({args.fleet_max_replicas})"
            )
        if not args.demo:
            raise SystemExit(
                "--fleet-max-replicas currently requires --demo (the "
                "in-process fleet autoscales over the demo's in-memory "
                "queue; AWS-backed fleets are one process per replica, "
                "scaled by the kube-sqs-autoscaler binary itself)"
            )

    import jax

    from .model import ModelConfig, init_params
    from .service import QueueWorker, ServiceConfig

    if args.hf_checkpoint and args.checkpoint_dir:
        raise SystemExit(
            "--hf-checkpoint and --checkpoint-dir are mutually exclusive"
        )

    # --- model: architecture from the trainer's manifest, or built-in ----
    # (speculative decoding needs 2k cache positions of headroom past the
    # generated tokens — see speculative.speculative_generate's budget)
    spec_headroom = (
        2 * args.speculative_draft_tokens
        if args.speculative_draft_layers else 0
    )
    # the prefix pool prepends a seq_len-long cached prefix to every
    # pooled row, so its rows need a second seq_len of cache positions
    pool_prefix = args.seq_len if args.prefix_pool else 0
    needed_ctx = max(
        64,
        len(prefix_ids) + pool_prefix + args.seq_len
        + args.generate_tokens + spec_headroom,
    )
    hf_params = None
    if args.hf_checkpoint:
        from .hf_convert import load_hf_llama

        family = "llama"
        model_config, hf_params = load_hf_llama(args.hf_checkpoint)
        log.info(
            "Imported HF llama checkpoint %s (d_model=%d layers=%d "
            "heads=%d/%d, %s readout)",
            args.hf_checkpoint, model_config.d_model, model_config.n_layers,
            model_config.n_heads, model_config.n_kv_heads,
            "untied" if "lm_head" in hf_params else "tied",
        )
        needed = len(prefix_ids) + args.seq_len + args.generate_tokens
        if model_config.max_seq_len < needed:
            raise SystemExit(
                f"HF model has max_seq_len={model_config.max_seq_len} < "
                f"seq_len + generate_tokens = {needed}; lower "
                "--seq-len/--generate-tokens"
            )
    elif args.checkpoint_dir:
        from .checkpoint import load_model_layout, load_model_manifest

        family, model_config = load_model_manifest(args.checkpoint_dir)
        param_layout = load_model_layout(args.checkpoint_dir)
        if family != args.family:
            log.info("Checkpoint manifest says family=%s (overriding CLI)",
                     family)
        needed = len(prefix_ids) + args.seq_len + args.generate_tokens
        if model_config.max_seq_len < needed:
            raise SystemExit(
                f"checkpointed model has max_seq_len="
                f"{model_config.max_seq_len} < seq_len + generate_tokens = "
                f"{needed}; lower --seq-len/--generate-tokens"
            )
    elif args.family == "llama":
        from .llama import LlamaConfig

        family = "llama"
        model_config = LlamaConfig(
            vocab_size=8192, d_model=512, n_heads=8, n_kv_heads=2,
            n_layers=4, d_ff=1408, max_seq_len=needed_ctx,
        )
    else:
        family = "gpt"
        model_config = ModelConfig(
            vocab_size=8192, d_model=512, n_heads=8, n_layers=4, d_ff=2048,
            max_seq_len=needed_ctx,
        )

    # --- mesh + weights --------------------------------------------------
    from .train import make_mesh, param_shardings

    mesh = None
    if args.model_parallel:
        mesh = make_mesh(model_parallel=args.model_parallel)
        if args.batch_size % mesh.shape["data"]:
            raise SystemExit(
                f"--batch-size {args.batch_size} must be divisible by the "
                f"mesh's data axis ({mesh.shape['data']})"
            )
        log.info("Serving mesh: %s over %d devices", dict(mesh.shape),
                 mesh.size)

    if hf_params is not None:
        params = hf_params
        if mesh is not None:
            params = jax.device_put(params, param_shardings(mesh, params))
    elif args.checkpoint_dir:
        from .checkpoint import TrainCheckpointer

        restore_mesh = mesh or make_mesh(jax.devices()[:1], model_parallel=1)
        checkpointer = TrainCheckpointer(args.checkpoint_dir)
        params = checkpointer.restore_params(restore_mesh, family,
                                             model_config,
                                             layout=param_layout)
        log.info("Restored weights from %s step %s", args.checkpoint_dir,
                 checkpointer.latest_step())
    else:
        if family == "llama":
            from .llama import init_llama_params

            params = init_llama_params(jax.random.key(0), model_config)
        else:
            params = init_params(jax.random.key(0), model_config)
        if mesh is not None:
            params = jax.device_put(params, param_shardings(mesh, params))

    if args.quantize == "int8":
        # applies to restored checkpoints AND random-init smoke mode
        from .quantize import quantize_params, quantized_bytes

        before = quantized_bytes(params)
        params = quantize_params(params, family=family)
        if mesh is not None:
            # pin the int8 codes to the weight's Megatron layout and the
            # per-channel scales to its output-axis slice (the quantize
            # ops above ran under GSPMD's inferred placement)
            params = jax.device_put(params, param_shardings(mesh, params))
        log.info(
            "Quantized weights to int8: %.1f MiB -> %.1f MiB",
            before / 2**20, quantized_bytes(params) / 2**20,
        )

    service_config = ServiceConfig(
        queue_url=args.sqs_queue_url, batch_size=args.batch_size,
        seq_len=args.seq_len, generate_tokens=args.generate_tokens,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        result_queue_url=args.result_queue_url,
        eos_id=None if args.eos_id < 0 else args.eos_id,
        quantized_kv=args.quantize_kv,
        decode_block=args.decode_block,
        shards=args.shards,
        request_ttl_s=args.request_ttl,
    )
    tokenizer = None
    if args.tokenizer:
        try:
            from transformers import AutoTokenizer
        except ImportError as err:
            raise SystemExit(f"--tokenizer needs transformers ({err})")
        tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)
        tok_vocab = len(tokenizer)  # incl. added special tokens
        if tok_vocab > model_config.vocab_size:
            # JAX gathers clamp out-of-bounds ids on device, so an
            # oversized tokenizer would silently serve garbage
            raise SystemExit(
                f"tokenizer vocab ({tok_vocab}) exceeds the model's "
                f"vocab_size ({model_config.vocab_size})"
            )
        if service_config.eos_id is None and tokenizer.eos_token_id is not None:
            service_config.eos_id = int(tokenizer.eos_token_id)
            log.info("eos_id %d from the tokenizer", service_config.eos_id)
        log.info("Tokenizer: %s (vocab %d)", args.tokenizer, tok_vocab)

    # --- shared prompt prefix: prefilled ONCE, before the serving fns
    # (the sharded factories pin it into their compiled generate)
    prefix_cache = None
    if prefix_ids:
        import jax.numpy as jnp

        bad = [i for i in prefix_ids if not 0 <= i < model_config.vocab_size]
        if bad:
            # JAX gathers clamp out-of-bounds ids on device, so these
            # would silently prefill garbage
            raise SystemExit(
                f"--prefix-ids {bad} out of range for vocab_size="
                f"{model_config.vocab_size}"
            )
        prefix_arr = jnp.asarray(prefix_ids, jnp.int32)
        if family == "llama":
            from .llama import (
                llama_prefill_prefix,
                llama_quantized_prefill_prefix,
            )

            _pfx_prefill = (
                llama_quantized_prefill_prefix if args.quantize_kv
                else llama_prefill_prefix
            )
        else:
            from .decode import prefill_prefix, quantized_prefill_prefix

            _pfx_prefill = (
                quantized_prefill_prefix if args.quantize_kv
                else prefill_prefix
            )
        prefix_cache = _pfx_prefill(params, prefix_arr, model_config)
        log.info("Prefix cache: %d shared tokens prefilled once",
                 len(prefix_ids))

    # --- compute fns: sharded (mesh) or single-chip ----------------------
    worker_kwargs = {}
    if mesh is not None:
        from .train import make_forward_step

        if family == "llama":
            from .llama import llama_forward, make_llama_serving_fns

            fwd = make_forward_step(mesh, model_config, params,
                                    forward_fn=llama_forward)
            _, _, gen = make_llama_serving_fns(
                mesh, model_config, params,
                quantized_cache=args.quantize_kv,
                prefix_cache=prefix_cache,
            )
        else:
            from .decode import make_serving_fns

            fwd = make_forward_step(mesh, model_config, params)
            _, _, gen = make_serving_fns(
                mesh, model_config, params,
                quantized_cache=args.quantize_kv,
                prefix_cache=prefix_cache,
            )
        from .service import sampling_keys

        keys = sampling_keys(service_config.sample_seed)
        worker_kwargs = {
            "forward_fn": fwd,
            "generate_fn": lambda p, t, n, lengths: gen(
                p, t, next(keys), lengths, n, args.temperature,
                service_config.top_k, service_config.top_p,
                service_config.eos_id,
            ),
        }
    elif family == "llama":
        from .llama import (
            llama_attention_fn_for,
            llama_forward_jit_with,
            llama_generate_jit,
        )

        # attention picked per BATCH BUCKET length (the worker pads to
        # power-of-two buckets, and the flash/dense crossover is decided
        # by the actual padded length, not --seq-len) — same policy as
        # the gpt family's default forward in service.QueueWorker
        from .service import sampling_keys

        keys = sampling_keys(service_config.sample_seed)
        worker_kwargs = {
            "forward_fn": lambda p, t: llama_forward_jit_with(
                p, t, model_config,
                llama_attention_fn_for(model_config, t.shape[1]),
            ),
            "generate_fn": lambda p, t, n, lengths: llama_generate_jit(
                p, t, n, model_config,
                temperature=args.temperature,
                rng=(next(keys) if args.temperature > 0.0 else None),
                # llama_attention_fn_for carries config.sliding_window
                # into the prefill kernel (flash windowed block-skip or
                # windowed dense) — a bare attention_fn_for pick would
                # prefill a Mistral-style model full-causal
                prompt_attention=llama_attention_fn_for(
                    model_config, t.shape[1]
                ),
                lengths=lengths, top_k=service_config.top_k,
                top_p=service_config.top_p,
                eos_id=service_config.eos_id,
                quantized_cache=service_config.quantized_kv,
            ),
        }
    if prefix_cache is not None:
        # the plain SINGLE-CHIP prefix generate seam serves only when no
        # other decode mode claims generate_fn below (beam/speculative),
        # takes the cache directly (continuous), or already pinned the
        # prefix into its compiled generate (the mesh factories above)
        if (mesh is None and not args.continuous and args.beams == 1
                and not args.speculative_draft_layers):
            from .service import sampling_keys as _sampling_keys

            pfx_keys = _sampling_keys(service_config.sample_seed)
            if family == "llama":
                from .llama import llama_generate_jit as _pfx_gen
            else:
                from .decode import generate_jit as _pfx_gen
            worker_kwargs["generate_fn"] = (
                lambda p, t, n, lengths: _pfx_gen(
                    p, t, n, model_config,
                    temperature=args.temperature,
                    rng=(next(pfx_keys) if args.temperature > 0.0
                         else None),
                    lengths=lengths, top_k=service_config.top_k,
                    top_p=service_config.top_p,
                    eos_id=service_config.eos_id,
                    quantized_cache=service_config.quantized_kv,
                    prefix_cache=prefix_cache,
                )
            )
    if args.beams > 1:
        if args.continuous:
            # the slot machine hosts the per-slot beam search itself
            # (ContinuousWorker below gets the beams knob)
            pass
        elif mesh is not None:
            # beams over the (data, model) mesh: expanded rows shard over
            # data, weights/caches keep their Megatron shardings
            from .beam import make_beam_serving_fn

            beam_run = make_beam_serving_fn(
                mesh, model_config, params, beams=args.beams,
                length_penalty=args.length_penalty,
                eos_id=service_config.eos_id,
                prefix_cache=prefix_cache,
                quantized_cache=service_config.quantized_kv,
            )
            worker_kwargs["generate_fn"] = (
                lambda p, t, n, lengths: beam_run(p, t, lengths, n)
            )
        else:
            from .beam import beam_search_jit

            if family == "llama":
                from .llama import llama_attention_fn_for as _prefill_pick

                def _beam_prefill_attention(bucket_len):
                    return _prefill_pick(model_config, bucket_len)
            else:
                from .flash import attention_fn_for as _prefill_pick

                _beam_prefill_attention = _prefill_pick

            worker_kwargs["generate_fn"] = (
                # prefill picks the bucket-length flash/dense kernel like
                # the plain generate paths (memoized factories,
                # jit-static safe); with a prefix the prompts are
                # suffixes of the once-prefilled cache
                lambda p, t, n, lengths: beam_search_jit(
                    p, model_config, t, n, args.beams,
                    length_penalty=args.length_penalty,
                    eos_id=service_config.eos_id,
                    # under a prefix the suffix prefill runs the chunk
                    # decoder (no attention override — beam_search
                    # rejects the pair, same as decode.generate)
                    attention_fn=(None if prefix_cache is not None else
                                  _beam_prefill_attention(t.shape[1])),
                    lengths=lengths,
                    prefix_cache=prefix_cache,
                    quantized_cache=service_config.quantized_kv,
                )
            )
        log.info("Beam search: %d beams", args.beams)

    if args.speculative_draft_layers:
        # early-exit self-draft: the same weights, truncated depth.
        # Greedy runs are token-identical to plain greedy decode;
        # temperature > 0 runs full speculative sampling (the rejection
        # rule keeps every emitted token an exact warped-target sample).
        # --continuous re-hosts the draft-and-verify round inside the
        # rolling slot machine (per-slot accept counts on the batcher).
        if args.generate_tokens < 1:
            raise SystemExit(
                "--speculative-draft-layers requires "
                "--generate-tokens >= 1"
            )
        n_draft = args.speculative_draft_layers
        k = args.speculative_draft_tokens
        if k < 1:
            raise SystemExit(
                f"--speculative-draft-tokens {k} must be >= 1"
            )
        if not 0 < n_draft < model_config.n_layers:
            raise SystemExit(
                f"--speculative-draft-layers {n_draft} must be in "
                f"[1, n_layers-1] (model has n_layers="
                f"{model_config.n_layers})"
            )
        budget = (len(prefix_ids) + args.seq_len + args.generate_tokens
                  + 2 * k)
        if budget > model_config.max_seq_len:
            # fail at startup, not at first-batch trace time inside the
            # worker's never-dies retry loop
            raise SystemExit(
                f"prefix + seq_len + generate_tokens + 2*draft_tokens = "
                f"{budget} exceeds the model's max_seq_len="
                f"{model_config.max_seq_len} (the speculative cache "
                "budget); lower --speculative-draft-tokens or the lengths"
            )
        from dataclasses import replace

        from .service import sampling_keys

        draft_config = replace(model_config, n_layers=n_draft)
        spec_keys = sampling_keys(service_config.sample_seed)
        if args.continuous:
            # the slot machine hosts the round itself (ContinuousWorker
            # below gets the draft knobs); no generate_fn to wire
            pass
        elif mesh is not None:
            # speculative serving over the (data, model) mesh: both
            # models' weights/caches keep their Megatron shardings, rows
            # shard over data (acceptance and rollback are row-local)
            from .speculative import make_speculative_serving_fn

            spec_run = make_speculative_serving_fn(
                mesh, model_config, params, draft_config,
                draft_tokens=k, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p,
                eos_id=service_config.eos_id,
                prefix_cache=prefix_cache,
                quantized_cache=service_config.quantized_kv,
            )
            worker_kwargs["generate_fn"] = (
                lambda p, t, n, lengths: spec_run(
                    p, dict(p, layers=p["layers"][:n_draft]), t, lengths,
                    next(spec_keys), n,
                )
            )
        else:
            from .speculative import (
                draft_prefix_from_target,
                speculative_generate_jit,
            )

            spec_draft_pc = (
                draft_prefix_from_target(prefix_cache, n_draft)
                if prefix_cache is not None else None
            )
            worker_kwargs["generate_fn"] = (
                lambda p, t, n, lengths: speculative_generate_jit(
                    p, model_config,
                    dict(p, layers=p["layers"][:n_draft]), draft_config,
                    t, n, k, lengths=lengths,
                    temperature=args.temperature,
                    rng=(next(spec_keys) if args.temperature > 0.0
                         else None),
                    top_k=args.top_k, top_p=args.top_p,
                    eos_id=service_config.eos_id,
                    quantized_cache=service_config.quantized_kv,
                    prefix_cache=prefix_cache,
                    draft_prefix_cache=spec_draft_pc,
                )
            )
        log.info(
            "Speculative decoding: %d-layer early-exit self-draft, "
            "%d proposals/round", n_draft, k,
        )

    if args.continuous and args.generate_tokens < 1:
        # rolling-slot serving: both families, greedy or sampled, eos,
        # tokenizer, replies, single-chip or (data, model)-sharded
        raise SystemExit("--continuous requires --generate-tokens >= 1")

    if args.demo:
        import numpy as np

        from ..metrics.fake import FakeMessageQueue

        queue = FakeMessageQueue()
        rng = np.random.default_rng(0)
        for _ in range(args.demo):
            ids = rng.integers(0, model_config.vocab_size, args.seq_len).tolist()
            queue.send_message("demo://queue", json.dumps(ids))
        service_config.queue_url = "demo://queue"
        result_queue = None
        if args.result_queue_url:
            # demo replies land on a second in-memory queue
            result_queue = FakeMessageQueue()
        if args.fleet_max_replicas:
            # the closed loop in one process: a real ControlLoop
            # autoscales a WorkerPool of continuous replicas over the
            # demo queue (spin-up shares params + compiled engine;
            # scale-down drains gracefully; a dead replica's in-flight
            # work re-dispatches to survivors)
            from ..core.loop import ControlLoop, LoopConfig
            from ..core.policy import PolicyConfig
            from ..fleet import FleetDriver, WorkerPool
            from ..metrics.queue import QueueMetricSource

            pool = WorkerPool.serving(
                queue, params, model_config, service_config,
                family=family, tokenizer=tokenizer,
                result_queue=result_queue, mesh=mesh, tenancy=tenancy,
                min=args.fleet_min_replicas, max=args.fleet_max_replicas,
            )
            journal = None
            if args.journal_path:
                from ..obs import TickJournal

                journal = TickJournal(
                    args.journal_path,
                    meta=_fleet_journal_meta(args, tenancy, knob_names),
                )
                # sharded admission plane: gossip / kill / rehydrate
                # transitions ride the same journal as kind="admission"
                # lines (PR 13 machinery; lenient readers skip them)
                for replica in pool.members:
                    fair = getattr(replica.worker, "_fair", None)
                    if hasattr(fair, "attach_journal"):
                        fair.attach_journal(journal)
            metrics = None
            obs_server = None
            if args.metrics_port:
                from .. import __version__
                from ..obs import ObservabilityServer, WorkloadMetrics

                metrics = WorkloadMetrics()
                metrics.set_build_info(
                    __version__,
                    scheduler=int(bool(args.scheduler)),
                    knobs=",".join(knob_names) if knob_names else "none",
                )
                pool.attach_metrics(metrics)
                obs_server = ObservabilityServer(
                    metrics, port=args.metrics_port
                )
                obs_server.start()
            depth_policy = None
            if tenancy is not None:
                # the forecaster seam's WHO-is-arriving signal: the
                # gates threshold on the SLO-weighted per-tenant staged
                # backlog (a tight-SLO tenant's requests move the
                # autoscaler harder than a batch tenant's), never
                # below the raw observed depth
                from ..forecast.tenants import TenantAwareDepth

                depth_policy = TenantAwareDepth(
                    pool.staged_by_tenant, tenancy
                )
            class _LastDepthSource:
                """Remembers the tick's observation so the knob policy
                decides on the depth the loop just journaled instead of
                re-polling the queue once per tick (doubled metric API
                traffic against a real backend, and a knob decision on
                a different depth than the tick's)."""

                def __init__(self, source):
                    self.source = source
                    self.last = 0

                def num_messages(self):
                    self.last = self.source.num_messages()
                    return self.last

            metric_source = _LastDepthSource(QueueMetricSource(
                queue, service_config.queue_url,
                ("ApproximateNumberOfMessages",),
            ))
            loop = ControlLoop(
                pool,
                metric_source,
                LoopConfig(
                    poll_interval=0.1,
                    policy=PolicyConfig(
                        scale_up_messages=2 * args.batch_size,
                        scale_down_messages=args.batch_size,
                        scale_up_cooldown=0.2,
                        scale_down_cooldown=0.4,
                    ),
                ),
                observer=journal,
                depth_policy=depth_policy,
            )
            if args.scheduler:
                # the one-scheduler seam: same interleave as registered
                # events, plus — with --knobs — the actuator applying
                # staged knob changes between cycles at safe points
                from ..sched import (
                    KnobActuator,
                    KnobError,
                    ReactiveKnobPolicy,
                    ScheduledFleetDriver,
                )

                actuator = None
                knob_policy = None
                if knob_names:
                    try:
                        actuator = KnobActuator(
                            pool, armed=knob_names,
                            journal=journal, metrics=metrics,
                        )
                    except KnobError as err:
                        raise SystemExit(str(err))
                    if "decode_block" in knob_names:
                        # backlog-reactive block policy: deep queue ->
                        # big block (amortize host work), shallow ->
                        # small block (tight TTFT floor); decisions
                        # ride the control tick and read the depth that
                        # tick observed — no second queue poll
                        knob_policy = ReactiveKnobPolicy(
                            actuator, lambda: metric_source.last,
                            high=2 * args.batch_size,
                            low=max(1, args.batch_size // 2),
                            block_high=max(args.decode_block, 8),
                            block_low=2,
                        )
                driver = ScheduledFleetDriver(
                    pool, loop, knobs=actuator, knob_policy=knob_policy,
                )
            else:
                driver = FleetDriver(pool, loop)
            start = time.perf_counter()
            stats = driver.run(
                until=lambda: pool.processed >= args.demo and pool.idle,
            )
            elapsed = time.perf_counter() - start
            log.info(
                "Fleet processed %d messages in %.2fs (%.1f msg/s, "
                "%d ticks, replicas %s, redispatched %d, duplicate "
                "replies suppressed %d)",
                pool.processed, elapsed, pool.processed / elapsed,
                stats["ticks"], stats["replica_trajectory"] or [1],
                pool.redispatched_total, pool.duplicates_suppressed,
            )
            pool.stop_all()
            if journal is not None:
                journal.close()
            if obs_server is not None:
                obs_server.stop()
            if result_queue is not None:
                for message in result_queue.receive_messages(
                        args.result_queue_url, max_messages=2):
                    log.info("Reply: %.120s", message["Body"])
            return
        if args.continuous:
            from .continuous import ContinuousWorker

            cworker = ContinuousWorker(
                queue, params, model_config, service_config, family=family,
                tokenizer=tokenizer, result_queue=result_queue, mesh=mesh,
                prefix_cache=prefix_cache,
                draft_layers=args.speculative_draft_layers,
                draft_tokens=args.speculative_draft_tokens,
                beams=args.beams,
                length_penalty=args.length_penalty,
                tenancy=tenancy,
            )
            comms = _maybe_attach_topology(args, cworker)
            obs = _maybe_serve_metrics(args.metrics_port, cworker,
                                       tenancy=tenancy, comms=comms)
            start = time.perf_counter()
            cworker.drain(total=args.demo)
            elapsed = time.perf_counter() - start
            log.info(
                "Processed %d messages in %.2fs (%.1f msg/s, continuous)",
                cworker.processed, elapsed, cworker.processed / elapsed,
            )
            if result_queue is not None:
                for message in result_queue.receive_messages(
                        args.result_queue_url, max_messages=2):
                    log.info("Reply: %.120s", message["Body"])
            if obs is not None:
                obs.stop()
            return
        worker = QueueWorker(queue, params, model_config, service_config,
                             tokenizer=tokenizer, result_queue=result_queue,
                             **worker_kwargs)
        obs = _maybe_serve_metrics(args.metrics_port, worker)
        start = time.perf_counter()
        while worker.processed < args.demo:
            with worker.timer.span("cycle"):
                worker.run_once()
        elapsed = time.perf_counter() - start
        log.info(
            "Processed %d messages in %.2fs (%.1f msg/s)",
            worker.processed, elapsed, worker.processed / elapsed,
        )
        if result_queue is not None:
            sample = result_queue.receive_messages(
                args.result_queue_url, max_messages=2
            )
            for message in sample:
                log.info("Reply: %.120s", message["Body"])
        if obs is not None:
            obs.stop()
        return

    from ..metrics.sqs_aws import AwsSqsService

    queue = AwsSqsService(region=args.aws_region)
    if args.continuous:
        from .continuous import ContinuousWorker

        cworker = ContinuousWorker(
            queue, params, model_config, service_config, family=family,
            tokenizer=tokenizer, prefix_cache=prefix_cache,
            # AWS SQS addresses queues per call by url, so the same
            # client publishes replies when --result-queue-url is set
            result_queue=(queue if args.result_queue_url else None),
            mesh=mesh,
            draft_layers=args.speculative_draft_layers,
            draft_tokens=args.speculative_draft_tokens,
            beams=args.beams,
            length_penalty=args.length_penalty,
            tenancy=tenancy,
        )
        comms = _maybe_attach_topology(args, cworker)
        _maybe_serve_metrics(args.metrics_port, cworker, tenancy=tenancy,
                             comms=comms)
        log.info("Starting continuous worker on %s", args.sqs_queue_url)
        cworker.run_forever()
        return
    worker = QueueWorker(
        queue, params, model_config, service_config, tokenizer=tokenizer,
        # AWS SQS addresses queues per call by url, so the same client
        # publishes replies when --result-queue-url is set
        result_queue=(queue if args.result_queue_url else None),
        **worker_kwargs,
    )
    _maybe_serve_metrics(args.metrics_port, worker)
    log.info("Starting worker on %s", args.sqs_queue_url)
    worker.run_forever()


def _fleet_journal_meta(args, tenancy, knob_names=()) -> dict:
    """The serving-fleet journal's header meta: which deployment knobs
    (incl. the tenancy/admission policy and the live-knob arming)
    produced these tick lines — the serving twin of the controller
    CLI's ``_journal_meta``."""
    return {
        "source": "serving-fleet",
        "queue_url": "demo://queue",
        "world": {
            "min_pods": args.fleet_min_replicas,
            "max_pods": args.fleet_max_replicas,
        },
        "serving": {
            "batch_size": args.batch_size,
            "generate_tokens": args.generate_tokens,
            "decode_block": args.decode_block,
            "shards": args.shards,
        },
        # the scheduler seam + armed live knobs: a journal reader must
        # know whether `knob` event lines can appear in this episode
        # and which subsystem owned the interleave
        "sched": {
            "scheduler": bool(args.scheduler),
            "knobs": list(knob_names),
        },
        # tenancy knobs: a journal reader must know which admission
        # policy (DRR weights, prefix pool, stickiness) shaped the
        # depth trajectory it is looking at
        "tenancy": (
            {
                "tenants": list(tenancy.tenants),
                "weights": list(tenancy.weights),
                "prefix_pool": tenancy.prefix_pool,
                "prefix_len": tenancy.prefix_len,
                "sticky": tenancy.sticky,
                "fair": tenancy.fair,
                "ttft_slo_s": list(tenancy.ttft_slo_s),
                "urgency_window_s": tenancy.urgency_window_s,
                "urgency_budget": tenancy.urgency_budget,
                "shed_tiers": tenancy.shed_tiers,
                "admission_shards": tenancy.admission_shards,
                "decode_slo_s": tenancy.decode_slo_s,
            }
            if tenancy is not None
            else {}
        ),
    }


def _maybe_attach_topology(args, cworker):
    """Build the ``--topology`` route-planning CollectiveScheduler
    over the live ``--shards``/``--model-parallel`` geometry and wire
    it through the worker's engine (None when the flag is off — the
    WHEN-only byte-identical path)."""
    if not args.topology:
        return None
    from ..comms import CollectiveScheduler, topology_from_geometry

    topology = topology_from_geometry(
        args.topology,
        shards=args.shards,
        model_parallel=args.model_parallel or 1,
    )
    comms = CollectiveScheduler(
        lifecycle=getattr(cworker, "lifecycle", None),
        topology=topology,
    )
    cworker.batcher.attach_comms(comms)
    logging.getLogger("worker").info(
        "Topology-aware routing on: %s (%d nodes, %d links)",
        args.topology, len(topology.nodes), len(topology.links),
    )
    return comms


def _maybe_serve_metrics(port: int, worker, tenancy=None, comms=None):
    """Start /metrics with the worker's serve-cycle SpanTimer attached
    (``--metrics-port 0`` = disabled).  Continuous workers additionally
    publish the serving gauges (tokens/s, time-to-first-token, active
    slots, decode-block utilization), refreshed every engine cycle;
    tenancy-enabled workers the per-tenant families and a build_info
    stamp naming the tenancy deployment knobs.  A topology-attached
    comms scheduler enables /debug/topology and the per-link gauge
    families."""
    if not port:
        return None
    from .. import __version__
    from ..obs import ObservabilityServer, WorkloadMetrics

    metrics = WorkloadMetrics()
    metrics.attach_timer("worker", worker.timer)
    if tenancy is not None:
        metrics.set_build_info(
            __version__,
            tenants=",".join(tenancy.tenants),
            tenant_weights=",".join(str(w) for w in tenancy.weights),
            tenant_slos=",".join(str(s) for s in tenancy.ttft_slo_s),
            urgency_window=tenancy.urgency_window_s,
            shed_tiers=tenancy.shed_tiers,
            prefix_pool=tenancy.prefix_pool,
            admission_shards=tenancy.admission_shards,
            decode_slo_budget=tenancy.decode_slo_s,
        )
    if hasattr(worker, "attach_metrics"):
        worker.attach_metrics(metrics)
    server = ObservabilityServer(metrics, port=port, comms=comms)
    server.start()
    return server


if __name__ == "__main__":
    main()
