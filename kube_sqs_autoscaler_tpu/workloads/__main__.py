"""Worker binary: ``python -m kube_sqs_autoscaler_tpu.workloads``.

Runs one queue-draining inference worker — the process a scaled Deployment
replica executes.  ``--demo N`` self-feeds a local in-memory queue with N
random messages instead of connecting to AWS (no credentials needed), which
is also the quickest way to see the full workload path run.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time

from ..utils.logging import configure_logging


def _honor_env_platforms() -> None:
    """Make ``JAX_PLATFORMS`` authoritative even when a site hook already
    imported jax and overrode platform selection via ``jax.config``."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def main(argv=None) -> None:
    configure_logging()
    _honor_env_platforms()
    log = logging.getLogger("worker")
    parser = argparse.ArgumentParser(prog="kube-sqs-autoscaler-worker")
    parser.add_argument("--sqs-queue-url", default="", help="The sqs queue url")
    parser.add_argument("--aws-region", default="", help="Your AWS region")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument(
        "--generate-tokens", type=int, default=0, metavar="N",
        help="decode N continuation tokens per message (KV-cache generate "
             "mode) instead of one classify forward",
    )
    parser.add_argument(
        "--family", choices=("gpt", "llama"), default="gpt",
        help="model family served: gpt (learned pos/MHA) or llama "
             "(RoPE/GQA — n_kv_heads-sized KV cache)",
    )
    parser.add_argument(
        "--demo", type=int, default=0, metavar="N",
        help="process N random messages from a local in-memory queue and exit",
    )
    args = parser.parse_args(argv)

    import jax

    from .model import ModelConfig, init_params
    from .service import QueueWorker, ServiceConfig

    worker_kwargs = {}
    if args.family == "llama":
        from .llama import (
            LlamaConfig,
            init_llama_params,
            llama_attention_fn_for,
            llama_forward_jit_with,
            llama_generate_jit,
        )

        model_config = LlamaConfig(
            vocab_size=8192, d_model=512, n_heads=8, n_kv_heads=2,
            n_layers=4, d_ff=1408,
            max_seq_len=max(64, args.seq_len + args.generate_tokens),
        )
        params = init_llama_params(jax.random.key(0), model_config)
        # flash kernel on TPU when seq_len tiles onto the MXU blocks —
        # for both the classify forward and the generate-mode prefill
        from .flash import attention_fn_for

        attend = llama_attention_fn_for(model_config, args.seq_len)
        prompt_attention = attention_fn_for(args.seq_len)
        worker_kwargs = {
            "forward_fn": lambda p, t: llama_forward_jit_with(
                p, t, model_config, attend
            ),
            "generate_fn": lambda p, t, n: llama_generate_jit(
                p, t, n, model_config, prompt_attention=prompt_attention
            ),
        }
    else:
        model_config = ModelConfig(
            vocab_size=8192, d_model=512, n_heads=8, n_layers=4, d_ff=2048,
            max_seq_len=max(64, args.seq_len + args.generate_tokens),
        )
        params = init_params(jax.random.key(0), model_config)
    service_config = ServiceConfig(
        queue_url=args.sqs_queue_url, batch_size=args.batch_size,
        seq_len=args.seq_len, generate_tokens=args.generate_tokens,
    )

    if args.demo:
        import numpy as np

        from ..metrics.fake import FakeMessageQueue

        queue = FakeMessageQueue()
        rng = np.random.default_rng(0)
        for _ in range(args.demo):
            ids = rng.integers(0, model_config.vocab_size, args.seq_len).tolist()
            queue.send_message("demo://queue", json.dumps(ids))
        service_config.queue_url = "demo://queue"
        worker = QueueWorker(queue, params, model_config, service_config,
                             **worker_kwargs)
        start = time.perf_counter()
        while worker.processed < args.demo:
            worker.run_once()
        elapsed = time.perf_counter() - start
        log.info(
            "Processed %d messages in %.2fs (%.1f msg/s)",
            worker.processed, elapsed, worker.processed / elapsed,
        )
        return

    from ..metrics.sqs_aws import AwsSqsService

    queue = AwsSqsService(region=args.aws_region)
    worker = QueueWorker(queue, params, model_config, service_config,
                         **worker_kwargs)
    log.info("Starting worker on %s", args.sqs_queue_url)
    worker.run_forever()


if __name__ == "__main__":
    main()
