"""LoRA: low-rank adapter fine-tuning for both model families.

Fine-tuning a full model multiplies optimizer memory by 3 (params + two
Adam moments); LoRA trains rank-``r`` factors ``A [in, r]``, ``B [r,
out]`` per projection instead — the adapter set is ~``r * (in + out) /
(in * out)`` of the base weights (<1% at r=8 on the flagship config), so
the frozen base stays in bf16 HBM once and only the adapters carry
optimizer state (no reference counterpart: the reference has no model
code, SURVEY.md §2).

Design: adapters are a *parallel pytree* mirroring ``params["layers"]``,
and :func:`apply_lora` produces effective weights ``W + (alpha/r)·A@B``
*inside* the jitted step.  That keeps every existing forward, loss,
attention kernel, and sharding rule untouched — a LoRA step is the
ordinary step evaluated at ``apply_lora(frozen, adapters)``, with
gradients flowing only to the adapters (the frozen base is a closed-over
constant).  The per-step ``A@B`` materialization costs ``in·r·out``
FLOPs per weight — noise next to the ``tokens·in·out`` forward matmuls
it shadows.

Init is the standard LoRA scheme: ``A ~ N(0, 1/r)``, ``B = 0`` — the
adapted model starts exactly equal to the base, so step 0's loss matches
the frozen model bit for bit (tested).

TPU notes: adapters replicate across the mesh (rank-8 factors are tiny;
replicating avoids resharding the skinny matmuls), while the frozen base
keeps its PARAM_AXES sharding — ``W + AB`` broadcasts the replicated
product into the sharded weight layout and XLA partitions the add.
:func:`merge_lora` folds the adapters into plain weights for serving
(zero inference overhead; the merged pytree round-trips through the
existing checkpoint/quantize/serve paths unchanged).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

# per-family default adaptation targets: the attention projections (the
# LoRA paper's choice) plus the MLP matmuls — every 2-D weight the block
# multiplies by — and the MoE expert stacks (3-D ``[E, in, out]``, which
# get PER-EXPERT rank-r factors; the router stays frozen deliberately:
# adapting it changes the discrete dispatch, the standard MoE
# fine-tuning practice keeps routing fixed)
DEFAULT_TARGETS = (
    "wq", "wkv", "wqkv", "wo", "w_up", "w_down", "w_gate_up",
    "w_up_experts", "w_down_experts", "w_gate_up_experts",
)


@dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # weight names (within each layer dict) that receive adapters; names
    # absent from a family's layers are skipped, so one default covers
    # both families
    targets: tuple = field(default_factory=lambda: DEFAULT_TARGETS)

    def __post_init__(self) -> None:
        if self.rank < 1:
            raise ValueError(f"rank={self.rank} must be >= 1")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora_params(
    rng: jax.Array, params: dict, config: LoraConfig
) -> dict:
    """Adapters for every targeted weight in ``params["layers"]``.

    Returns ``{"layers": [{name: {"a": [in, r], "b": [r, out]}, ...},
    ...]}`` in fp32 (adapters are tiny; fp32 keeps the update math
    exact).  3-D expert stacks ``[E, in, out]`` get per-expert factors
    ``a [E, in, r]`` / ``b [E, r, out]`` (same leading-axis batching as
    the pipeline stage adapters).  ``B = 0`` start:
    ``apply_lora(params, adapters) == params``.
    """
    layers = []
    for i, layer in enumerate(params["layers"]):
        adapters = {}
        for t, name in enumerate(config.targets):
            w = layer.get(name)
            if w is None or w.ndim not in (2, 3):
                continue
            # fold in the stable (layer, target-index) pair — hash(name)
            # would be salted per process and break seed reproducibility
            key = jax.random.fold_in(jax.random.fold_in(rng, i), t)
            lead = w.shape[:-2]  # () for 2-D, (E,) for expert stacks
            adapters[name] = {
                "a": (
                    jax.random.normal(
                        key, (*lead, w.shape[-2], config.rank), jnp.float32
                    )
                    / config.rank
                ),
                "b": jnp.zeros((*lead, config.rank, w.shape[-1]),
                               jnp.float32),
            }
        if not adapters:
            raise ValueError(
                f"no targeted weights found in layer {i}: targets="
                f"{config.targets}, layer keys={sorted(layer)}"
            )
        layers.append(adapters)
    return {"layers": layers}


def apply_lora(params: dict, adapters: dict, config: LoraConfig) -> dict:
    """Effective parameters ``W + (alpha/r)·A@B`` for adapted weights
    (everything else passes through by reference).  Pure — call inside
    the jitted step so the delta participates in autodiff; gradients
    w.r.t. ``adapters`` flow through the add, the base stays constant.
    """
    merged_layers = []
    for layer, adapter in zip(params["layers"], adapters["layers"]):
        merged = dict(layer)
        for name, ab in adapter.items():
            w = layer[name]
            # matmul over the trailing two axes; any leading axis (the
            # expert stack's E) batches through
            delta = (
                jnp.einsum("...ir,...ro->...io", ab["a"], ab["b"])
                * config.scale
            )
            merged[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
        merged_layers.append(merged)
    return dict(params, layers=merged_layers)


def merge_lora(params: dict, adapters: dict, config: LoraConfig) -> dict:
    """Fold adapters into plain weights (serving form, zero overhead).
    Same math as :func:`apply_lora`; a separate name so call sites say
    what they mean."""
    return apply_lora(params, adapters, config)


def lora_param_count(adapters: dict) -> int:
    return sum(p.size for p in jax.tree.leaves(adapters))


def _jit_adapter_step(
    mesh, optimizer, compute_grads, adapter_state, batch_sharding
):
    """The one adapter-only optimizer step: shared by the flat and
    pipelined LoRA step builders (they differ only in the loss closure
    inside ``compute_grads`` and the batch sharding).  Adapters and
    their Adam moments replicate across the mesh; their gradients arrive
    via XLA's all-reduce of the data-parallel shards."""
    import optax

    from .train import replicated

    def train_step(state, tokens):
        loss_value, grads = compute_grads(state["adapters"], tokens)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["adapters"]
        )
        adapters = optax.apply_updates(state["adapters"], updates)
        return (
            {
                "adapters": adapters,
                "opt_state": opt_state,
                "step": state["step"] + 1,
            },
            loss_value,
        )

    rep = replicated(mesh)
    state_shard = jax.tree.map(lambda _: rep, adapter_state,
                               is_leaf=lambda x: x is None)
    return jax.jit(
        train_step,
        in_shardings=(state_shard, batch_sharding),
        out_shardings=(state_shard, rep),
        donate_argnums=0,
    )


def make_lora_train_step(
    mesh,
    model_config: Any,
    train_config: Any,
    frozen_params: dict,
    adapter_state: dict,
    lora: LoraConfig,
    loss: Any = None,
):
    """Compile one adapter-only optimizer step over the mesh.

    ``adapter_state`` comes from :func:`init_lora_train_state`; the
    frozen base is closed over (already placed on the mesh with its
    usual shardings) and never donated or updated.  ``loss(params,
    tokens, attention_fn)`` defaults to the family objective via
    ``train.loss_fn`` — pass ``llama.llama_loss_fn``-shaped callables for
    other families (same seam as ``train.make_train_step``).
    """
    from .train import (
        accumulate_value_and_grad,
        batch_sharding,
        make_optimizer,
        mesh_attention_fn,
    )

    optimizer = make_optimizer(train_config)
    # sliding-window configs fine-tune windowed, like every other step
    # builder (a bare mesh_attention_fn(mesh) would silently train a
    # Mistral-style base full-causal)
    attention_fn = mesh_attention_fn(
        mesh, window=getattr(model_config, "sliding_window", None)
    )
    if loss is None:
        from .train import loss_fn

        loss = partial(loss_fn, config=model_config,
                       remat=train_config.remat)

    def adapter_loss(adapters, tokens):
        return loss(
            apply_lora(frozen_params, adapters, lora), tokens,
            attention_fn=attention_fn,
        )

    # grad_accum composes here like everywhere else: the shared fp32
    # chunked scan, accumulating only the (tiny) adapter gradients
    compute_grads = accumulate_value_and_grad(
        jax.value_and_grad(adapter_loss), train_config.grad_accum
    )
    return _jit_adapter_step(
        mesh, optimizer, compute_grads, adapter_state, batch_sharding(mesh)
    )


def init_lora_train_state(
    rng: jax.Array, params: dict, lora: LoraConfig, train_config: Any
) -> dict:
    """Adapters + their optimizer state (the trainable state is ONLY the
    adapters — the base model carries no moments)."""
    from .train import make_optimizer

    adapters = init_lora_params(rng, params, lora)
    opt_state = make_optimizer(train_config).init(adapters)
    return {
        "adapters": adapters,
        "opt_state": opt_state,
        "step": jnp.zeros((), jnp.int32),
    }


def _pipeline_targets(targets: tuple) -> tuple:
    """Translate flat-layout target names to the stage-stacked layout's:
    the pipeline splits fused projections (``stack_layers`` /
    ``stack_llama_layers``), so a flat target like ``wqkv`` means the
    split ``wq``/``wk``/``wv`` there.  Adapting the splits individually
    is the LoRA paper's own per-projection scheme — rank ``r`` per
    projection rather than one rank-``r`` factor across the fused axis."""
    split = {
        "wqkv": ("wq", "wk", "wv"),
        "wkv": ("wk", "wv"),
        "w_gate_up": ("w_gate", "w_up"),
        # the stacked MoE layout splits the fused SwiGLU expert
        # projection the same way (pipeline.stack_llama_layers)
        "w_gate_up_experts": ("w_gate_experts", "w_up_experts"),
    }
    out: list = []
    for name in targets:
        for t in split.get(name, (name,)):
            if t not in out:
                out.append(t)
    return tuple(out)


def init_pipeline_lora_params(
    rng: jax.Array, params: dict, config: LoraConfig
) -> dict:
    """Adapters for the stage-stacked pipeline layout
    (:func:`.pipeline.as_pipeline_params` /
    :func:`.pipeline.as_llama_pipeline_params`).

    Stacked layer weights carry a leading layer axis ``[L, in, out]``,
    so each target gets ONE adapter pair ``a [L, in, r]``, ``b [L, r,
    out]`` covering every layer — the per-layer factors ride the same
    leading axis as the weights they adapt (and shard over ``"pipe"``
    with them if placed; the trainer replicates them — they are tiny).
    MoE expert stacks add an expert axis (``[L, E, in, out]``) and get
    PER-EXPERT factors ``a [L, E, in, r]``, ``b [L, E, r, out]`` — the
    stage-stacked form of the flat path's per-expert adapters (the
    router stays frozen, same as flat).  Same init scheme as
    :func:`init_lora_params`: ``A ~ N(0, 1/r)``, ``B = 0`` so the
    adapted model starts exactly at the base.
    """
    stages = params["stages"]
    adapters = {}
    for t, name in enumerate(_pipeline_targets(config.targets)):
        w = stages.get(name)
        if w is None or w.ndim not in (3, 4):
            continue
        key = jax.random.fold_in(rng, t)
        adapters[name] = {
            "a": (
                jax.random.normal(
                    key, (*w.shape[:-1], config.rank), jnp.float32
                )
                / config.rank
            ),
            "b": jnp.zeros((*w.shape[:-2], config.rank, w.shape[-1]),
                           jnp.float32),
        }
    if not adapters:
        raise ValueError(
            f"no targeted stage weights found: targets={config.targets}, "
            f"stage keys={sorted(stages)}"
        )
    return {"stages": adapters}


def apply_pipeline_lora(
    params: dict, adapters: dict, config: LoraConfig
) -> dict:
    """Effective stage stacks ``W + (alpha/r)·A@B`` (leading layer axis
    batched through the einsum; non-adapted leaves pass through by
    reference).  Pure — call inside the jitted step, before the stacks
    enter the pipeline's ``shard_map``: the add happens in auto-sharded
    land, so XLA slices the (replicated) delta into each stage's
    ``"pipe"`` shard without collectives."""
    stages = dict(params["stages"])
    for name, ab in adapters["stages"].items():
        w = stages[name]
        eq = "leir,lero->leio" if w.ndim == 4 else "lir,lro->lio"
        delta = jnp.einsum(eq, ab["a"], ab["b"]) * config.scale
        stages[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    return dict(params, stages=stages)


def init_pipeline_lora_train_state(
    rng: jax.Array, params: dict, lora: LoraConfig, train_config: Any
) -> dict:
    """:func:`init_lora_train_state` for the stage-stacked layout."""
    from .train import make_optimizer

    adapters = init_pipeline_lora_params(rng, params, lora)
    opt_state = make_optimizer(train_config).init(adapters)
    return {
        "adapters": adapters,
        "opt_state": opt_state,
        "step": jnp.zeros((), jnp.int32),
    }


def lora_pipeline_value_and_grad(
    mesh,
    model_config: Any,
    pcfg: Any,
    frozen_params: dict,
    lora: LoraConfig,
    llama: bool = False,
    remat: bool = False,
    moe: Any = None,
):
    """``(adapters, tokens) -> (loss, adapter_grads)`` through the
    pipeline, either schedule.

    GPipe: plain autodiff of the pipelined loss evaluated at
    :func:`apply_pipeline_lora` (the frozen stacks are a closed-over
    constant).  1F1B: the hand-built backward computes effective-WEIGHT
    gradients; the adapter gradients follow by the chain rule of
    ``W_eff = W + s·A@B`` — ``dA = s · dW @ Bᵀ``, ``dB = s · Aᵀ @ dW``
    (batched over the leading layer — and, for expert stacks, expert —
    axes) — so the 1F1B memory schedule and the LoRA optimizer-state
    savings compose.  ``moe`` swaps in the routed pipeline objective
    (aux term included; the frozen router's gradients are discarded
    like every other non-adapted leaf, expert adapters train through
    the dispatch/combine).  Exported for the schedule-equality test."""
    from .pipeline import (
        llama_one_f_one_b_value_and_grad,
        llama_pipeline_loss_fn,
        moe_one_f_one_b_value_and_grad,
        moe_pipeline_loss_fn,
        one_f_one_b_value_and_grad,
        pipeline_loss_fn,
    )

    if pcfg.schedule == "1f1b":
        if moe is not None:
            vag_full = partial(
                moe_one_f_one_b_value_and_grad,
                config=model_config, moe=moe, pcfg=pcfg, mesh=mesh,
                llama=llama,
            )
        else:
            vag_full = partial(
                llama_one_f_one_b_value_and_grad if llama
                else one_f_one_b_value_and_grad,
                config=model_config, pcfg=pcfg, mesh=mesh, remat=remat,
            )

        def adapter_vag(adapters, tokens):
            eff = apply_pipeline_lora(frozen_params, adapters, lora)
            loss, full_grads = vag_full(eff, tokens)
            dstages = full_grads["stages"]
            dadapters = {"stages": {}}
            for name, ab in adapters["stages"].items():
                dw = dstages[name].astype(jnp.float32)
                if dw.ndim == 4:  # expert stacks: [L, E, in, out]
                    eq_a, eq_b = "leio,lero->leir", "leir,leio->lero"
                else:
                    eq_a, eq_b = "lio,lro->lir", "lir,lio->lro"
                dadapters["stages"][name] = {
                    "a": jnp.einsum(eq_a, dw, ab["b"]) * lora.scale,
                    "b": jnp.einsum(eq_b, ab["a"], dw) * lora.scale,
                }
            # the frozen base's other gradients (embed/head/router/
            # non-adapted stage leaves) are discarded — nothing updates
            # them
            return loss, dadapters

        return adapter_vag

    if moe is not None:
        def adapter_loss(adapters, tokens):
            return moe_pipeline_loss_fn(
                apply_pipeline_lora(frozen_params, adapters, lora),
                tokens, config=model_config, moe=moe, pcfg=pcfg,
                mesh=mesh, llama=llama,
            )

        return jax.value_and_grad(adapter_loss)

    loss_fn = llama_pipeline_loss_fn if llama else pipeline_loss_fn

    def adapter_loss(adapters, tokens):
        return loss_fn(
            apply_pipeline_lora(frozen_params, adapters, lora), tokens,
            config=model_config, pcfg=pcfg, mesh=mesh, remat=remat,
        )

    return jax.value_and_grad(adapter_loss)


def make_lora_pipeline_train_step(
    mesh,
    model_config: Any,
    pcfg: Any,
    train_config: Any,
    frozen_params: dict,
    adapter_state: dict,
    lora: LoraConfig,
    llama: bool = False,
    moe: Any = None,
):
    """Compile one adapter-only optimizer step over a pipeline mesh,
    either schedule (:func:`lora_pipeline_value_and_grad`).  The frozen
    stage stacks stay a closed-over constant (their usual
    ``"pipe"``-sharded layout, never donated); gradient accumulation
    composes via the shared fp32 chunked scan over the batch axis
    (``accum_axis=1`` — axis 0 is the pipeline's own microbatch
    schedule).  ``moe``: adapter-only fine-tuning of a frozen routed
    base through the MoE pipeline objective (no remat — the flat MoE
    constraint).
    """
    from .pipeline import pipeline_batch_sharding
    from .train import accumulate_value_and_grad, make_optimizer

    if moe is not None:
        from .moe import _require_no_remat

        _require_no_remat(train_config)
    optimizer = make_optimizer(train_config)
    compute_grads = accumulate_value_and_grad(
        lora_pipeline_value_and_grad(
            mesh, model_config, pcfg, frozen_params, lora, llama=llama,
            remat=getattr(train_config, "remat", False), moe=moe,
        ),
        train_config.grad_accum,
        accum_axis=1,
    )
    return _jit_adapter_step(
        mesh, optimizer, compute_grads, adapter_state,
        pipeline_batch_sharding(mesh),
    )


def lora_pipeline_checkpoint_state(
    frozen_params: dict, state: dict, lora: LoraConfig, llama: bool = False
) -> dict:
    """:func:`lora_checkpoint_state` for a pipelined LoRA run: the
    merged weights are UNSTACKED to the flat serving layout before
    storage, so the on-disk ``params`` read like any flat checkpoint
    (serve binary, ``restore_params``, hf-export — same contract as the
    flat LoRA checkpoint), while the ``lora`` subtree keeps the
    stage-stacked adapter train state resume needs."""
    from .pipeline import unstack_layers, unstack_llama_layers

    merged = apply_pipeline_lora(frozen_params, state["adapters"], lora)
    unstack = unstack_llama_layers if llama else unstack_layers
    return {
        "params": unstack(merged),
        "step": state["step"],
        "lora": {
            "adapters": state["adapters"],
            "opt_state": state["opt_state"],
        },
    }


def lora_checkpoint_state(
    frozen_params: dict, state: dict, lora: LoraConfig
) -> dict:
    """The on-disk form of a LoRA run: MERGED weights under ``params``
    (so the serving worker's partial ``params`` restore and
    ``restore_params`` work on LoRA checkpoints unchanged) plus the
    adapter train state under ``lora`` — what resume actually needs.
    The frozen base itself is NOT stored: it is reproducible from the
    run's own seed or HF checkpoint, and merged = base + delta would
    store it redundantly anyway."""
    return {
        "params": merge_lora(frozen_params, state["adapters"], lora),
        "step": state["step"],
        "lora": {
            "adapters": state["adapters"],
            "opt_state": state["opt_state"],
        },
    }


