"""Chrome/Perfetto trace-event export of tick records.

Turns a journal (or the live :class:`~.journal.TickRing`) into the JSON
trace-event format that ``chrome://tracing`` / https://ui.perfetto.dev
load directly: one complete ("X") span per tick with child spans for the
tick's three phases (observe → decide → actuate, from the record's span
fields), plus instant ("i") events at the moments an operator actually
hunts for in a postmortem — gate fires (with actuation failures marked),
cooldown skips, and metric failures.

Timestamps are microseconds from the first record's start (the loop's
own clock — virtual under a ``FakeClock``), so traces from simulation
and production render identically.  Served live at ``/debug/trace`` by
:class:`~.server.ObservabilityServer`.
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Sequence

from ..core.events import TickRecord
from ..core.policy import Gate

_PID = 1
_TID = 1

_SPAN_FIELDS = (
    ("observe", "observe_s"),
    ("decide", "decide_s"),
    ("actuate", "actuate_s"),
)


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def trace_events(
    records: Sequence[TickRecord] | Iterable[TickRecord],
    time_origin: float | None = None,
) -> list[dict[str, Any]]:
    """The records as a flat trace-event list (oldest record first).

    ``time_origin`` defaults to the first record's start, so traces begin
    at t=0 regardless of the recording clock's epoch.
    """
    records = list(records)
    if not records:
        return []
    origin = records[0].start if time_origin is None else time_origin
    events: list[dict[str, Any]] = []
    for index, record in enumerate(records):
        start = record.start - origin
        end = start + record.duration
        events.append(
            {
                "name": "tick",
                "cat": "tick",
                "ph": "X",
                "ts": _us(start),
                "dur": _us(record.duration),
                "pid": _PID,
                "tid": _TID,
                "args": {
                    "tick": index,
                    "num_messages": record.num_messages,
                    "decision_messages": record.decision_messages,
                    "up": record.up.value,
                    "down": record.down.value,
                },
            }
        )
        cursor = start
        for name, field in _SPAN_FIELDS:
            span = getattr(record, field)
            if span is None:
                continue
            events.append(
                {
                    "name": name,
                    "cat": "phase",
                    "ph": "X",
                    "ts": _us(cursor),
                    "dur": _us(span),
                    "pid": _PID,
                    "tid": _TID,
                }
            )
            cursor += span
        if record.metric_error is not None:
            events.append(
                _instant("metric-failure", end, {"error": record.metric_error})
            )
        for direction, gate, error in (
            ("up", record.up, record.up_error),
            ("down", record.down, record.down_error),
        ):
            if gate is Gate.COOLING:
                events.append(
                    _instant("cooldown-skip", end, {"direction": direction})
                )
            elif gate is Gate.FIRE:
                args: dict[str, Any] = {
                    "direction": direction,
                    "ok": error is None,
                }
                if error is not None:
                    args["error"] = error
                events.append(_instant(f"scale-{direction}", end, args))
    return events


def _instant(name: str, at: float, args: dict[str, Any],
             cat: str = "event") -> dict[str, Any]:
    return {
        "name": name,
        "cat": cat,
        "ph": "i",
        "s": "t",  # thread-scoped instant
        "ts": _us(at),
        "pid": _PID,
        "tid": _TID,
        "args": args,
    }


def instant_trace_events(
    events: Iterable[Any], time_origin: float | None = None
) -> list[dict[str, Any]]:
    """Generic instant events from ``(name, t, args)``-shaped values.

    ``events`` is any iterable of objects with ``name``/``t``/``args``
    attributes — the fleet's :class:`~..fleet.FleetEvent` supervisor
    decisions (replica spawn / kill / drain) are the motivating
    producer.  Timestamps share the same clock as the tick records they
    are merged with (``to_chrome_trace(..., extra_events=...)``), so
    scaling decisions land on the same timeline as the ticks that caused
    them; ``time_origin`` defaults to the first event's time.

    Shard-domain events (``shard-*``: activate/drain as well as the
    chaos loop's quarantine/probe/readmit instants) get their own
    ``"shard"`` category so Perfetto can filter the shard failure
    domain separately from replica lifecycle events; prefix-pool
    residency decisions (``prefix-*``: the per-tenant pool's
    install/evict instants) likewise land under ``"prefix"``, the
    overload ladder's tier transitions (``overload-*``) under
    ``"overload"``, and the disaggregated planes' KV-handoff batches
    (``kv-*`` / ``plane-*``) under ``"plane"``.
    """
    events = list(events)
    if not events:
        return []
    origin = events[0].t if time_origin is None else time_origin

    def _cat(name: str) -> str:
        if name.startswith("shard-"):
            return "shard"
        if name.startswith("prefix-"):
            return "prefix"
        if name.startswith("overload-"):
            return "overload"
        if name.startswith("restart-"):
            # the durable store's controller-restart / rehydration
            # instants (core/durable.py) — their own lane so a
            # postmortem can line recovery up against the ticks
            return "restart"
        if name.startswith("knob-"):
            # live engine-knob changes (sched/knobs.py KnobActuator) —
            # their own lane so an operator can line a tokens/s or
            # TTFT inflection up against the knob flip that caused it
            return "knob"
        if name.startswith("kv-") or name.startswith("plane-"):
            # the disaggregated planes (planes/pool.py): KV handoff
            # batches and plane-level lifecycle instants — their own
            # lane so the prefill->decode shuttle reads separately from
            # replica churn
            return "plane"
        return "fleet"

    return [
        _instant(e.name, e.t - origin, dict(e.args), cat=_cat(e.name))
        for e in events
    ]


def to_chrome_trace(
    records: Sequence[TickRecord] | Iterable[TickRecord],
    meta: dict[str, Any] | None = None,
    extra_events: Sequence[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """The JSON-object trace format (``{"traceEvents": [...]}``).

    ``extra_events`` are pre-built trace-event dicts appended verbatim
    (e.g. the fleet's :func:`instant_trace_events` with ``time_origin``
    set to the first tick's start, so both streams share t=0)."""
    trace: dict[str, Any] = {
        "traceEvents": trace_events(records) + list(extra_events or ()),
        "displayTimeUnit": "ms",
    }
    if meta:
        trace["otherData"] = meta
    return trace


def render_chrome_trace(
    records: Sequence[TickRecord] | Iterable[TickRecord],
    meta: dict[str, Any] | None = None,
    extra_events: Sequence[dict[str, Any]] | None = None,
) -> str:
    """``to_chrome_trace`` as a compact JSON string (the HTTP body)."""
    return json.dumps(
        to_chrome_trace(records, meta, extra_events), separators=(",", ":")
    )
