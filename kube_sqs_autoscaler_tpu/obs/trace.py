"""Chrome/Perfetto trace-event export of tick records.

Turns a journal (or the live :class:`~.journal.TickRing`) into the JSON
trace-event format that ``chrome://tracing`` / https://ui.perfetto.dev
load directly: one complete ("X") span per tick with child spans for the
tick's three phases (observe → decide → actuate, from the record's span
fields), plus instant ("i") events at the moments an operator actually
hunts for in a postmortem — gate fires (with actuation failures marked),
cooldown skips, and metric failures.

Timestamps are microseconds from the first record's start (the loop's
own clock — virtual under a ``FakeClock``), so traces from simulation
and production render identically.  Served live at ``/debug/trace`` by
:class:`~.server.ObservabilityServer`.
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Sequence

from ..core.events import TickRecord
from ..core.policy import Gate

_PID = 1
_TID = 1

#: Category -> (pid, tid, process label, thread label).  Perfetto
#: renders one lane per (pid, tid), so giving each event category a
#: STATIC track assignment turns the previously interleaved single row
#: into separate lanes: controller ticks, fleet/replica lifecycle,
#: shard failure domain, restart/rehydration, knob actuations, the
#: overload ladder, prefix-pool residency, the disaggregated planes'
#: KV shuttle, and per-request lifecycle spans.  Keyed by category —
#: never by discovery order — so the same event lands on the same lane
#: across controller restarts and journal-rotation rejoins (pinned by
#: tests).
_TRACKS: dict[str, tuple[int, int, str, str]] = {
    "tick": (_PID, _TID, "controller", "ticks"),
    "phase": (_PID, _TID, "controller", "ticks"),
    "event": (_PID, _TID, "controller", "ticks"),
    "fleet": (2, 1, "fleet", "replicas"),
    "shard": (2, 2, "fleet", "shards"),
    "restart": (2, 3, "fleet", "restart"),
    "knob": (2, 4, "fleet", "knobs"),
    "overload": (3, 1, "admission", "overload"),
    "prefix": (3, 2, "admission", "prefix-pool"),
    "plane": (3, 3, "admission", "kv-shuttle"),
    "request": (4, 1, "requests", "queue"),
}

#: The request process's per-phase lanes (pid 4): each lifecycle span
#: renders on the lane of the phase that owns it, threaded together by
#: flow arrows carrying the trace's flow id.
_REQUEST_PID = 4
_REQUEST_LANES: dict[str, tuple[int, str]] = {
    "queue": (1, "queue"),
    "prefill": (2, "prefill"),
    "handoff": (3, "kv-handoff"),
    "decode": (4, "decode"),
    "settle": (5, "settle"),
    # scheduled-collective transfer windows (comms/): rendered at their
    # ABSOLUTE stamp times, not cursor-chained — an overlapped transfer
    # sits visibly parallel to the decode span hiding it
    "transfer": (6, "transfers"),
}

_SPAN_FIELDS = (
    ("observe", "observe_s"),
    ("decide", "decide_s"),
    ("actuate", "actuate_s"),
)


def track_for(cat: str) -> tuple[int, int]:
    """The stable (pid, tid) lane of an event category."""
    pid, tid, _, _ = _TRACKS.get(cat, _TRACKS["fleet"])
    return pid, tid


def track_metadata_events() -> list[dict[str, Any]]:
    """Perfetto ``"M"`` metadata naming every track in :data:`_TRACKS`
    (process_name / thread_name), plus the request process's phase
    lanes.  Appended by :func:`to_chrome_trace` only when the trace has
    real events — an empty trace stays empty."""
    events: list[dict[str, Any]] = []
    seen_pid: set[int] = set()
    seen_tid: set[tuple[int, int]] = set()

    def _add(pid: int, tid: int, process: str, thread: str) -> None:
        if pid not in seen_pid:
            seen_pid.add(pid)
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": process},
            })
        if (pid, tid) not in seen_tid:
            seen_tid.add((pid, tid))
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": thread},
            })

    for pid, tid, process, thread in _TRACKS.values():
        _add(pid, tid, process, thread)
    for tid, thread in _REQUEST_LANES.values():
        _add(_REQUEST_PID, tid, "requests", thread)
    return events


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def trace_events(
    records: Sequence[TickRecord] | Iterable[TickRecord],
    time_origin: float | None = None,
) -> list[dict[str, Any]]:
    """The records as a flat trace-event list (oldest record first).

    ``time_origin`` defaults to the first record's start, so traces begin
    at t=0 regardless of the recording clock's epoch.
    """
    records = list(records)
    if not records:
        return []
    origin = records[0].start if time_origin is None else time_origin
    events: list[dict[str, Any]] = []
    for index, record in enumerate(records):
        start = record.start - origin
        end = start + record.duration
        events.append(
            {
                "name": "tick",
                "cat": "tick",
                "ph": "X",
                "ts": _us(start),
                "dur": _us(record.duration),
                "pid": _PID,
                "tid": _TID,
                "args": {
                    "tick": index,
                    "num_messages": record.num_messages,
                    "decision_messages": record.decision_messages,
                    "up": record.up.value,
                    "down": record.down.value,
                },
            }
        )
        cursor = start
        for name, field in _SPAN_FIELDS:
            span = getattr(record, field)
            if span is None:
                continue
            events.append(
                {
                    "name": name,
                    "cat": "phase",
                    "ph": "X",
                    "ts": _us(cursor),
                    "dur": _us(span),
                    "pid": _PID,
                    "tid": _TID,
                }
            )
            cursor += span
        if record.metric_error is not None:
            events.append(
                _instant("metric-failure", end, {"error": record.metric_error})
            )
        for direction, gate, error in (
            ("up", record.up, record.up_error),
            ("down", record.down, record.down_error),
        ):
            if gate is Gate.COOLING:
                events.append(
                    _instant("cooldown-skip", end, {"direction": direction})
                )
            elif gate is Gate.FIRE:
                args: dict[str, Any] = {
                    "direction": direction,
                    "ok": error is None,
                }
                if error is not None:
                    args["error"] = error
                events.append(_instant(f"scale-{direction}", end, args))
    return events


def _instant(name: str, at: float, args: dict[str, Any],
             cat: str = "event") -> dict[str, Any]:
    pid, tid = track_for(cat)
    return {
        "name": name,
        "cat": cat,
        "ph": "i",
        "s": "t",  # thread-scoped instant
        "ts": _us(at),
        "pid": pid,
        "tid": tid,
        "args": args,
    }


def instant_trace_events(
    events: Iterable[Any], time_origin: float | None = None
) -> list[dict[str, Any]]:
    """Generic instant events from ``(name, t, args)``-shaped values.

    ``events`` is any iterable of objects with ``name``/``t``/``args``
    attributes — the fleet's :class:`~..fleet.FleetEvent` supervisor
    decisions (replica spawn / kill / drain) are the motivating
    producer.  Timestamps share the same clock as the tick records they
    are merged with (``to_chrome_trace(..., extra_events=...)``), so
    scaling decisions land on the same timeline as the ticks that caused
    them; ``time_origin`` defaults to the first event's time.

    Shard-domain events (``shard-*``: activate/drain as well as the
    chaos loop's quarantine/probe/readmit instants) get their own
    ``"shard"`` category so Perfetto can filter the shard failure
    domain separately from replica lifecycle events; prefix-pool
    residency decisions (``prefix-*``: the per-tenant pool's
    install/evict instants) likewise land under ``"prefix"``, the
    overload ladder's tier transitions (``overload-*``) under
    ``"overload"``, and the disaggregated planes' KV-handoff batches
    (``kv-*`` / ``plane-*``) under ``"plane"``.
    """
    events = list(events)
    if not events:
        return []
    origin = events[0].t if time_origin is None else time_origin

    def _cat(name: str) -> str:
        if name.startswith("shard-"):
            return "shard"
        if name.startswith("prefix-"):
            return "prefix"
        if name.startswith("overload-"):
            return "overload"
        if name.startswith("restart-"):
            # the durable store's controller-restart / rehydration
            # instants (core/durable.py) — their own lane so a
            # postmortem can line recovery up against the ticks
            return "restart"
        if name.startswith("knob-"):
            # live engine-knob changes (sched/knobs.py KnobActuator) —
            # their own lane so an operator can line a tokens/s or
            # TTFT inflection up against the knob flip that caused it
            return "knob"
        if name.startswith("admission-"):
            # the sharded admission plane (workloads/admission_shards
            # .py): shard kill / rehydrate instants — their own lane so
            # staging-plane churn reads separately from engine-shard
            # chaos
            return "admission"
        if name.startswith("kv-") or name.startswith("plane-"):
            # the disaggregated planes (planes/pool.py): KV handoff
            # batches and plane-level lifecycle instants — their own
            # lane so the prefill->decode shuttle reads separately from
            # replica churn
            return "plane"
        return "fleet"

    return [
        _instant(e.name, e.t - origin, dict(e.args), cat=_cat(e.name))
        for e in events
    ]


def request_trace_events(
    traces: Iterable[Any], time_origin: float | None = None
) -> list[dict[str, Any]]:
    """Per-request lifecycle spans threaded by Perfetto flow arrows.

    ``traces`` is any iterable of :class:`~.lifecycle.RequestTrace`
    values (anything with ``rid`` / ``flow_id`` / ``tenant`` and the
    ``first``/``last`` stamp accessors).  Each request renders as one
    span per lifecycle phase — queue wait, prefill, KV-handoff stall,
    decode, settle — on the ``requests`` process's per-phase lanes,
    linked start-to-finish by flow events (``s``/``t``/``f``) carrying
    the trace's flow id, so Perfetto draws the arrow a postmortem
    follows: THIS request waited here, prefilled there, stalled on the
    shuttle, decoded on the plane.  ``time_origin`` defaults to the
    first trace's arrival so request spans share t=0 with whatever tick
    records they are merged with.
    """
    from .lifecycle import (  # local: avoid import cycle
        phase_durations,
        transfer_spans,
    )

    traces = list(traces)
    starts = [
        t.first("arrival") for t in traces
        if t.first("arrival") is not None
    ]
    if time_origin is None:
        if not starts:
            return []
        time_origin = min(starts)
    events: list[dict[str, Any]] = []
    for trace in traces:
        arrival = trace.first("arrival")
        if arrival is None:
            continue
        durations = phase_durations(trace)
        cursor = arrival - time_origin
        spans: list[tuple[str, float, float]] = []
        for phase in ("queue", "prefill", "handoff", "decode", "settle"):
            span = durations.get(phase)
            if span is None:
                continue
            spans.append((phase, cursor, span))
            cursor += span
        if not spans:
            continue
        args = {
            "rid": trace.rid,
            "tenant": trace.tenant,
            "notes": dict(trace.notes),
        }
        if getattr(trace, "error", None) is not None:
            args["error"] = trace.error
        for index, (phase, start, span) in enumerate(spans):
            tid, _ = _REQUEST_LANES[phase]
            events.append({
                "name": phase,
                "cat": "request",
                "ph": "X",
                "ts": _us(start),
                "dur": _us(span),
                "pid": _REQUEST_PID,
                "tid": tid,
                "args": args,
            })
            # the flow arrow: start at the first span, step through the
            # middle ones, finish (binding to the enclosing slice) at
            # the last — one arrow per request, id = its flow id, which
            # the registry keeps unique across restart epochs
            if index == 0:
                ph = "s"
            elif index == len(spans) - 1:
                ph = "f"
            else:
                ph = "t"
            flow: dict[str, Any] = {
                "name": "request",
                "cat": "request",
                "ph": ph,
                "id": trace.flow_id,
                "ts": _us(start),
                "pid": _REQUEST_PID,
                "tid": tid,
            }
            if ph == "f":
                flow["bp"] = "e"
            events.append(flow)
        # scheduled-collective windows (comms/): absolute-time spans on
        # the transfers lane.  The chained spans above start at the
        # trace's absolute stamp times too, so a transfer dispatched
        # while a block decodes renders exactly under the decode span
        # it hides behind — the overlap the bench gate looks for.
        transfer_tid, _ = _REQUEST_LANES["transfer"]
        routes = getattr(trace, "routes", None) or []
        for index, (t0, t1) in enumerate(transfer_spans(trace)):
            transfer_args = args
            if index < len(routes):
                # the comms route planner appended hop lists in stamp
                # order — the i-th route belongs to the i-th span
                transfer_args = dict(args)
                transfer_args["route"] = routes[index]
            events.append({
                "name": "transfer",
                "cat": "request",
                "ph": "X",
                "ts": _us(t0 - time_origin),
                "dur": _us(max(0.0, t1 - t0)),
                "pid": _REQUEST_PID,
                "tid": transfer_tid,
                "args": transfer_args,
            })
    return events


def to_chrome_trace(
    records: Sequence[TickRecord] | Iterable[TickRecord],
    meta: dict[str, Any] | None = None,
    extra_events: Sequence[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """The JSON-object trace format (``{"traceEvents": [...]}``).

    ``extra_events`` are pre-built trace-event dicts appended verbatim
    (e.g. the fleet's :func:`instant_trace_events` with ``time_origin``
    set to the first tick's start, so both streams share t=0)."""
    events = trace_events(records) + list(extra_events or ())
    if events:
        # name the tracks (process/thread lanes) — but an empty trace
        # stays byte-empty, so consumers can cheaply test for "nothing
        # recorded yet"
        events = track_metadata_events() + events
    trace: dict[str, Any] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
    }
    if meta:
        trace["otherData"] = meta
    return trace


def render_chrome_trace(
    records: Sequence[TickRecord] | Iterable[TickRecord],
    meta: dict[str, Any] | None = None,
    extra_events: Sequence[dict[str, Any]] | None = None,
) -> str:
    """``to_chrome_trace`` as a compact JSON string (the HTTP body)."""
    return json.dumps(
        to_chrome_trace(records, meta, extra_events), separators=(",", ":")
    )
