"""Observability: Prometheus metrics, health endpoints, flight recorder.

Extension over the reference, which has *no* metrics endpoint, no
Prometheus, no health/readiness probes (SURVEY.md §5).  Opt-in via
``--metrics-port`` (default 0 = disabled ⇒ reference behavior exactly).

The flight recorder (:mod:`.journal`) adds the *historical* counterpart
of the live gauges: a bounded in-memory ring and an append-only JSONL
journal of every tick record, exportable as Chrome trace-event JSON
(:mod:`.trace`) and replayable through :mod:`..sim.replay`.
"""

from .journal import (
    JOURNAL_SCHEMA_VERSION,
    JournalSchemaError,
    TickJournal,
    TickRing,
    read_journal,
    read_journal_episodes,
)
from .prometheus import ControllerMetrics, WorkloadMetrics
from .server import ObservabilityServer
from .trace import render_chrome_trace, to_chrome_trace, trace_events

__all__ = [
    "ControllerMetrics",
    "JOURNAL_SCHEMA_VERSION",
    "JournalSchemaError",
    "ObservabilityServer",
    "TickJournal",
    "TickRing",
    "WorkloadMetrics",
    "read_journal",
    "read_journal_episodes",
    "render_chrome_trace",
    "to_chrome_trace",
    "trace_events",
]
