"""Observability: Prometheus metrics, health endpoints, flight recorder.

Extension over the reference, which has *no* metrics endpoint, no
Prometheus, no health/readiness probes (SURVEY.md §5).  Opt-in via
``--metrics-port`` (default 0 = disabled ⇒ reference behavior exactly).

The flight recorder (:mod:`.journal`) adds the *historical* counterpart
of the live gauges: a bounded in-memory ring and an append-only JSONL
journal of every tick record, exportable as Chrome trace-event JSON
(:mod:`.trace`) and replayable through :mod:`..sim.replay`.

Request-lifecycle tracing (:mod:`.lifecycle`) adds the per-REQUEST
counterpart of the per-tick recorder: bounded phase-stamped traces of
every request across planes, shards, and restarts, decomposable into
per-phase latency histograms and Perfetto flow spans.
"""

from .journal import (
    JOURNAL_SCHEMA_VERSION,
    JournalSchemaError,
    TickJournal,
    TickRing,
    read_journal,
    read_journal_episodes,
)
from .lifecycle import (
    LifecycleRegistry,
    RequestTrace,
    phase_durations,
    request_key,
    transfer_spans,
    validate_chain,
)
from .prometheus import ControllerMetrics, WorkloadMetrics
from .server import ObservabilityServer
from .trace import (
    render_chrome_trace,
    request_trace_events,
    to_chrome_trace,
    trace_events,
)

__all__ = [
    "ControllerMetrics",
    "JOURNAL_SCHEMA_VERSION",
    "JournalSchemaError",
    "LifecycleRegistry",
    "ObservabilityServer",
    "RequestTrace",
    "TickJournal",
    "TickRing",
    "WorkloadMetrics",
    "phase_durations",
    "read_journal",
    "read_journal_episodes",
    "render_chrome_trace",
    "request_key",
    "request_trace_events",
    "to_chrome_trace",
    "trace_events",
    "transfer_spans",
    "validate_chain",
]
