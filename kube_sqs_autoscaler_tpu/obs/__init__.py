"""Observability: Prometheus metrics + health/readiness endpoints.

Extension over the reference, which has *no* metrics endpoint, no
Prometheus, no health/readiness probes (SURVEY.md §5).  Opt-in via
``--metrics-port`` (default 0 = disabled ⇒ reference behavior exactly).
"""

from .prometheus import ControllerMetrics, WorkloadMetrics
from .server import ObservabilityServer

__all__ = ["ControllerMetrics", "ObservabilityServer", "WorkloadMetrics"]
